// Native $set/$unset/$delete property aggregation for predictionio_tpu.
//
// The reference folds special events into per-entity property maps with
// an HBase scan + per-row fold inside `aggregateProperties`
// («data/.../storage/LEvents :: aggregateProperties» — SURVEY.md §2.2
// [U], mount empty). The TPU rebuild's Python fold
// (data/datamap.py::aggregate_properties) materializes one Event +
// DataMap per row — the exact per-event cost the columnar ratings scan
// (pio_scan.cpp) eliminated. This TU gives the property-read path the
// same treatment: stream the filtered rows once via the sqlite3 C API in
// (event_time, creation_time) order, fold $set/$unset/$delete in C++
// with raw JSON value spans (no JSON value parse at all — values are
// spliced back verbatim, so the Python side parses one object per
// ENTITY, not one per event), and hand back a packed blob of
//   entity_id \0 first_updated \0 last_updated \0 folded_json \0
// per surviving entity.
//
// Fold semantics (must match data/datamap.py::aggregate_properties):
//   - rows arrive ordered by (event_time, creation_time, id) ascending
//     (unique id as final tiebreak — exact-timestamp ties must resolve
//     the same way in every tier);
//   - $set creates/updates keys (later sets win per key); creation
//     stamps first_updated, every $set stamps last_updated;
//   - $unset drops the named keys IF the entity exists, and stamps
//     last_updated even when the keys are absent or the bag is empty;
//   - $delete removes the entity entirely; a later $set recreates it
//     with a fresh first_updated.
//
// Keys are fully JSON-decoded (\uXXXX incl. surrogate pairs) so a
// $unset spelled with escapes matches a $set spelled raw, exactly as
// Python's json.loads-ed dict keys do; output keys are re-encoded pure
// ASCII (\uXXXX) so even lone-surrogate keys survive the round trip.
// Any surprise — malformed JSON, non-object properties, bad escape —
// aborts the whole scan (rc != 0) and the wrapper falls back to the
// bit-identical per-event Python fold.
//
// Same two-phase C ABI and dlopen'd sqlite3 pattern as pio_scan.cpp.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <dlfcn.h>

namespace {

// -- minimal sqlite3 C API surface (stable ABI, declared locally; each
// native TU carries its own copy — no cross-TU coupling) ----------------
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
constexpr int kSqliteOk = 0;
constexpr int kSqliteRow = 100;
constexpr int kSqliteDone = 101;
constexpr int kOpenReadonly = 0x00000001;

struct SqliteApi {
    int (*open_v2)(const char*, sqlite3**, int, const char*);
    int (*close_v2)(sqlite3*);
    int (*prepare_v2)(sqlite3*, const char*, int, sqlite3_stmt**,
                      const char**);
    int (*step)(sqlite3_stmt*);
    int (*finalize)(sqlite3_stmt*);
    int (*bind_text)(sqlite3_stmt*, int, const char*, int, void*);
    const unsigned char* (*column_text)(sqlite3_stmt*, int);
    int (*column_bytes)(sqlite3_stmt*, int);
    const char* (*errmsg)(sqlite3*);
    bool ok = false;
};

const SqliteApi& sqlite_api() {
    static SqliteApi api = [] {
        SqliteApi a;
        void* h = dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_GLOBAL);
        if (!h) h = dlopen("libsqlite3.so", RTLD_NOW | RTLD_GLOBAL);
        if (!h) return a;
        auto sym = [&](const char* name) { return dlsym(h, name); };
        a.open_v2 = reinterpret_cast<decltype(a.open_v2)>(
            sym("sqlite3_open_v2"));
        a.close_v2 = reinterpret_cast<decltype(a.close_v2)>(
            sym("sqlite3_close_v2"));
        a.prepare_v2 = reinterpret_cast<decltype(a.prepare_v2)>(
            sym("sqlite3_prepare_v2"));
        a.step = reinterpret_cast<decltype(a.step)>(sym("sqlite3_step"));
        a.finalize = reinterpret_cast<decltype(a.finalize)>(
            sym("sqlite3_finalize"));
        a.bind_text = reinterpret_cast<decltype(a.bind_text)>(
            sym("sqlite3_bind_text"));
        a.column_text = reinterpret_cast<decltype(a.column_text)>(
            sym("sqlite3_column_text"));
        a.column_bytes = reinterpret_cast<decltype(a.column_bytes)>(
            sym("sqlite3_column_bytes"));
        a.errmsg = reinterpret_cast<decltype(a.errmsg)>(
            sym("sqlite3_errmsg"));
        a.ok = a.open_v2 && a.close_v2 && a.prepare_v2 && a.step &&
               a.finalize && a.bind_text && a.column_text &&
               a.column_bytes && a.errmsg;
        return a;
    }();
    return api;
}

thread_local std::string g_error;

// -- JSON string decoding (full, json.loads-equivalent) -----------------
// Decodes a JSON string starting at *p == '"'. \uXXXX escapes combine
// surrogate pairs into astral codepoints; a LONE surrogate is encoded
// WTF-8 style (json.loads accepts lone surrogates into Python strs, and
// key identity must match that). Returns false on any malformed input.
inline void append_utf8(uint32_t cp, std::string* out) {
    if (cp < 0x80) {
        out->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
        out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
        out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
        out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
        out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
        out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
        out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
        out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
        out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
        out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
}

inline bool parse_hex4(const char* s, uint32_t* out) {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
        char c = s[i];
        v <<= 4;
        if (c >= '0' && c <= '9') v |= c - '0';
        else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
        else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
        else return false;
    }
    *out = v;
    return true;
}

bool decode_json_string(const char*& p, const char* end, std::string* out) {
    if (p >= end || *p != '"') return false;
    ++p;
    while (p < end) {
        unsigned char c = static_cast<unsigned char>(*p);
        if (c == '"') {
            ++p;
            return true;
        }
        if (c == '\\') {
            if (p + 1 >= end) return false;
            char e = p[1];
            p += 2;
            switch (e) {
                case '"': if (out) out->push_back('"'); break;
                case '\\': if (out) out->push_back('\\'); break;
                case '/': if (out) out->push_back('/'); break;
                case 'b': if (out) out->push_back('\b'); break;
                case 'f': if (out) out->push_back('\f'); break;
                case 'n': if (out) out->push_back('\n'); break;
                case 'r': if (out) out->push_back('\r'); break;
                case 't': if (out) out->push_back('\t'); break;
                case 'u': {
                    if (p + 4 > end) return false;
                    uint32_t cp;
                    if (!parse_hex4(p, &cp)) return false;
                    p += 4;
                    if (cp >= 0xD800 && cp < 0xDC00 && p + 6 <= end &&
                        p[0] == '\\' && p[1] == 'u') {
                        uint32_t lo;
                        if (!parse_hex4(p + 2, &lo)) return false;
                        if (lo >= 0xDC00 && lo < 0xE000) {
                            cp = 0x10000 + ((cp - 0xD800) << 10) +
                                 (lo - 0xDC00);
                            p += 6;
                        }
                        // else: lone high surrogate, keep as-is (WTF-8)
                    }
                    if (out) append_utf8(cp, out);
                    break;
                }
                default: return false;
            }
            continue;
        }
        if (out) out->push_back(static_cast<char>(c));
        ++p;
    }
    return false;  // unterminated
}

// Re-encode a decoded (WTF-8) key as a pure-ASCII JSON string so the
// assembled object is loadable by json.loads regardless of what the key
// contained (incl. lone surrogates, which raw WTF-8 bytes would break).
bool encode_json_string_ascii(const std::string& k, std::string* out) {
    static const char* hex = "0123456789abcdef";
    out->push_back('"');
    const unsigned char* p = reinterpret_cast<const unsigned char*>(k.data());
    const unsigned char* end = p + k.size();
    while (p < end) {
        unsigned char c = *p;
        uint32_t cp;
        int len;
        if (c < 0x80) { cp = c; len = 1; }
        else if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; len = 2; }
        else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; len = 3; }
        else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; len = 4; }
        else return false;
        if (p + len > end) return false;
        for (int i = 1; i < len; ++i) {
            if ((p[i] & 0xC0) != 0x80) return false;
            cp = (cp << 6) | (p[i] & 0x3F);
        }
        p += len;
        if (cp == '"') { out->append("\\\""); }
        else if (cp == '\\') { out->append("\\\\"); }
        else if (cp >= 0x20 && cp < 0x7F) {
            out->push_back(static_cast<char>(cp));
        } else if (cp < 0x10000) {
            out->append("\\u");
            out->push_back(hex[(cp >> 12) & 0xF]);
            out->push_back(hex[(cp >> 8) & 0xF]);
            out->push_back(hex[(cp >> 4) & 0xF]);
            out->push_back(hex[cp & 0xF]);
        } else {
            uint32_t v = cp - 0x10000;
            uint32_t hi = 0xD800 + (v >> 10), lo = 0xDC00 + (v & 0x3FF);
            for (uint32_t s : {hi, lo}) {
                out->append("\\u");
                out->push_back(hex[(s >> 12) & 0xF]);
                out->push_back(hex[(s >> 8) & 0xF]);
                out->push_back(hex[(s >> 4) & 0xF]);
                out->push_back(hex[s & 0xF]);
            }
        }
    }
    out->push_back('"');
    return true;
}

// -- JSON object splitter -----------------------------------------------
// Splits a top-level JSON object into (decoded key, raw value span)
// pairs. Values are NOT parsed beyond bracket/string balancing — the
// raw span is spliced verbatim into the folded output. Duplicate keys:
// later wins (matches json.loads). Returns false on anything that is
// not a well-formed object.
struct Splitter {
    const char* p;
    const char* end;

    void skip_ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                           *p == '\r'))
            ++p;
    }

    bool skip_value() {
        skip_ws();
        if (p >= end) return false;
        if (*p == '"') return decode_json_string(p, end, nullptr);
        if (*p == '{' || *p == '[') {
            int depth = 0;
            while (p < end) {
                if (*p == '"') {
                    if (!decode_json_string(p, end, nullptr)) return false;
                    continue;
                }
                if (*p == '{' || *p == '[') ++depth;
                else if (*p == '}' || *p == ']') {
                    --depth;
                    if (depth < 0) return false;
                    if (depth == 0) { ++p; return true; }
                }
                ++p;
            }
            return false;
        }
        // number / true / false / null: advance to a delimiter
        const char* start = p;
        while (p < end && *p != ',' && *p != '}' && *p != ']' &&
               *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r')
            ++p;
        return p > start;
    }

    bool split(std::vector<std::pair<std::string, std::string>>* out) {
        skip_ws();
        if (p >= end || *p != '{') return false;
        ++p;
        skip_ws();
        if (p < end && *p == '}') { ++p; return true; }
        while (p < end) {
            skip_ws();
            std::string key;
            if (!decode_json_string(p, end, &key)) return false;
            skip_ws();
            if (p >= end || *p != ':') return false;
            ++p;
            skip_ws();
            const char* vstart = p;
            if (!skip_value()) return false;
            out->emplace_back(std::move(key), std::string(vstart, p - vstart));
            skip_ws();
            if (p < end && *p == ',') { ++p; continue; }
            if (p < end && *p == '}') { ++p; return true; }
            return false;
        }
        return false;
    }
};

// -- fold state ---------------------------------------------------------
// Keys are interned once into dense uint32 ids (property keys repeat
// massively — a 2M-event stream typically has <100 distinct keys), so
// per-entity state is a flat vector of (key id, raw value span) probed
// linearly instead of a per-entity hash map — no bucket allocations,
// cache-friendly for the usual <20 keys per entity.
struct AggEntity {
    std::vector<std::pair<uint32_t, std::string>> kv;
    std::string first, last;  // raw event_time text (Python parses once)
};

struct AggResult {
    std::string blob;       // eid\0 first\0 last\0 json\0 per entity
    int64_t n_entities = 0;
};

}  // namespace

extern "C" {

const char* pio_agg_error() { return g_error.c_str(); }

// Runs the whole fold. `sql` must select
//   0 entity_id TEXT, 1 event TEXT, 2 properties TEXT, 3 event_time TEXT
// ordered by (event_time, creation_time, id) ascending — the fold is
// order-sensitive and trusts the statement's ORDER BY. Returns 0 with a
// handle + sizes, or -1 (pio_agg_error() has the reason; the caller
// falls back to the per-event Python fold).
int64_t pio_agg_open(const char* db_path, const char* sql,
                     const char** params, int64_t n_params,
                     const char** required, int64_t n_required,
                     void** out_handle, int64_t* out_n,
                     int64_t* out_bytes) {
    const SqliteApi& api = sqlite_api();
    if (!api.ok) {
        g_error = "libsqlite3 not loadable";
        return -1;
    }
    sqlite3* db = nullptr;
    if (api.open_v2(db_path, &db, kOpenReadonly, nullptr) != kSqliteOk) {
        g_error = db ? api.errmsg(db) : "open failed";
        if (db) api.close_v2(db);
        return -1;
    }
    sqlite3_stmt* stmt = nullptr;
    if (api.prepare_v2(db, sql, -1, &stmt, nullptr) != kSqliteOk) {
        g_error = api.errmsg(db);
        api.close_v2(db);
        return -1;
    }
    for (int64_t i = 0; i < n_params; ++i) {
        if (api.bind_text(stmt, static_cast<int>(i + 1), params[i], -1,
                          reinterpret_cast<void*>(-1)) != kSqliteOk) {
            g_error = api.errmsg(db);
            api.finalize(stmt);
            api.close_v2(db);
            return -1;
        }
    }

    std::unordered_map<std::string, AggEntity> state;
    std::unordered_map<std::string, uint32_t> key_ids;
    std::vector<std::string> key_names;
    std::vector<std::pair<std::string, std::string>> kvs;
    std::string eid_buf;
    auto intern_key = [&](std::string&& k) -> uint32_t {
        auto it = key_ids.find(k);
        if (it != key_ids.end()) return it->second;
        uint32_t id = static_cast<uint32_t>(key_names.size());
        key_names.push_back(k);
        key_ids.emplace(std::move(k), id);
        return id;
    };
    int rc;
    bool failed = false;
    while ((rc = api.step(stmt)) == kSqliteRow) {
        const char* eid =
            reinterpret_cast<const char*>(api.column_text(stmt, 0));
        int eid_n = api.column_bytes(stmt, 0);
        const char* ev =
            reinterpret_cast<const char*>(api.column_text(stmt, 1));
        const char* props =
            reinterpret_cast<const char*>(api.column_text(stmt, 2));
        int props_n = api.column_bytes(stmt, 2);
        const char* t =
            reinterpret_cast<const char*>(api.column_text(stmt, 3));
        if (!eid || !ev || !t) {
            g_error = "NULL entity_id/event/event_time";
            failed = true;
            break;
        }
        eid_buf.assign(eid, eid_n);  // reused buffer: no per-row malloc
        if (std::strcmp(ev, "$delete") == 0) {
            state.erase(eid_buf);
            continue;
        }
        const bool is_set = std::strcmp(ev, "$set") == 0;
        const bool is_unset = !is_set && std::strcmp(ev, "$unset") == 0;
        if (!is_set && !is_unset) {
            g_error = std::string("unexpected event '") + ev +
                      "' (WHERE must filter to special events)";
            failed = true;
            break;
        }
        kvs.clear();
        Splitter sp{props ? props : "", (props ? props : "") + props_n};
        if (!sp.split(&kvs)) {
            g_error = "unparseable properties JSON — Python fallback";
            failed = true;
            break;
        }
        if (is_set) {
            auto it = state.find(eid_buf);
            if (it == state.end()) {
                it = state.emplace(eid_buf, AggEntity{}).first;
                it->second.first.assign(t);
            }
            auto& entkv = it->second.kv;
            for (auto& kv : kvs) {
                uint32_t id = intern_key(std::move(kv.first));
                bool found = false;
                for (auto& e : entkv) {
                    if (e.first == id) {
                        e.second = std::move(kv.second);
                        found = true;
                        break;
                    }
                }
                if (!found) entkv.emplace_back(id, std::move(kv.second));
            }
            it->second.last.assign(t);
        } else {  // $unset: only touches entities that exist
            auto it = state.find(eid_buf);
            if (it != state.end()) {
                auto& entkv = it->second.kv;
                for (auto& kv : kvs) {
                    auto kit = key_ids.find(kv.first);
                    if (kit == key_ids.end()) continue;  // never $set
                    for (size_t i = 0; i < entkv.size(); ++i) {
                        if (entkv[i].first == kit->second) {
                            entkv[i] = std::move(entkv.back());
                            entkv.pop_back();
                            break;
                        }
                    }
                }
                it->second.last.assign(t);
            }
        }
    }
    api.finalize(stmt);
    if (!failed && rc != kSqliteDone) {
        g_error = api.errmsg(db);
        failed = true;
    }
    api.close_v2(db);
    if (failed) return -1;

    // -- required filter + deterministic assembly -----------------------
    // required keys → interned ids; a required key never seen in any
    // $set cannot be on any entity, so the result is empty
    std::vector<uint32_t> req_ids;
    bool req_impossible = false;
    for (int64_t i = 0; i < n_required; ++i) {
        auto it = key_ids.find(required[i]);
        if (it == key_ids.end()) {
            req_impossible = true;
            break;
        }
        req_ids.push_back(it->second);
    }
    std::vector<const std::pair<const std::string, AggEntity>*> items;
    if (!req_impossible) {
        items.reserve(state.size());
        for (auto& kv : state) {
            bool ok = true;
            for (uint32_t rid : req_ids) {
                bool has = false;
                for (auto& e : kv.second.kv) {
                    if (e.first == rid) { has = true; break; }
                }
                if (!has) { ok = false; break; }
            }
            if (ok) items.push_back(&kv);
        }
    }
    std::sort(items.begin(), items.end(),
              [](auto* a, auto* b) { return a->first < b->first; });

    // pre-encode each interned key's ASCII-escaped JSON form once
    std::vector<std::string> key_json(key_names.size());
    for (size_t i = 0; i < key_names.size(); ++i) {
        if (!encode_json_string_ascii(key_names[i], &key_json[i])) {
            g_error = "invalid WTF-8 in decoded key";
            return -1;
        }
    }

    auto* res = new AggResult();
    std::vector<const std::pair<uint32_t, std::string>*> keys;
    for (auto* item : items) {
        res->blob.append(item->first);
        res->blob.push_back('\0');
        res->blob.append(item->second.first);
        res->blob.push_back('\0');
        res->blob.append(item->second.last);
        res->blob.push_back('\0');
        keys.clear();
        for (auto& kv : item->second.kv) keys.push_back(&kv);
        std::sort(keys.begin(), keys.end(),
                  [&](auto* a, auto* b) {
                      return key_names[a->first] < key_names[b->first];
                  });
        res->blob.push_back('{');
        bool first = true;
        for (auto* kv : keys) {
            if (!first) res->blob.push_back(',');
            first = false;
            res->blob.append(key_json[kv->first]);
            res->blob.push_back(':');
            res->blob.append(kv->second);
        }
        res->blob.push_back('}');
        res->blob.push_back('\0');
        ++res->n_entities;
    }
    *out_handle = res;
    *out_n = res->n_entities;
    *out_bytes = static_cast<int64_t>(res->blob.size());
    return 0;
}

int64_t pio_agg_fill(void* handle, char* buf) {
    auto* res = static_cast<AggResult*>(handle);
    if (!res) return -1;
    std::memcpy(buf, res->blob.data(), res->blob.size());
    return 0;
}

void pio_agg_free(void* handle) {
    delete static_cast<AggResult*>(handle);
}

}  // extern "C"
