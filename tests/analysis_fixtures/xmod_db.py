"""Leaf of the cross-module blocking fixture: the sqlite calls the
route two modules up must be blamed for."""

import sqlite3


def fetch_rows(table):
    conn = sqlite3.connect(":memory:")
    cur = conn.execute("select * from t")
    return cur.fetchall()
