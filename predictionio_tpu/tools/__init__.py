"""CLI console + ops tooling (`pio-tpu` verbs, import/export, dashboard).

Mirrors the reference's `tools/` module (SURVEY.md §2.3 [U]).
"""
