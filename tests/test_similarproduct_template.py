"""Similar Product template end-to-end: view events + $set item categories
→ implicit ALS → item-item cosine queries with filters (SURVEY.md §2.4
Similar Product row; §7.2 step 7)."""

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.similarproduct.SimilarProductEngine"


def ingest_views(storage, app_name="SimApp", n_users=16, n_groups=2,
                 items_per_group=4):
    """Users in group g repeatedly view group-g items: items co-viewed
    within a group should come out more similar than across groups."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    for g in range(n_groups):
        for j in range(items_per_group):
            le.insert(
                Event(event="$set", entity_type="item", entity_id=f"g{g}i{j}",
                      properties=DataMap({"categories": [f"cat{g}"]})),
                app_id)
    for u in range(n_users):
        g = u % n_groups
        # each user views all but one item of their group (rotating holdout)
        for j in range(items_per_group):
            if j == u % items_per_group:
                continue
            le.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"g{g}i{j}"),
                app_id)


def variant_dict(app_name="SimApp", rank=4, iters=15):
    return {
        "id": "sim-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": {
            "rank": rank, "numIterations": iters, "lambda": 0.05,
            "alpha": 2.0, "seed": 1}}],
    }


class TestSimilarProductEndToEnd:
    def test_train_and_similar(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        r = engine.predict(ep, models, {"items": ["g0i0"], "num": 3})
        items = [s["item"] for s in r["itemScores"]]
        assert len(items) == 3
        assert "g0i0" not in items  # basket excluded
        # co-viewed group-0 items must outrank group-1 items
        assert set(items[:2]) <= {f"g0i{j}" for j in range(4)}
        scores = [s["score"] for s in r["itemScores"]]
        assert scores == sorted(scores, reverse=True)

    def test_filters(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        models = engine.train(ctx, ep)

        # whiteList restricts candidates
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "whiteList": ["g1i0", "g1i1"]})
        assert {s["item"] for s in r["itemScores"]} <= {"g1i0", "g1i1"}
        # blackList removes candidates
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "blackList": ["g0i1"]})
        assert "g0i1" not in {s["item"] for s in r["itemScores"]}
        # categories filter keeps only matching items
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "categories": ["cat1"]})
        got = {s["item"] for s in r["itemScores"]}
        assert got and got <= {f"g1i{j}" for j in range(4)}

    def test_unknown_items_empty(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        r = engine.predict(ep, models, {"items": ["nope"], "num": 3})
        assert r == {"itemScores": []}

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptySim"))
        variant = EngineVariant.from_dict(variant_dict("EmptySim"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no view events"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)

    def test_template_engine_json_parses(self):
        import os

        from predictionio_tpu.workflow.workflow_utils import read_engine_json

        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "similarproduct", "engine.json")
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert ep.algorithm_params_list[0][0] == "als"
        assert ep.algorithm_params_list[0][1].rank == 10


class TestSimilarProductGrid:
    def test_train_grid_matches_sequential_per_cell(self, memory_storage):
        """r5: the grid-batched eval path extended to the similarproduct
        family — cells over (λ, iterations) train as one device program
        and each equals its own sequential train."""
        from predictionio_tpu.controller import WorkflowContext
        from predictionio_tpu.templates.similarproduct.engine import (
            ALSAlgorithm, ALSAlgorithmParams,
        )
        from predictionio_tpu.workflow.workflow_utils import (
            EngineVariant, extract_engine_params, get_engine,
        )

        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        ds, prep, _, _ = engine.components(ep)
        pd = prep.prepare(ctx, ds.read_training(ctx))

        algos = [ALSAlgorithm(ALSAlgorithmParams(
                     rank=4, numIterations=n, lambda_=lam, seed=2))
                 for n, lam in ((3, 0.05), (5, 0.05), (4, 0.2))]
        grid = ALSAlgorithm.train_grid(ctx, pd, algos)
        assert grid is not None and len(grid) == 3
        for algo, gm in zip(algos, grid):
            sm = algo.train(ctx, pd)
            np.testing.assert_allclose(
                gm.item_factors_unit, sm.item_factors_unit,
                rtol=2e-4, atol=2e-5)
        # different cells are genuinely different models
        assert np.abs(grid[0].item_factors_unit
                      - grid[2].item_factors_unit).max() > 1e-4


class TestSimilarProductEvaluation:
    def test_read_eval_folds_and_grid_eval(self, memory_storage,
                                           monkeypatch):
        """r5: the leave-views-out read_eval protocol + the evaluation
        grid routing through Engine.eval_grid (one batched program per
        fold, mixed iteration horizons)."""
        from predictionio_tpu.controller import WorkflowContext
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.templates.similarproduct.evaluation import (
            SimilarProductEvaluation,
        )

        ingest_views(memory_storage)
        monkeypatch.setenv("PIO_EVAL_APP_NAME", "SimApp")
        monkeypatch.setenv("PIO_EVAL_K", "2")
        ev = SimilarProductEvaluation()
        ctx = WorkflowContext(storage=memory_storage, seed=1)

        # protocol shape: folds partition views; every query anchors on
        # a KEPT item of the same user and the actual is the held-out
        ds = ev.engine.components(ev.engine_params_list[0])[0]
        folds = ds.read_eval(ctx)
        assert len(folds) == 2
        for fold_td, qa in folds:
            assert len(fold_td.user_idx) > 0 and len(qa) > 0
            for q, a in qa:
                assert q["items"] and a["items"]
                assert q["items"][0] != a["items"][0]

        result = MetricEvaluator.evaluate(ctx, ev, ev.engine_params_list)
        assert len(result.all_results) == len(ev.engine_params_list)
        scores = [r.scores[result.metric_name] for r in result.all_results]
        assert all(np.isfinite(s) for s in scores)
        assert result.best.scores[result.metric_name] == max(scores)


class TestSimilarProductCheckpoint:
    """Round 5: the SURVEY.md §5 checkpoint/resume contract reaches every
    ALS-backed template, not only recommendation — `ctx.checkpoint_dir`
    plumbs into this template's `als_train` too, and an interrupted train
    resumes to the uninterrupted result."""

    def _train(self, storage, ckpt_dir, iters):
        variant = EngineVariant.from_dict(variant_dict(iters=iters))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=storage, seed=1,
                              checkpoint_dir=ckpt_dir, checkpoint_every=1)
        return engine.train(ctx, ep)[0]

    def test_interrupted_resume_matches_uninterrupted(
            self, memory_storage, tmp_path, caplog):
        import logging

        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        ingest_views(memory_storage)
        want = self._train(memory_storage, None, iters=6)
        ck = str(tmp_path / "ck")
        self._train(memory_storage, ck, iters=3)  # the "interrupted" run
        cm = CheckpointManager(str(tmp_path / "ck" / "als"))
        assert cm.latest_step() == 3
        with caplog.at_level(logging.INFO):
            got = self._train(memory_storage, ck, iters=6)
        assert any("resumed from checkpoint step 3" in r.getMessage()
                   for r in caplog.records)
        assert cm.latest_step() == 6
        np.testing.assert_allclose(got.item_factors_unit,
                                   want.item_factors_unit,
                                   rtol=1e-4, atol=1e-5)
