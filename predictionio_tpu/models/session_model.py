"""SessionRecModel: the session-based next-item model's served state.

This module is numpy-only on purpose: the online plane imports it to
type-dispatch fold handles (`online/session.py`) and must stay loadable
in processes that never touch jax. The attention forward pass lives with
the DASE components in `templates/sessionrec/engine.py`; everything here
is id bookkeeping plus the ONE rule both the training path and the
online fold must share — what "a user's recent-item window" means.

The canonical window rule (`recent_window`): keep-last dedup per item
(an item's position is its LATEST event), order by (event time, item
id), keep the most recent `max_len` items. The (time, item) sort key —
not raw event order — is what makes the window a pure function of the
keep-last history the online plane already caches, so replaying a
tailed batch after a crash rebuilds a bit-identical window
(at-least-once delivery is free, same as ALS fold-in idempotence).

The per-user `session_vecs` entry is the user's pooled session
embedding — mean of the window's item-embedding rows — recomputed by
every fold that touches the user. Serving's attention scorer derives
everything from the window itself; the pooled vector exists so drills
and parity checks can compare session state bitwise without running the
attention stack, and so a degraded/debug path has a cheap per-user
representation.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap


def recent_window(pairs: Iterable[Tuple[str, object]],
                  max_len: int) -> List[str]:
    """Canonical session window over `(item_id, event_time)` pairs.

    Keep-last per item, sorted by (last event time, item id), most
    recent `max_len` items, oldest → newest. Pure and deterministic:
    re-applying the same events (tailer replay) or receiving them in a
    different arrival order produces the same window, because only the
    latest time per item survives and the sort key breaks time ties by
    item id rather than arrival order.
    """
    last: Dict[str, object] = {}
    for item, t in pairs:
        prev = last.get(item)
        if prev is None or not (t < prev):  # keep-last; ties keep newest
            last[item] = t
    ordered = sorted(last.items(), key=lambda kv: (kv[1], kv[0]))
    if max_len > 0:
        ordered = ordered[-max_len:]
    return [item for item, _ in ordered]


@dataclasses.dataclass
class SessionRecModel:
    """Immutable served state for the sessionrec template.

    `params` is a plain dict pytree of numpy arrays (pickles with the
    model store, device_puts cleanly at dispatch):

        emb    [V+1, D]  item embeddings; row V is the sequence pad row
        pos    [Lmax, D] learned positional embeddings (Lmax = top tier)
        blocks [{wq, wk, wv, wo, w1, b1, w2, b2}]  attention blocks

    `user_windows[user]` is the user's canonical recent-item window as
    item-id strings (oldest → newest, ≤ max_seq_len); `session_vecs` is
    the matching pooled embedding per user (see module docstring). Both
    are what the online fold swaps — the learned `params` only change on
    retrain.
    """

    params: dict
    item_ids: BiMap
    user_windows: Dict[str, Tuple[str, ...]]
    session_vecs: Dict[str, np.ndarray]
    max_seq_len: int
    n_heads: int

    @property
    def n_items(self) -> int:
        return int(self.params["emb"].shape[0]) - 1

    def window_rows(self, items: Iterable[str]) -> List[int]:
        """Embedding rows for the known items of a window, order kept.
        Items trained into the embedding table only — cold items (ids
        the last retrain never saw) are ignored, matching how ALS
        fold-in treats cold opposing rows."""
        out = []
        for i in items:
            row = self.item_ids.get(str(i))
            if row is not None:
                out.append(int(row))
        return out

    def session_vec_of(self, items: Iterable[str]) -> np.ndarray:
        """Pooled session embedding for an item window: mean of the
        known items' embedding rows (zeros when none are known). This is
        the exact recompute rule the online fold applies per touched
        user, so a drill can assert fold output bitwise."""
        rows = self.window_rows(items)
        emb = np.asarray(self.params["emb"])
        if not rows:
            return np.zeros(emb.shape[1], dtype=emb.dtype)
        return emb[np.asarray(rows, np.int32)].mean(axis=0)
