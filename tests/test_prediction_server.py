"""Prediction server conformance: deploy from stored instance, /queries.json,
hot-reload on retrain, /stop — SURVEY.md §3.2 contract."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.workflow.batch_predict import run_batch_predict
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.create_server import (
    PredictionServer,
    ServerConfig,
    load_served_state,
)
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)
from tests.test_recommendation_template import FACTORY, ingest_ratings, variant_dict


def train_once(storage, iters=10):
    variant = EngineVariant.from_dict(variant_dict(iters=iters))
    engine = get_engine(variant.engine_factory)
    ep = extract_engine_params(engine, variant)
    ctx = WorkflowContext(storage=storage, seed=1)
    return CoreWorkflow.run_train(engine, ep, variant, ctx)


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def deployed(memory_storage):
    expected = ingest_ratings(memory_storage)
    train_once(memory_storage)
    config = ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                          engine_variant="rec-test")
    server = PredictionServer(config, memory_storage)
    server.start()
    yield server, expected, memory_storage
    server.shutdown()


class TestPredictionServer:
    def test_status_page(self, deployed):
        server, _, _ = deployed
        status, body = call(server.port, "GET", "/")
        assert status == 200
        assert body["engineFactory"] == FACTORY
        assert body["engineInstanceId"] == server.instance_id

    def test_queries(self, deployed):
        server, expected, _ = deployed
        status, body = call(server.port, "POST", "/queries.json",
                            {"user": "u0", "num": 3})
        assert status == 200
        items = [s["item"] for s in body["itemScores"]]
        assert items[0] == expected["u0"]
        # unknown user → empty scores, not an error
        status, body = call(server.port, "POST", "/queries.json",
                            {"user": "nobody", "num": 3})
        assert status == 200 and body == {"itemScores": []}

    def test_malformed_query_400(self, deployed):
        server, _, _ = deployed
        status, _ = call(server.port, "POST", "/queries.json", {"num": 3})
        assert status == 400  # missing "user" key

    def test_deploy_without_training_fails_cleanly(self, memory_storage):
        config = ServerConfig(engine_id="never-trained")
        with pytest.raises(RuntimeError, match="No completed engine instance"):
            load_served_state(memory_storage, config)

    def test_hot_reload_serves_new_instance(self, deployed):
        server, _, storage = deployed
        old_id = server.instance_id
        new_instance = train_once(storage, iters=12)  # retrain
        status, body = call(server.port, "POST", "/reload")
        assert status == 200
        assert body["engineInstanceId"] == new_instance.id != old_id
        # still serves queries after reload
        status, _ = call(server.port, "POST", "/queries.json",
                         {"user": "u0", "num": 2})
        assert status == 200

    def test_stop_endpoint(self, memory_storage):
        ingest_ratings(memory_storage)
        train_once(memory_storage)
        config = ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                              engine_variant="rec-test")
        server = PredictionServer(config, memory_storage)
        server.start()
        status, body = call(server.port, "POST", "/stop")
        assert status == 200
        import time
        for _ in range(50):  # wait for socket to close
            time.sleep(0.1)
            try:
                call(server.port, "GET", "/")
            except (ConnectionError, urllib.error.URLError, OSError):
                break
        else:
            pytest.fail("server still alive after /stop")


class TestConnectionBurst:
    def test_32_simultaneous_connects_all_served(self, deployed):
        """Regression for the round-4 ladder finding: socketserver's
        default listen backlog of 5 RST'd a >5-connection burst
        (ECONNRESET at 32 load clients). utils/http.py raises
        request_queue_size to 128 — a 32-socket burst must now fully
        connect and every connection must answer a query."""
        import socket as socket_mod

        server, _, _ = deployed
        socks = []
        try:
            # connect all 32 BEFORE any handler thread reads a request —
            # the queue, not handler speed, is what's under test
            for _ in range(32):
                s = socket_mod.create_connection(("127.0.0.1", server.port),
                                                 timeout=10)
                socks.append(s)
            body = json.dumps({"user": "1", "num": 1}).encode()
            req = (b"POST /queries.json HTTP/1.1\r\n"
                   b"Host: x\r\nContent-Type: application/json\r\n"
                   b"Content-Length: " + str(len(body)).encode() +
                   b"\r\nConnection: close\r\n\r\n" + body)
            for s in socks:
                s.sendall(req)
            for s in socks:
                s.settimeout(30)
                first = s.recv(64)
                assert b"200" in first.split(b"\r\n")[0], first
        finally:
            for s in socks:
                s.close()


class TestBatchPredict:
    def test_batch_predict_roundtrip(self, deployed, tmp_path):
        server, expected, storage = deployed
        inp = tmp_path / "queries.jsonl"
        out = tmp_path / "out.jsonl"
        inp.write_text('{"user": "u0", "num": 2}\n{"user": "u1", "num": 2}\n')
        n = run_batch_predict(str(inp), str(out), engine_id="rec-test",
                              engine_variant="rec-test", storage=storage)
        assert n == 2
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines[0]["query"] == {"user": "u0", "num": 2}
        assert lines[0]["prediction"]["itemScores"][0]["item"] == expected["u0"]
        assert lines[1]["prediction"]["itemScores"][0]["item"] == expected["u1"]


class TestReviewRegressions:
    def test_topk_dtypes_consistent_across_batch_sizes(self):
        import numpy as np
        from predictionio_tpu.ops.ranking import recommend_topk

        u = np.random.default_rng(0).normal(size=(100, 4)).astype(np.float32)
        v = np.random.default_rng(1).normal(size=(20, 4)).astype(np.float32)
        s_small, i_small = recommend_topk(u, v, np.arange(3, dtype=np.int32), 5)
        s_big, i_big = recommend_topk(u, v, np.arange(100, dtype=np.int32), 5)
        assert s_small.dtype == s_big.dtype == np.float32
        assert i_small.dtype == i_big.dtype == np.int32
        # same answers either path
        np.testing.assert_array_equal(i_small, i_big[:3])

    def test_deploy_cli_bad_engine_json(self, memory_storage, tmp_path, capsys):
        from predictionio_tpu.tools.console import main

        bad = tmp_path / "engine.json"
        bad.write_text("{not json")
        rc = main(["deploy", "--engine-json", str(bad), "--port", "0"])
        assert rc == 1
        assert "Cannot parse" in capsys.readouterr().err

    def test_deploy_cli_untrained_clean(self, memory_storage, capsys):
        from predictionio_tpu.tools.console import main

        rc = main(["deploy", "--engine-id", "ghost", "--engine-variant", "ghost",
                   "--engine-json", "/nonexistent", "--port", "0"])
        assert rc == 1
        assert "Deploy failed" in capsys.readouterr().err
