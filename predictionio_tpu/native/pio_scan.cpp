// Native columnar event scan for predictionio_tpu.
//
// The reference's bulk training read is an HBase TableInputFormat scan
// feeding Spark executors («HBPEvents» — SURVEY.md §2.2 [U]). The TPU
// rebuild's equivalent is this: walk the SQLite event table once via the
// sqlite3 C API, code entity/target strings to dense ints with a hash
// map, extract one numeric JSON property, and parse fixed-width UTC
// timestamps — filling caller-allocated numpy buffers directly. No
// per-event Python object, no Python per-row cost at all (measured ~6×
// faster than the window-function SQL path at 2M events, which itself
// is ~2× the per-event path).
//
// sqlite3 is loaded with dlopen (no link-time dependency; the image
// ships libsqlite3.so.0 without headers, so the handful of C-API
// prototypes used are declared locally — the sqlite3 C ABI is stable).
//
// Two-phase C ABI like the bucketizer (pio_native.cpp): open() runs the
// whole scan into internal buffers and reports sizes; fill() copies into
// caller numpy arrays + '\0'-joined sorted id strings; free() releases.
// On any surprise (unloadable sqlite, bad timestamp format, sqlite
// error) the wrapper falls back to the pure-SQL path, keeping behavior
// identical with and without a toolchain.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <dlfcn.h>

namespace {

// -- minimal sqlite3 C API surface (stable ABI, declared locally) -------
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
constexpr int kSqliteOk = 0;
constexpr int kSqliteRow = 100;
constexpr int kSqliteDone = 101;
constexpr int kOpenReadonly = 0x00000001;
constexpr int kColNull = 5;

struct SqliteApi {
    int (*open_v2)(const char*, sqlite3**, int, const char*);
    int (*close_v2)(sqlite3*);
    int (*prepare_v2)(sqlite3*, const char*, int, sqlite3_stmt**,
                      const char**);
    int (*step)(sqlite3_stmt*);
    int (*finalize)(sqlite3_stmt*);
    int (*bind_text)(sqlite3_stmt*, int, const char*, int, void*);
    int (*column_type)(sqlite3_stmt*, int);
    const unsigned char* (*column_text)(sqlite3_stmt*, int);
    int (*column_bytes)(sqlite3_stmt*, int);
    const char* (*errmsg)(sqlite3*);
    bool ok = false;
};

const SqliteApi& sqlite_api() {
    static SqliteApi api = [] {
        SqliteApi a;
        void* h = dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_GLOBAL);
        if (!h) h = dlopen("libsqlite3.so", RTLD_NOW | RTLD_GLOBAL);
        if (!h) return a;
        auto sym = [&](const char* name) { return dlsym(h, name); };
        a.open_v2 = reinterpret_cast<decltype(a.open_v2)>(
            sym("sqlite3_open_v2"));
        a.close_v2 = reinterpret_cast<decltype(a.close_v2)>(
            sym("sqlite3_close_v2"));
        a.prepare_v2 = reinterpret_cast<decltype(a.prepare_v2)>(
            sym("sqlite3_prepare_v2"));
        a.step = reinterpret_cast<decltype(a.step)>(sym("sqlite3_step"));
        a.finalize = reinterpret_cast<decltype(a.finalize)>(
            sym("sqlite3_finalize"));
        a.bind_text = reinterpret_cast<decltype(a.bind_text)>(
            sym("sqlite3_bind_text"));
        a.column_type = reinterpret_cast<decltype(a.column_type)>(
            sym("sqlite3_column_type"));
        a.column_text = reinterpret_cast<decltype(a.column_text)>(
            sym("sqlite3_column_text"));
        a.column_bytes = reinterpret_cast<decltype(a.column_bytes)>(
            sym("sqlite3_column_bytes"));
        a.errmsg = reinterpret_cast<decltype(a.errmsg)>(
            sym("sqlite3_errmsg"));
        a.ok = a.open_v2 && a.close_v2 && a.prepare_v2 && a.step &&
               a.finalize && a.bind_text && a.column_type && a.column_text &&
               a.column_bytes && a.errmsg;
        return a;
    }();
    return api;
}

thread_local std::string g_error;

// -- fixed-width UTC ISO-8601 timestamp → unix seconds ------------------
// Stored format (data/events.py::format_time): YYYY-MM-DDTHH:MM:SS.ffffffZ
// (27 bytes). Returns NaN on any other shape; the caller then aborts the
// native scan and the wrapper falls back to SQL (which parses anything
// sqlite's julianday accepts).
inline int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
    y -= m <= 2;
    const int64_t era = (y >= 0 ? y : y - 399) / 400;
    const int64_t yoe = y - era * 400;
    const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

inline bool parse_uint(const char* s, int len, int64_t* out) {
    int64_t v = 0;
    for (int i = 0; i < len; ++i) {
        if (s[i] < '0' || s[i] > '9') return false;
        v = v * 10 + (s[i] - '0');
    }
    *out = v;
    return true;
}

double parse_time_fixed(const char* s, int n) {
    if (n != 27 || s[4] != '-' || s[7] != '-' || s[10] != 'T' ||
        s[13] != ':' || s[16] != ':' || s[19] != '.' || s[26] != 'Z')
        return std::nan("");
    int64_t y, mo, d, h, mi, se, us;
    if (!parse_uint(s, 4, &y) || !parse_uint(s + 5, 2, &mo) ||
        !parse_uint(s + 8, 2, &d) || !parse_uint(s + 11, 2, &h) ||
        !parse_uint(s + 14, 2, &mi) || !parse_uint(s + 17, 2, &se) ||
        !parse_uint(s + 20, 6, &us))
        return std::nan("");
    const int64_t days = days_from_civil(y, mo, d);
    return static_cast<double>(days * 86400 + h * 3600 + mi * 60 + se) +
           static_cast<double>(us) * 1e-6;
}

// -- top-level JSON numeric property extraction -------------------------
// Matches the SQL path's CAST(json_extract(props, '$.key') AS REAL)
// closely enough for training data: numbers parse, string-coded numbers
// parse via numeric prefix (CAST semantics), true/false → 1/0, anything
// else (or absent key) → NaN. Only depth-1 keys match, like $-paths.
struct JsonScanner {
    const char* p;
    const char* end;

    bool skip_ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                           *p == '\r'))
            ++p;
        return p < end;
    }

    // on entry *p == '"'; leaves p past the closing quote. Appends the
    // raw (unescaped-length) bytes to out for key comparison; escape
    // sequences are copied through minimally (\" \\ \/ pass the second
    // byte; \uXXXX and others keep raw bytes — keys with escapes then
    // simply never match a plain value_key, which is fine).
    bool parse_string(std::string* out) {
        ++p;  // opening quote
        while (p < end) {
            if (*p == '"') {
                ++p;
                return true;
            }
            if (*p == '\\' && p + 1 < end) {
                char c = p[1];
                if (out) {
                    if (c == '"' || c == '\\' || c == '/') out->push_back(c);
                    else if (c == 'n') out->push_back('\n');
                    else if (c == 't') out->push_back('\t');
                    else { out->push_back('\\'); out->push_back(c); }
                }
                p += 2;
                continue;
            }
            if (out) out->push_back(*p);
            ++p;
        }
        return false;
    }

    // skip any JSON value (p at its first byte)
    bool skip_value() {
        if (!skip_ws()) return false;
        if (*p == '"') return parse_string(nullptr);
        if (*p == '{' || *p == '[') {
            int depth = 0;
            while (p < end) {
                if (*p == '"') {
                    if (!parse_string(nullptr)) return false;
                    continue;
                }
                if (*p == '{' || *p == '[') ++depth;
                else if (*p == '}' || *p == ']') {
                    --depth;
                    if (depth == 0) { ++p; return true; }
                }
                ++p;
            }
            return false;
        }
        // number / literal: advance to delimiter
        while (p < end && *p != ',' && *p != '}' && *p != ']' &&
               *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r')
            ++p;
        return true;
    }
};

float json_num_value(const char* json, int n, const std::string& key) {
    JsonScanner s{json, json + n};
    if (!s.skip_ws() || *s.p != '{') return std::nanf("");
    ++s.p;
    std::string k;
    while (s.skip_ws()) {
        if (*s.p == '}') return std::nanf("");
        if (*s.p == ',') { ++s.p; continue; }
        if (*s.p != '"') return std::nanf("");
        k.clear();
        if (!s.parse_string(&k)) return std::nanf("");
        if (!s.skip_ws() || *s.p != ':') return std::nanf("");
        ++s.p;
        if (k == key) {
            if (!s.skip_ws()) return std::nanf("");
            const char* vp = s.p;
            if (*vp == '"') {
                std::string v;
                JsonScanner vs{vp, s.end};
                if (!vs.parse_string(&v)) return std::nanf("");
                if (v.empty()) return std::nanf("");
                char* endp = nullptr;
                double d = std::strtod(v.c_str(), &endp);
                // CAST semantics: numeric prefix; no digits at all → NaN
                // (SQL CAST gives 0.0 there; training data never hits it)
                if (endp == v.c_str()) return std::nanf("");
                return static_cast<float>(d);
            }
            if (std::strncmp(vp, "true", 4) == 0) return 1.0f;
            if (std::strncmp(vp, "false", 5) == 0) return 0.0f;
            char* endp = nullptr;
            double d = std::strtod(vp, &endp);
            if (endp == vp) return std::nanf("");
            return static_cast<float>(d);
        }
        if (!s.skip_value()) return std::nanf("");
    }
    return std::nanf("");
}

// -- scan handle --------------------------------------------------------
struct ScanResult {
    std::vector<int32_t> ent, tgt, ev;
    std::vector<float> val;
    std::vector<double> tim;
    std::vector<std::string> ent_ids, tgt_ids;  // sorted
    int64_t ent_bytes = 0, tgt_bytes = 0;       // incl. one NUL each
};

// first-appearance intern; returns code
inline int32_t intern(std::unordered_map<std::string, int32_t>& m,
                      std::vector<std::string>& order, const char* s,
                      int n) {
    auto it = m.find(std::string(s, n));  // one lookup; emplace below reuses
    if (it != m.end()) return it->second;
    int32_t code = static_cast<int32_t>(order.size());
    order.emplace_back(s, n);
    m.emplace(order.back(), code);
    return code;
}

}  // namespace

extern "C" {

const char* pio_scan_error() { return g_error.c_str(); }

// Runs the full scan. Returns 0 and a handle on success; -1 on failure
// (pio_scan_error() has the reason; the caller falls back to SQL).
// Column order expected from `sql`:
//   0 entity_id TEXT, 1 target_entity_id TEXT|NULL, 2 event TEXT,
//   3 properties TEXT, 4 event_time TEXT
int64_t pio_scan_open(const char* db_path, const char* sql,
                      const char** params, int64_t n_params,
                      const char* value_key,
                      const char** event_names, int64_t n_event_names,
                      void** out_handle, int64_t* out_n,
                      int64_t* out_n_ent, int64_t* out_ent_bytes,
                      int64_t* out_n_tgt, int64_t* out_tgt_bytes) {
    const SqliteApi& api = sqlite_api();
    if (!api.ok) {
        g_error = "libsqlite3 not loadable";
        return -1;
    }
    sqlite3* db = nullptr;
    if (api.open_v2(db_path, &db, kOpenReadonly, nullptr) != kSqliteOk) {
        g_error = db ? api.errmsg(db) : "open failed";
        if (db) api.close_v2(db);
        return -1;
    }
    sqlite3_stmt* stmt = nullptr;
    if (api.prepare_v2(db, sql, -1, &stmt, nullptr) != kSqliteOk) {
        g_error = api.errmsg(db);
        api.close_v2(db);
        return -1;
    }
    for (int64_t i = 0; i < n_params; ++i) {
        // SQLITE_TRANSIENT == (void*)-1: sqlite copies the text
        if (api.bind_text(stmt, static_cast<int>(i + 1), params[i], -1,
                          reinterpret_cast<void*>(-1)) != kSqliteOk) {
            g_error = api.errmsg(db);
            api.finalize(stmt);
            api.close_v2(db);
            return -1;
        }
    }

    std::unordered_map<std::string, int32_t> ent_map, tgt_map, ev_map;
    std::vector<std::string> ent_order, tgt_order;
    for (int64_t i = 0; i < n_event_names; ++i)
        ev_map.emplace(event_names[i], static_cast<int32_t>(i));
    const std::string vkey = value_key ? value_key : "";

    auto* res = new ScanResult();
    int rc;
    while ((rc = api.step(stmt)) == kSqliteRow) {
        const char* e = reinterpret_cast<const char*>(
            api.column_text(stmt, 0));
        int elen = api.column_bytes(stmt, 0);
        res->ent.push_back(intern(ent_map, ent_order, e ? e : "", elen));

        if (api.column_type(stmt, 1) == kColNull) {
            res->tgt.push_back(-1);
        } else {
            const char* t = reinterpret_cast<const char*>(
                api.column_text(stmt, 1));
            int tlen = api.column_bytes(stmt, 1);
            res->tgt.push_back(intern(tgt_map, tgt_order, t ? t : "", tlen));
        }

        const char* ev = reinterpret_cast<const char*>(
            api.column_text(stmt, 2));
        auto it = ev_map.find(ev ? ev : "");
        res->ev.push_back(it == ev_map.end() ? -1 : it->second);

        if (vkey.empty()) {
            res->val.push_back(std::nanf(""));
        } else {
            const char* pj = reinterpret_cast<const char*>(
                api.column_text(stmt, 3));
            int plen = api.column_bytes(stmt, 3);
            res->val.push_back(pj ? json_num_value(pj, plen, vkey)
                                  : std::nanf(""));
        }

        const char* ts = reinterpret_cast<const char*>(
            api.column_text(stmt, 4));
        int tslen = api.column_bytes(stmt, 4);
        double t = ts ? parse_time_fixed(ts, tslen) : std::nan("");
        if (std::isnan(t)) {
            g_error = "non-canonical event_time format";
            api.finalize(stmt);
            api.close_v2(db);
            delete res;
            return -1;
        }
        res->tim.push_back(t);
    }
    api.finalize(stmt);
    if (rc != kSqliteDone) {
        g_error = api.errmsg(db);
        api.close_v2(db);
        delete res;
        return -1;
    }
    api.close_v2(db);

    // remap first-appearance codes → sorted-order codes (BiMap contract:
    // codes follow sorted distinct-id order on every backend path)
    auto remap = [](std::vector<std::string>& order,
                    std::vector<int32_t>& codes, int64_t* total_bytes) {
        const size_t n = order.size();
        std::vector<int32_t> perm(n);
        for (size_t i = 0; i < n; ++i) perm[i] = static_cast<int32_t>(i);
        std::sort(perm.begin(), perm.end(), [&](int32_t a, int32_t b) {
            return order[a] < order[b];
        });
        std::vector<int32_t> old_to_new(n);
        std::vector<std::string> sorted_ids(n);
        int64_t bytes = 0;
        for (size_t i = 0; i < n; ++i) {
            old_to_new[perm[i]] = static_cast<int32_t>(i);
            sorted_ids[i] = std::move(order[perm[i]]);
            bytes += static_cast<int64_t>(sorted_ids[i].size()) + 1;
        }
        for (auto& c : codes)
            if (c >= 0) c = old_to_new[c];
        order = std::move(sorted_ids);
        *total_bytes = bytes;
    };
    remap(ent_order, res->ent, &res->ent_bytes);
    remap(tgt_order, res->tgt, &res->tgt_bytes);
    res->ent_ids = std::move(ent_order);
    res->tgt_ids = std::move(tgt_order);

    *out_handle = res;
    *out_n = static_cast<int64_t>(res->ent.size());
    *out_n_ent = static_cast<int64_t>(res->ent_ids.size());
    *out_ent_bytes = res->ent_bytes;
    *out_n_tgt = static_cast<int64_t>(res->tgt_ids.size());
    *out_tgt_bytes = res->tgt_bytes;
    return 0;
}

int64_t pio_scan_fill(void* handle, int32_t* ent, int32_t* tgt, int32_t* ev,
                      float* val, double* tim, char* entity_buf,
                      char* target_buf) {
    auto* res = static_cast<ScanResult*>(handle);
    if (!res) return -1;
    const size_t n = res->ent.size();
    std::memcpy(ent, res->ent.data(), n * sizeof(int32_t));
    std::memcpy(tgt, res->tgt.data(), n * sizeof(int32_t));
    std::memcpy(ev, res->ev.data(), n * sizeof(int32_t));
    std::memcpy(val, res->val.data(), n * sizeof(float));
    std::memcpy(tim, res->tim.data(), n * sizeof(double));
    char* p = entity_buf;
    for (const auto& s : res->ent_ids) {
        std::memcpy(p, s.data(), s.size());
        p += s.size();
        *p++ = '\0';
    }
    p = target_buf;
    for (const auto& s : res->tgt_ids) {
        std::memcpy(p, s.data(), s.size());
        p += s.size();
        *p++ = '\0';
    }
    return 0;
}

void pio_scan_free(void* handle) {
    delete static_cast<ScanResult*>(handle);
}

}  // extern "C"
