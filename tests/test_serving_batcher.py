"""Serving micro-batcher correctness: batched ≡ sequential bitwise across
every bucket size (padding rows included), deadline expiry while queued
never reaches the scoring path, per-item isolation, and the ≤5% overhead
bar at batch-of-1."""

import gc
import http.client
import json
import statistics
import threading
import time

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.serving import (
    AdmissionConfig,
    BatcherConfig,
    MicroBatcher,
    ServingConfig,
    ServingPlane,
)
from predictionio_tpu.serving.admission import DeadlineExceeded
from predictionio_tpu.serving.batcher import bucket_ladder
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)
from tests.test_recommendation_template import ingest_ratings, variant_dict


@pytest.fixture()
def rec_engine(memory_storage):
    """Trained recommendation engine (ALS) + resolved serving pieces."""
    ingest_ratings(memory_storage)
    variant = EngineVariant.from_dict(variant_dict())
    engine = get_engine(variant.engine_factory)
    ep = extract_engine_params(engine, variant)
    ctx = WorkflowContext(storage=memory_storage, seed=1)
    instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
    blob = memory_storage.model_data_models().get(instance.id).models
    models = engine.deserialize_models(blob, instance.id, ep)
    components = engine.components(ep)
    return engine, ep, models, components


class TestBucketLadder:
    def test_powers_of_two_capped(self):
        assert bucket_ladder(32) == (1, 2, 4, 8, 16, 32)
        assert bucket_ladder(1) == (1,)
        assert bucket_ladder(24) == (1, 2, 4, 8, 16, 24)

    def test_config_override(self):
        assert BatcherConfig(buckets=(8, 2, 8)).resolved_buckets() == (2, 8)


class TestBatchedParity:
    """The acceptance bar: a query's result must not depend on which batch
    it arrived in — batched dispatch bitwise-equal to sequential predicts
    for every bucket size, padding rows included."""

    def test_engine_predict_batch_matches_sequential(self, rec_engine):
        engine, ep, models, components = rec_engine
        queries = [{"user": f"u{i % 12}", "num": 3 + (i % 4)}
                   for i in range(33)]
        sequential = [engine.predict(ep, models, q, components=components)
                      for q in queries]
        # every bucket size of the default ladder, plus one past max_batch
        for size in (1, 2, 3, 4, 7, 8, 16, 32, 33):
            batched = engine.predict_batch(ep, models, queries[:size],
                                           components=components)
            assert batched == sequential[:size], f"batch size {size}"

    def test_padding_rows_are_invisible(self, rec_engine):
        """A batch of 3 pads to bucket 4: the dispatch sees 4 queries, the
        callers see 3 results, bitwise equal to sequential."""
        engine, ep, models, components = rec_engine
        queries = [{"user": f"u{i}", "num": 3} for i in range(3)]
        sequential = [engine.predict(ep, models, q, components=components)
                      for q in queries]
        seen_sizes = []

        def dispatch(qs):
            seen_sizes.append(len(qs))
            return engine.predict_batch(ep, models, qs,
                                        components=components)

        # fill mode holds the batch open until all three queue together
        b = MicroBatcher(dispatch, BatcherConfig(max_batch=3,
                                                 max_wait_ms=500.0,
                                                 buckets=(1, 2, 4)))
        try:
            results = [None] * 3
            ts = [threading.Thread(target=lambda i=i: results.__setitem__(
                i, b.submit(queries[i]))) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            b.close()
        assert seen_sizes == [4]  # 3 live + 1 padding row
        assert results == sequential

    def test_similarproduct_batch_matches_sequential(self):
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.templates.similarproduct.engine import (
            ALSAlgorithm,
            ALSAlgorithmParams,
            SimilarProductModel,
        )

        rng = np.random.default_rng(7)
        n = 40
        f = rng.normal(size=(n, 6)).astype(np.float32)
        unit = (f / np.linalg.norm(f, axis=1, keepdims=True)).astype(
            np.float32)
        ids = BiMap.string_int(f"i{j}" for j in range(n))
        model = SimilarProductModel(
            item_factors_unit=unit, item_ids=ids,
            item_categories={"i0": ["a"], "i1": ["b"]})
        algo = ALSAlgorithm(ALSAlgorithmParams())
        queries = (
            # vectorizable: filterless, known items
            [{"items": [f"i{j}"], "num": 5} for j in range(10)]
            # multi-item baskets
            + [{"items": ["i1", "i3", "i5"], "num": 4}]
            # per-item fallbacks: filters, unknown items, empty
            + [{"items": ["i0"], "num": 5, "categories": ["b"]},
               {"items": ["i2"], "num": 5, "blackList": ["i3"]},
               {"items": ["nope"], "num": 5},
               {"items": ["i4", "nope"], "num": 5},
               {"items": ["i6"], "num": 0}]
            # a second num group
            + [{"items": [f"i{j}"], "num": 7} for j in range(20, 24)])
        sequential = [algo.predict(model, q) for q in queries]
        assert algo.batch_predict(model, queries) == sequential
        # order independence: shuffled batch, same per-query answers
        perm = rng.permutation(len(queries))
        shuffled = algo.batch_predict(model, [queries[i] for i in perm])
        assert shuffled == [sequential[i] for i in perm]

    def test_productranking_batch_matches_sequential(self, memory_storage):
        from predictionio_tpu.templates.productranking.engine import (
            RankingALSAlgorithm,
        )
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithmParams,
        )

        ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        blob = memory_storage.model_data_models().get(instance.id).models
        model = engine.deserialize_models(blob, instance.id, ep)[0]
        algo = RankingALSAlgorithm(ALSAlgorithmParams())
        queries = [
            {"user": "u0", "items": ["i1", "i3", "i5"]},
            {"user": "u1", "items": ["i0", "i2"]},
            {"user": "u0", "items": ["i7", "nope", "i2"]},  # repeat user
            {"user": "stranger", "items": ["i1"]},  # isOriginal path
            {"user": "u2", "items": []},
        ]
        sequential = [algo.predict(model, q) for q in queries]
        assert algo.batch_predict(model, queries) == sequential


class TestAdmittedAwareFill:
    """The fill hold is adaptive: `max_wait_ms` caps the wait for
    admitted-but-not-yet-queued requests, it is not a fixed stall."""

    def test_lone_request_is_never_held(self):
        """With a deliberately huge cap (5s), a lone request must still
        answer immediately — admitted == 1 means nobody else is coming."""
        seen = []

        def dispatch(qs):
            seen.append(len(qs))
            return list(qs)

        plane = ServingPlane(
            dispatch,
            config=ServingConfig(batcher=BatcherConfig(max_wait_ms=5000.0)))
        try:
            t0 = time.perf_counter()
            result, degraded = plane.handle_query("q")
            elapsed = time.perf_counter() - t0
        finally:
            plane.close()
        assert result == "q" and degraded is False
        assert seen == [1]
        assert elapsed < 1.0, f"lone request stalled {elapsed:.3f}s"

    def test_concurrent_admitted_requests_coalesce(self):
        """Overlapping admitted requests leave as (a) shared batch(es),
        not one dispatch each."""
        seen = []

        def dispatch(qs):
            seen.append(len(qs))
            time.sleep(0.05)  # hold the dispatch so the rest overlap
            return list(qs)

        plane = ServingPlane(
            dispatch,
            config=ServingConfig(batcher=BatcherConfig(max_wait_ms=5000.0)))
        results = {}
        start = threading.Barrier(4)

        def run(i):
            start.wait()
            results[i] = plane.handle_query(f"q{i}")[0]

        try:
            ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
        finally:
            plane.close()
        assert results == {i: f"q{i}" for i in range(4)}
        # dispatch sizes are bucket-padded, so compare counts, not sums
        assert len(seen) < 4, f"no coalescing happened: {seen}"
        assert max(seen) >= 2, f"no multi-query batch formed: {seen}"


class TestDeadlines:
    def test_expired_while_queued_never_dispatched(self):
        """A request whose deadline lapses in the queue gets
        DeadlineExceeded (→ 503) and its query NEVER reaches the dispatch
        function — the device does no work nobody is waiting for."""
        dispatched = []
        release = threading.Event()

        def slow(qs):
            dispatched.append(list(qs))
            release.wait(10)
            return qs

        b = MicroBatcher(slow, BatcherConfig(max_batch=4))
        try:
            blocker = threading.Thread(target=lambda: b.submit("blocker"))
            blocker.start()
            deadline = time.monotonic() + 5
            while not dispatched and time.monotonic() < deadline:
                time.sleep(0.005)
            assert dispatched, "blocker never dispatched"
            with pytest.raises(DeadlineExceeded):
                b.submit("late", deadline=time.monotonic() + 0.02)
            release.set()
            blocker.join(timeout=10)
            # drain: give the dispatcher a beat to process the queue
            time.sleep(0.1)
        finally:
            release.set()
            b.close()
        assert not any("late" in batch for batch in dispatched), dispatched

    def test_expired_before_dispatch_inline(self):
        b = MicroBatcher(lambda qs: qs)
        try:
            with pytest.raises(DeadlineExceeded):
                b.submit("q", deadline=time.monotonic() - 1)
        finally:
            b.close()


class TestIsolation:
    def test_poison_query_fails_alone(self):
        """One malformed query must answer its own error, not 400 the
        innocent queries it was co-batched with."""

        def dispatch(qs):
            if any(q == "poison" for q in qs):
                raise ValueError("bad query")
            return [q.upper() for q in qs]

        b = MicroBatcher(dispatch, BatcherConfig(max_batch=8,
                                                 max_wait_ms=500.0))
        try:
            results = {}

            def run(q):
                try:
                    results[q] = b.submit(q)
                except ValueError as e:
                    results[q] = e
            ts = [threading.Thread(target=run, args=(q,))
                  for q in ("a", "poison", "b")]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            b.close()
        assert results["a"] == "A" and results["b"] == "B"
        assert isinstance(results["poison"], ValueError)

    def test_poisoned_full_bucket_retries_at_original_tier(self):
        """Regression: the per-item fallback used to dispatch each
        survivor as a bare batch of 1 — a shape the grouped attempt
        never warmed, so one poisoned sequence in a full bucket minted
        a fresh compile per innocent co-batched query. Every retry must
        arrive at the ORIGINAL padded size (query repeated to fill it),
        and survivors must still get correct answers."""
        calls = []
        release = threading.Event()

        def dispatch(qs):
            calls.append(list(qs))
            if qs[0] == "blocker":
                release.wait(10)
                return list(qs)
            if any(q == "poison" for q in qs):
                raise ValueError("bad sequence")
            return [q.upper() for q in qs]

        b = MicroBatcher(dispatch, BatcherConfig(max_batch=4))
        results = {}

        def run(q):
            try:
                results[q] = b.submit(q)
            except ValueError as e:
                results[q] = e

        try:
            blocker = threading.Thread(target=run, args=("blocker",))
            blocker.start()
            deadline = time.monotonic() + 5
            while not calls and time.monotonic() < deadline:
                time.sleep(0.005)
            assert calls, "blocker never dispatched"
            ts = [threading.Thread(target=run, args=(q,))
                  for q in ("a", "poison", "b", "c")]
            for t in ts:
                t.start()
            # hold the blocker until the full bucket is queued, so the
            # poison is deterministically co-batched with 3 survivors
            deadline = time.monotonic() + 5
            while len(b._queue) < 4 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(b._queue) == 4, "bucket never filled"
            release.set()
            for t in ts:
                t.join(timeout=10)
            blocker.join(timeout=10)
        finally:
            release.set()
            b.close()
        assert results["a"] == "A" and results["b"] == "B" \
            and results["c"] == "C"
        assert isinstance(results["poison"], ValueError)
        grouped = calls[1]  # [0] is the blocker
        assert sorted(grouped) == ["a", "b", "c", "poison"]
        retries = calls[2:]
        assert len(retries) == 4  # one per member, in batch order
        for retry in retries:
            # repeated to the original bucket size — never re-padded
            # down onto a fresh (smaller) tier mid-incident
            assert len(retry) == len(grouped)
            assert set(retry) == {retry[0]}

    def test_dispatch_result_count_mismatch_is_an_error(self):
        b = MicroBatcher(lambda qs: [])
        try:
            with pytest.raises(RuntimeError, match="0 results"):
                b.submit("q")
        finally:
            b.close()

    def test_closed_batcher_rejects(self):
        b = MicroBatcher(lambda qs: qs)
        b.close()
        with pytest.raises(RuntimeError, match="shut down"):
            b.submit("q")


# -- overhead bar -----------------------------------------------------------

def test_batcher_overhead_under_5_percent_at_batch_of_1():
    """The serving plane's per-request machinery (deadline parse, admit,
    inline batcher dispatch, release) must cost ≤5% of a real loopback
    request p50 at batch-of-1 — micro-batching must be free when there is
    nothing to batch. Same methodology as the telemetry overhead bar:
    machinery timed in-process against a measured HTTP p50 (an A/B of two
    live servers at this tolerance would be noise-bound)."""
    from predictionio_tpu.utils.http import HttpService, JsonRequestHandler

    class _PingHandler(JsonRequestHandler):
        def do_GET(self):
            self.send_json(200, {"ok": True})

    svc = HttpService("127.0.0.1", 0, _PingHandler, server_name="batchbar")
    svc.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=10)
        samples = []
        for _ in range(50):  # warm-up
            conn.request("GET", "/")
            conn.getresponse().read()
        for _ in range(300):
            t0 = time.perf_counter()
            conn.request("GET", "/")
            conn.getresponse().read()
            samples.append(time.perf_counter() - t0)
        conn.close()
    finally:
        svc.shutdown()
    request_p50 = statistics.median(samples)

    plane = ServingPlane(lambda qs: qs,
                         config=ServingConfig(
                             admission=AdmissionConfig(max_queue=64)),
                         name="batchbar")
    headers = {"X-PIO-Deadline-Ms": "1000"}
    n = 2000
    batches = []
    gc.disable()
    try:
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(n):
                plane.handle_query(i, headers)
            batches.append((time.perf_counter() - t0) / n)
    finally:
        gc.enable()
        plane.close()
    per_request = min(batches)

    assert per_request <= 0.05 * request_p50, (
        f"serving plane adds {per_request * 1e6:.1f}µs/request against a "
        f"{request_p50 * 1e6:.1f}µs p50 "
        f"({per_request / request_p50:.1%} > 5%)")
