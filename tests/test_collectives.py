"""Collectives + multi-host helpers on the 8-virtual-device CPU mesh —
real SPMD semantics, not local[n] make-believe (SURVEY.md §4.2 note)."""

import numpy as np
import pytest

import jax

from predictionio_tpu.parallel.collectives import (
    all_gather_rows,
    all_reduce_sum,
    all_to_all_rows,
    reduce_scatter_rows,
    ring_exchange,
    ring_mapreduce_rows,
)
from predictionio_tpu.parallel.distributed import (
    make_global_array,
    parse_mesh_shape,
    process_row_range,
)
from predictionio_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    named_sharding,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh({DATA_AXIS: 8, MODEL_AXIS: 1})


@pytest.fixture(scope="module")
def mesh_model4():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        x = np.arange(16, dtype=np.float32)
        xs = jax.device_put(x, named_sharding(mesh8, DATA_AXIS))
        out = all_reduce_sum(mesh8, xs)
        # psum over shards of a [16] array sharded by 8: each shard [2]
        # sums elementwise with the others → [2] replicated
        expected = x.reshape(8, 2).sum(axis=0)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_all_gather_rows(self, mesh8):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        xs = jax.device_put(x, named_sharding(mesh8, DATA_AXIS, None))
        out = all_gather_rows(mesh8, xs)
        np.testing.assert_allclose(np.asarray(out), x)
        assert out.sharding.is_fully_replicated

    def test_reduce_scatter_rows(self, mesh8):
        x = np.ones((16, 4), dtype=np.float32)
        xr = jax.device_put(x, named_sharding(mesh8))  # replicated
        out = reduce_scatter_rows(mesh8, xr)
        # every device contributed the same [16,4]; psum_scatter sums the
        # 8 copies and leaves each device rows 2i..2i+1 → all values 8
        np.testing.assert_allclose(np.asarray(out), 8.0 * x)

    def test_all_to_all_rows_is_involution(self, mesh8):
        x = np.arange(64, dtype=np.float32).reshape(64, 1)
        xs = jax.device_put(x, named_sharding(mesh8, DATA_AXIS, None))
        once = all_to_all_rows(mesh8, xs)
        twice = all_to_all_rows(mesh8, once)
        # exchanging chunk (d, b) → (b, d) twice is the identity
        np.testing.assert_allclose(np.asarray(twice), x)
        assert not np.allclose(np.asarray(once), x)  # it did move data

    def test_ring_exchange_rotates_blocks(self, mesh_model4):
        x = np.repeat(np.arange(4, dtype=np.float32), 2).reshape(8, 1)
        xs = jax.device_put(x, named_sharding(mesh_model4, MODEL_AXIS, None))
        out = ring_exchange(mesh_model4, xs, MODEL_AXIS)
        # device d's block (value d) lands on device d+1 mod 4
        expected = np.repeat([3, 0, 1, 2], 2).astype(np.float32).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_ring_mapreduce_sums_all_blocks(self, mesh_model4):
        x = np.repeat(np.arange(4, dtype=np.float32), 2).reshape(8, 1)
        xs = jax.device_put(x, named_sharding(mesh_model4, MODEL_AXIS, None))
        out = ring_mapreduce_rows(
            mesh_model4, lambda block, i: block, xs, MODEL_AXIS)
        # every device sees every block once → each accumulates 0+1+2+3
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 6.0))


class TestDistributedHelpers:
    def test_parse_mesh_shape(self):
        assert parse_mesh_shape("data=16,model=4") == {"data": 16, "model": 4}
        assert parse_mesh_shape(" data=2 ") == {"data": 2}
        with pytest.raises(ValueError):
            parse_mesh_shape("data:16")
        with pytest.raises(ValueError):
            parse_mesh_shape("")

    def test_process_row_range_single_process(self):
        assert process_row_range(100) == (0, 100)

    def test_make_global_array_places_row_sharded(self, mesh8):
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        arr = make_global_array(mesh8, x)
        np.testing.assert_allclose(np.asarray(arr), x)
        # row-sharded over 8 devices → each shard holds 2 rows
        shard_shapes = {s.data.shape for s in arr.addressable_shards}
        assert shard_shapes == {(2, 2)}
