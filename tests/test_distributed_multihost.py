"""Multi-host control plane e2e: 2 real processes × 4 CPU devices each
federate into one 8-device world via `jax.distributed` and assemble a
correct global sharded array — the TPU-native replacement for the
reference's Spark driver↔executor bootstrap (SURVEY.md §2.7). Runs the
same `PIO_COORDINATOR_ADDRESS`/`PIO_NUM_PROCESSES`/`PIO_PROCESS_ID`
contract `pio train` uses on a real pod."""

import json
import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["PIO_TEST_REPO"])
    import numpy as np
    from predictionio_tpu.parallel import distributed

    # PIO_JAX_PLATFORM=cpu in the env exercises the platform override
    # inside initialize_from_env (the production path on CPU-only hosts)
    assert distributed.initialize_from_env()
    import jax
    import jax.numpy as jnp

    mesh = distributed.global_mesh()
    lo, hi = distributed.process_row_range(16)
    local = (np.arange(lo, hi, dtype=np.float32).reshape(-1, 1)
             * np.ones((1, 4), np.float32))
    garr = distributed.make_global_array(mesh, local)
    total = float(jax.jit(jnp.sum)(garr))
    out = {
        "pid": jax.process_index(),
        "devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "sum": total,
        "rows": [int(lo), int(hi)],
        "mesh": dict(mesh.shape),
    }
    with open(os.environ["PIO_TEST_OUT"], "w") as f:
        json.dump(out, f)
""")


@pytest.mark.e2e
def test_two_process_global_mesh(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PIO_CONF_DIR", None)
        env.update(
            PIO_JAX_PLATFORM="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES="2",
            PIO_PROCESS_ID=str(pid),
            PIO_TEST_REPO=str(REPO),
            PIO_TEST_OUT=str(tmp_path / f"out{pid}.json"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o

    results = [json.loads((tmp_path / f"out{i}.json").read_text())
               for i in range(2)]
    expected_sum = float(sum(range(16)) * 4)
    for pid, r in enumerate(results):
        assert r["pid"] == pid
        assert r["devices"] == 8 and r["local_devices"] == 4
        assert r["sum"] == expected_sum  # every rank sees the global sum
        assert r["mesh"] == {"data": 8, "model": 1}
    # the two ranks fed disjoint halves of the global rows
    assert results[0]["rows"] == [0, 8] and results[1]["rows"] == [8, 16]



TRAIN_ENV_KEYS = dict(
    PIO_STORAGE_REPOSITORIES_METADATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE="SQL",
    PIO_STORAGE_SOURCES_SQL_TYPE="sqlite",
)


def _seed_ratings(db, app_name, n_events, n_users, n_items, seed):
    """App + random rate events straight through the storage layer."""
    import numpy as np

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.events import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.sqlite import SQLiteBackend

    backend = SQLiteBackend(str(db))
    app_id = backend.apps().insert(App(id=0, name=app_name))
    rng = np.random.default_rng(seed)
    backend.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=str(u),
               target_entity_type="item", target_entity_id=str(i),
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, n_users, n_events),
                            rng.integers(0, n_items, n_events),
                            rng.integers(1, 6, n_events))],
        app_id=app_id)
    backend.close()


def _write_engine_json(path, app_name, engine_id, rank, iters):
    path.write_text(json.dumps({
        "id": engine_id, "engineFactory":
            "predictionio_tpu.templates.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": {
            "rank": rank, "numIterations": iters, "lambda": 0.05,
            "seed": 1}}],
    }))


def _run_two_rank_train(engine_json, db, basedir, extra_env=None):
    """Launch TWO `bin/pio train` ranks federated via PIO_COORDINATOR_*;
    returns their outputs after asserting both exited 0. THE pod-contract
    harness — tests state only what differs (e.g. the MODELDATA source)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PIO_CONF_DIR", None)
        env.update(
            TRAIN_ENV_KEYS,
            PIO_STORAGE_SOURCES_SQL_PATH=str(db),
            PIO_FS_BASEDIR=str(basedir),
            PIO_JAX_PLATFORM="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES="2",
            PIO_PROCESS_ID=str(pid),
            PYTHONPATH=f"{REPO}{os.pathsep}" + os.environ.get("PYTHONPATH", ""),
        )
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [str(REPO / "bin" / "pio"), "train",
             "--engine-json", str(engine_json)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    return outs


@pytest.mark.e2e
def test_two_process_pio_train_cli(tmp_path):
    """The real pod contract end-to-end: TWO `bin/pio train` processes
    federate via PIO_COORDINATOR_* into one 8-device world over a shared
    file store; every rank trains (collectives need all of them), rank 0
    alone persists the model + COMPLETED instance, and the persisted
    model loads and answers a query."""
    import sqlite3

    db = tmp_path / "pio.db"
    _seed_ratings(db, "MHApp", 3000, 48, 32, seed=3)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "MHApp", "mh", rank=8, iters=3)

    outs = _run_two_rank_train(engine_json, db, tmp_path)

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT id FROM engine_instances WHERE status='COMPLETED'"
    ).fetchall()
    assert len(completed) == 1  # rank 0 only — no duplicate instances
    models = conn.execute("SELECT count(*) FROM models").fetchone()[0]
    assert models == 1
    conn.close()
    # rank 0 reported the REAL persisted instance id (rank 1 prints a
    # worker placeholder)
    assert f"Engine instance ID: {completed[0][0]}" in outs[0]

    # the persisted model must load and answer a query (single process)
    from predictionio_tpu.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )
    from predictionio_tpu.workflow.workflow_utils import (
        EngineVariant, extract_engine_params, get_engine,
    )

    src = SourceConfig(name="SQL", type="sqlite", path=str(db))
    storage = Storage(StorageConfig(metadata=src, modeldata=src,
                                    eventdata=src))
    try:
        variant = EngineVariant.from_dict(json.loads(engine_json.read_text()))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        blob = storage.model_data_models().get(completed[0][0]).models
        models_obj = engine.deserialize_models(blob, completed[0][0], ep)
        r = engine.predict(ep, models_obj, {"user": "1", "num": 3})
        # seen-item exclusion may leave fewer than `num` candidates; the
        # claim is that the persisted model answers, not the exact count
        assert 1 <= len(r["itemScores"]) <= 3
    finally:
        storage.close()


@pytest.mark.e2e
def test_two_process_train_persists_to_object_store(tmp_path):
    """Multi-host deployments without a shared filesystem point MODELDATA
    at the s3 source (docs/operations.md); rank 0's model blob must land
    in the object store and load back."""
    import sqlite3

    from predictionio_tpu.storage.objectstore import S3Client
    from predictionio_tpu.storage.objectstore_server import ObjectStoreServer

    srv = ObjectStoreServer(str(tmp_path / "objects")).start()
    try:
        db = tmp_path / "pio.db"
        _seed_ratings(db, "MHS3App", 1500, 32, 24, seed=5)
        engine_json = tmp_path / "engine.json"
        _write_engine_json(engine_json, "MHS3App", "mhs3", rank=6, iters=2)

        _run_two_rank_train(engine_json, db, tmp_path, extra_env={
            "PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
            "PIO_STORAGE_SOURCES_OBJ_PATH":
                f"s3://pio/models?endpoint=http://127.0.0.1:{srv.port}",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ",
        })

        conn = sqlite3.connect(db)
        (instance_id,) = conn.execute(
            "SELECT id FROM engine_instances WHERE status='COMPLETED'"
        ).fetchone()
        conn.close()
        # exactly one model object, named by the instance, fetchable
        blobs = os.listdir(tmp_path / "objects" / "pio" / "models")
        assert blobs == [f"{instance_id}.model"]
        data = S3Client(f"http://127.0.0.1:{srv.port}", "pio").get_object(
            f"models/{instance_id}.model")
        assert data and len(data) > 1000
    finally:
        srv.shutdown()
