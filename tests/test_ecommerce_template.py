"""E-Commerce template end-to-end: view/buy events + $set categories +
constraint/unavailableItems → implicit ALS → filtered recommendations with
serve-time LEventStore lookups (SURVEY.md §2.4 E-Commerce row; §3.2
`ECommAlgorithm.predict → LEventStore.findByEntity`)."""

from datetime import datetime, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.ecommerce.ECommerceEngine"
APP = "EcomApp"


def ts(h):
    return datetime(2026, 1, 1, h, tzinfo=timezone.utc)


def ingest(storage, n_users=12, n_groups=2, items_per_group=4):
    """Group structure like the similar-product fixture, plus buys."""
    app_id = storage.meta_apps().insert(App(id=0, name=APP))
    le = storage.l_events()
    for g in range(n_groups):
        for j in range(items_per_group):
            le.insert(
                Event(event="$set", entity_type="item", entity_id=f"g{g}i{j}",
                      properties=DataMap({"categories": [f"cat{g}"]}),
                      event_time=ts(0)),
                app_id)
    for u in range(n_users):
        g = u % n_groups
        holdout = u % items_per_group
        for j in range(items_per_group):
            if j == holdout:
                continue
            le.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"g{g}i{j}",
                      event_time=ts(1)),
                app_id)
        # one buy to weight the strongest item
        le.insert(
            Event(event="buy", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"g{g}i{(holdout + 1) % items_per_group}",
                  event_time=ts(2)),
            app_id)
    return app_id


def variant_dict(algo_overrides=None):
    params = {
        "appName": APP, "rank": 4, "numIterations": 15, "lambda": 0.05,
        "alpha": 2.0, "seed": 1, "cacheTTLSeconds": 0.0,
    }
    params.update(algo_overrides or {})
    return {
        "id": "ecom-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": APP}},
        "algorithms": [{"name": "ecomm", "params": params}],
    }


def trained(memory_storage, algo_overrides=None):
    variant = EngineVariant.from_dict(variant_dict(algo_overrides))
    engine = get_engine(variant.engine_factory)
    ep = extract_engine_params(engine, variant)
    ctx = WorkflowContext(storage=memory_storage, seed=1)
    models = engine.train(ctx, ep)
    return engine, ep, models


class TestECommerceEndToEnd:
    def test_unseen_only_excludes_seen_items(self, memory_storage):
        ingest(memory_storage)
        engine, ep, models = trained(memory_storage)
        r = engine.predict(ep, models, {"user": "u0", "num": 10})
        items = [s["item"] for s in r["itemScores"]]
        assert items, "expected recommendations"
        # u0 (group 0, holdout item g0i0) has seen g0i1..3 and bought g0i1
        seen = {"g0i1", "g0i2", "g0i3"}
        assert not (set(items) & seen)
        assert "g0i0" in items  # the held-out item is recommendable

    def test_unavailable_items_filtered_and_constraint_updates(
        self, memory_storage
    ):
        app_id = ingest(memory_storage)
        engine, ep, models = trained(memory_storage)
        le = memory_storage.l_events()
        le.insert(
            Event(event="$set", entity_type="constraint",
                  entity_id="unavailableItems",
                  properties=DataMap({"items": ["g0i0"]}), event_time=ts(3)),
            app_id)
        r = engine.predict(ep, models, {"user": "u0", "num": 10})
        assert "g0i0" not in [s["item"] for s in r["itemScores"]]
        # a newer constraint replaces the old one (findByEntity latest=True)
        le.insert(
            Event(event="$set", entity_type="constraint",
                  entity_id="unavailableItems",
                  properties=DataMap({"items": []}), event_time=ts(4)),
            app_id)
        r = engine.predict(ep, models, {"user": "u0", "num": 10})
        assert "g0i0" in [s["item"] for s in r["itemScores"]]

    def test_cold_start_scores_via_recent_views(self, memory_storage):
        app_id = ingest(memory_storage)
        engine, ep, models = trained(memory_storage)
        # "fresh" user unknown to the model, with post-train view events
        le = memory_storage.l_events()
        le.insert(
            Event(event="view", entity_type="user", entity_id="fresh",
                  target_entity_type="item", target_entity_id="g1i0",
                  event_time=ts(5)),
            app_id)
        r = engine.predict(ep, models, {"user": "fresh", "num": 2})
        items = [s["item"] for s in r["itemScores"]]
        assert items
        # recommendations should come from the co-viewed group 1
        assert set(items) <= {f"g1i{j}" for j in range(4)}
        assert "g1i0" not in items  # viewed → seen-filtered

    def test_unknown_user_no_history_empty(self, memory_storage):
        ingest(memory_storage)
        engine, ep, models = trained(memory_storage)
        r = engine.predict(ep, models, {"user": "ghost", "num": 3})
        assert r == {"itemScores": []}

    def test_category_and_whitelist_filters(self, memory_storage):
        ingest(memory_storage)
        engine, ep, models = trained(memory_storage, {"unseenOnly": False})
        r = engine.predict(ep, models, {
            "user": "u0", "num": 10, "categories": ["cat1"]})
        got = {s["item"] for s in r["itemScores"]}
        assert got and got <= {f"g1i{j}" for j in range(4)}
        r = engine.predict(ep, models, {
            "user": "u0", "num": 10, "whiteList": ["g0i1"]})
        assert [s["item"] for s in r["itemScores"]] == ["g0i1"]
        r = engine.predict(ep, models, {
            "user": "u0", "num": 10, "blackList": ["g0i1"],
            "categories": ["cat0"]})
        assert "g0i1" not in {s["item"] for s in r["itemScores"]}

    def test_ttl_cache_serves_stale_within_ttl(self, memory_storage):
        """The deploy path resolves components ONCE (Engine.predict docstring)
        so the algorithm instance — and its TTL cache — persists across
        queries; within the TTL a new constraint event is not yet visible."""
        app_id = ingest(memory_storage)
        engine, ep, models = trained(
            memory_storage, {"cacheTTLSeconds": 60.0})
        comps = engine.components(ep)
        r = engine.predict(ep, models, {"user": "u0", "num": 10},
                           components=comps)
        assert "g0i0" in [s["item"] for s in r["itemScores"]]
        # constraint lands but the cached (empty) unavailable set is used
        memory_storage.l_events().insert(
            Event(event="$set", entity_type="constraint",
                  entity_id="unavailableItems",
                  properties=DataMap({"items": ["g0i0"]}), event_time=ts(3)),
            app_id)
        r = engine.predict(ep, models, {"user": "u0", "num": 10},
                           components=comps)
        assert "g0i0" in [s["item"] for s in r["itemScores"]]
        # a freshly resolved instance (empty cache) sees it immediately
        r = engine.predict(ep, models, {"user": "u0", "num": 10})
        assert "g0i0" not in [s["item"] for s in r["itemScores"]]

    def test_model_roundtrips_through_persistence(self, memory_storage):
        ingest(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"
        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        r = engine.predict(ep, models, {"user": "u0", "num": 3})
        assert r["itemScores"]

    def test_template_engine_json_parses(self):
        import os

        from predictionio_tpu.workflow.workflow_utils import read_engine_json

        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "ecommerce", "engine.json")
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        name, params = ep.algorithm_params_list[0]
        assert name == "ecomm"
        assert params.seenEvents == ["buy", "view"]
        assert params.unseenOnly is True


class TestECommerceCheckpoint:
    """Round 5: `ctx.checkpoint_dir` plumbs into this template's
    `als_train` (SURVEY.md §5 checkpoint/resume for every ALS template)."""

    def test_interrupted_resume_matches_uninterrupted(
            self, memory_storage, tmp_path, caplog):
        import logging

        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        ingest(memory_storage)
        _, _, want = trained(memory_storage, {"numIterations": 6})

        def ckpt_train(iters):
            variant = EngineVariant.from_dict(
                variant_dict({"numIterations": iters}))
            engine = get_engine(variant.engine_factory)
            ep = extract_engine_params(engine, variant)
            ctx = WorkflowContext(storage=memory_storage, seed=1,
                                  checkpoint_dir=str(tmp_path / "ck"),
                                  checkpoint_every=1)
            return engine.train(ctx, ep)[0]

        ckpt_train(3)  # the "interrupted" run
        cm = CheckpointManager(str(tmp_path / "ck" / "als"))
        assert cm.latest_step() == 3
        with caplog.at_level(logging.INFO):
            got = ckpt_train(6)
        assert any("resumed from checkpoint step 3" in r.getMessage()
                   for r in caplog.records)
        assert cm.latest_step() == 6
        np.testing.assert_allclose(got.user_factors, want[0].user_factors,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got.item_factors, want[0].item_factors,
                                   rtol=1e-4, atol=1e-5)
