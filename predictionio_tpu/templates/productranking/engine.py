"""Product Ranking engine template (DASE components).

Parity with the upstream gallery template
«template-scala-parallel-productranking» [U]: rank a GIVEN list of items
for a user (e.g. re-order a landing page or a search result) by the
user's predicted preference, instead of searching the whole catalog.

Reuses the Recommendation template's data path and ALS training wholesale
(same events, same `ops/als.py` mesh-sharded train); only serving
differs: the query names the candidate items, scores come from one tiny
host-side dot product, and — matching the upstream contract — when the
model cannot rank (unknown user) the original item order comes back with
`"isOriginal": true`. Items unknown to the model keep their incoming
relative order after the ranked ones, at score 0.

Wire shapes:
    query:  {"user": "u1", "items": ["i3", "i1", "i9"]}
    result: {"itemScores": [{"item": "i1", "score": 3.2}, ...],
             "isOriginal": false}
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from predictionio_tpu.controller import Engine, EngineFactory, FirstServing
from predictionio_tpu.models.als_model import ALSModel
from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm as _RecommendationALS,
    DataSource,
    DataSourceParams,
    Preparator,
    PreparedData,
    TrainingData,
)

Query = dict
PredictedResult = dict


class RankingALSAlgorithm(_RecommendationALS):
    """Recommendation's ALS train + ranking-specific serving."""

    @staticmethod
    def _rank(model: ALSModel, uvec: np.ndarray, items: list) -> list:
        # unknown items enter the ranking at score 0 (upstream contract),
        # NOT appended after known ones — an explicit-feedback model can
        # score disliked items negative, and the response must stay
        # score-descending (ties keep incoming order)
        scored = []
        for pos, item in enumerate(items):
            row = model.item_ids.get(item)
            score = (0.0 if row is None
                     else float(uvec @ model.item_factors[int(row)]))
            scored.append((score, pos, item))
        scored.sort(key=lambda t: (-t[0], t[1]))
        return [{"item": item, "score": s} for s, _, item in scored]

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        items = [str(i) for i in (query.get("items") or [])]
        user = str(query.get("user", ""))
        urow = model.user_ids.get(user)
        if urow is None or not items:
            # upstream contract: can't personalize → echo the original
            # order and say so
            return {"itemScores": [{"item": i, "score": 0.0}
                                   for i in items],
                    "isOriginal": True}
        return {"itemScores": self._rank(model, model.user_factors[int(urow)],
                                         items),
                "isOriginal": False}

    def batch_predict(self, model: ALSModel, queries) -> list[PredictedResult]:
        """Batched path for the serving micro-batcher (overrides the
        recommendation template's user-grouped top-k, which serves a
        different query shape). Scoring rides the same `_rank` ops per
        query — batched ≡ sequential bitwise by construction — and the
        batch win is resolving each hot user's factor row once per batch
        instead of once per co-batched request."""
        uvecs: dict[str, Optional[np.ndarray]] = {}
        out = []
        for q in queries:
            items = [str(i) for i in (q.get("items") or [])]
            user = str(q.get("user", ""))
            if user not in uvecs:
                urow = model.user_ids.get(user)
                uvecs[user] = (None if urow is None
                               else model.user_factors[int(urow)])
            uvec = uvecs[user]
            if uvec is None or not items:
                out.append({"itemScores": [{"item": i, "score": 0.0}
                                           for i in items],
                            "isOriginal": True})
            else:
                out.append({"itemScores": self._rank(model, uvec, items),
                            "isOriginal": False})
        return out


class ProductRankingEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            data_source_class_map=DataSource,
            preparator_class_map=Preparator,
            algorithm_class_map={"als": RankingALSAlgorithm},
            serving_class_map=FirstServing,
        )


__all__ = [
    "ProductRankingEngine",
    "RankingALSAlgorithm",
    "DataSource",
    "DataSourceParams",
    "Preparator",
    "PreparedData",
    "TrainingData",
    "Query",
]
