"""Rule pack (c): the jit shape-discipline rule.

Every distinct argument shape entering a jit boundary compiles a new
executable (~0.35 s on the serving path vs ~1 ms warm). The repo's
discipline: unbounded runtime sizes (``len(...)`` of store-fetched
data, ``.shape`` of a ragged batch) must pass through a tier/pad helper
(``foldin.py``'s power-of-4 capacity tiers + ``_pad_rows``, the
micro-batcher's bucket ladder) before they become a traced dimension.

The rule tracks, per module, which names are bound to jit-wrapped
callables —

    solve = metered_jit(_solve_rows, label="...")
    self._score = jax.jit(score_fn)
    @jax.jit / @partial(jax.jit, static_argnums=...) decorated defs

— and flags call sites of those callables where an argument expression
derives from ``len(...)`` or ``.shape`` and neither the argument nor
the enclosing function goes through a recognizable pad/tier/bucket
helper (any call whose name contains ``pad``, ``tier``, ``bucket``, or
``chunk``).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.engine import Finding, Project, rule

_JIT_FACTORIES = {"metered_jit", "jit", "pjit"}
_HELPER_MARKERS = ("pad", "tier", "bucket", "chunk")


def _is_jit_factory(call: ast.Call) -> bool:
    t = astutil.terminal_name(call)
    if t in _JIT_FACTORIES:
        return True
    # functools.partial(jax.jit, ...) / partial(metered_jit, ...)
    if t == "partial" and call.args:
        return astutil.terminal_name(call.args[0]) in _JIT_FACTORIES
    return False


def _jit_bound_names(tree: ast.AST) -> Set[str]:
    """Names (locals and self-attrs, by terminal name) bound to
    jit-wrapped callables, plus @jit-decorated function names."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_factory(node.value):
                for tgt in node.targets:
                    t = astutil.terminal_name(tgt)
                    if t:
                        names.add(t)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                t = astutil.terminal_name(d)
                if t in _JIT_FACTORIES:
                    names.add(node.name)
                elif (t == "partial" and isinstance(dec, ast.Call)
                      and dec.args
                      and astutil.terminal_name(
                          dec.args[0]) in _JIT_FACTORIES):
                    names.add(node.name)
    return names


def _unbounded_dim(arg: ast.AST) -> Optional[str]:
    """A description of the unbounded size the expression derives from,
    or None."""
    for n in ast.walk(arg):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return "len(...)"
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return ".shape"
    return None


def _has_helper_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            t = astutil.terminal_name(n)
            if t and any(m in t.lower() for m in _HELPER_MARKERS):
                return True
    return False


@rule("jit-shape-discipline",
      "arguments to jit-wrapped callables must not derive a traced "
      "dimension from unbounded runtime sizes without a pad/tier "
      "helper")
def jit_shape_discipline(project: Project) -> Iterable[Finding]:
    for mod in project.modules():
        if mod.tree is None:
            continue
        jit_names = _jit_bound_names(mod.tree)
        if not jit_names:
            continue
        for fn_name, fn in astutil.function_defs(mod.tree).items():
            fn_has_helper = _has_helper_call(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = astutil.terminal_name(node)
                if callee not in jit_names:
                    continue
                if callee == fn_name:
                    continue    # the jit'd fn's own (traced) body
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    dim = _unbounded_dim(arg)
                    if dim is None:
                        continue
                    if fn_has_helper or _has_helper_call(arg):
                        continue
                    yield Finding(
                        "jit-shape-discipline", mod.rel, node.lineno,
                        f"{fn_name}() passes a dimension derived from "
                        f"{dim} into jit-compiled {callee}() without a "
                        f"pad/tier helper — every new size retraces "
                        f"(~0.35 s) instead of hitting the compile "
                        f"cache",
                        symbol=f"{fn_name}->{callee}",
                        hint="round the size through a capacity tier / "
                             "bucket ladder (e.g. _pad_rows, "
                             "bucket_ragged) before the jit boundary")
                    break
