"""Columnar event batches — the device-feed form of an event scan.

This is the rebuild's answer to the reference's bulk read path
(«HBPEvents → TableInputFormat scan» → RDD, SURVEY.md §2.2 [U]): where
the reference hands Spark executors raw HBase regions, we hand the host
loader dense numpy columns with integer-coded entities, ready for
`jax.device_put` onto a sharded mesh axis. String→int coding happens in
the storage backend (SQL window functions — see
`storage/sqlite.py::SQLiteLEvents.find_columnar`), so no per-event
Python object is ever materialized on the 2M–20M-event training path.
"""

from __future__ import annotations

import dataclasses
from itertools import chain
from typing import Iterable, Optional, Sequence

import numpy as np

from predictionio_tpu.data.bimap import BiMap


@dataclasses.dataclass(frozen=True)
class EventColumns:
    """Columnar batch of events.

    `entity_ids`/`target_ids` are int32 codes via the attached BiMaps
    (target −1 when absent), `event_codes` int32 via `event_names`,
    `values` float32 (the chosen property, NaN when absent), `times`
    float64 unix seconds. All arrays share one length; rows keep
    (event_time, creation_time) order so downstream windowed ops (e.g.
    Markov chains) stay valid. BiMap codes follow the **sorted** order of
    the distinct id strings — deterministic across backends and re-runs.
    """

    entity_ids: np.ndarray
    target_ids: np.ndarray
    event_codes: np.ndarray
    values: np.ndarray
    times: np.ndarray
    entity_bimap: BiMap
    target_bimap: BiMap
    event_names: list[str]

    def __len__(self) -> int:
        return int(self.entity_ids.shape[0])


def columns_from_numeric_rows(
    rows: Sequence[tuple],
    entity_uniques: Iterable[str],
    target_uniques: Iterable[str],
    event_names: Sequence[str],
) -> EventColumns:
    """Assemble `EventColumns` from already-coded numeric rows.

    `rows` are `(entity_code, target_code, event_code, value, time)`
    tuples where a missing value is encoded as +inf (JSON cannot encode
    infinity, so the sentinel cannot collide with real property values)
    and a missing target is −1. One flat `np.fromiter` pass keeps the
    Python-per-row cost to tuple iteration only.
    """
    n = len(rows)
    if n:
        flat = np.fromiter(
            chain.from_iterable(rows), dtype=np.float64, count=5 * n
        ).reshape(n, 5)
    else:
        flat = np.empty((0, 5), dtype=np.float64)
    values = flat[:, 3].astype(np.float32)
    values[np.isinf(values)] = np.nan
    return EventColumns(
        entity_ids=flat[:, 0].astype(np.int32),
        target_ids=flat[:, 1].astype(np.int32),
        event_codes=flat[:, 2].astype(np.int32),
        values=values,
        times=flat[:, 4].copy(),
        entity_bimap=BiMap.string_int(entity_uniques),
        target_bimap=BiMap.string_int(target_uniques),
        event_names=list(event_names),
    )


SPECIAL_EVENTS = ("$set", "$unset", "$delete")


def numeric_or_none(v) -> Optional[float]:
    """Canonical value-property coercion for columnar scans: numbers and
    bools pass through, numeric strings parse, everything else (None,
    non-numeric text, containers) is missing. Matches the SQL tier's
    json_type-gated CAST and the native reader's strtod within the
    canonical value space (numbers / numeric strings / bools); exotic
    corner cases like '3abc' are backend-defined prefix-vs-reject."""
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    return None


def columns_from_events(
    events,
    event_names: Optional[list] = None,
    value_key: Optional[str] = None,
    ordered: bool = True,
) -> EventColumns:
    """Fold already-materialized `Event` objects into `EventColumns` —
    the generic tier every backend (and the batch view's cached-snapshot
    path) shares. Output contract matches the pushed-down scans: sorted
    BiMap codes, (event_time, creation_time, id) row order when
    `ordered` — the unique id as final tiebreak, matching the SQL and
    C++ tiers' ORDER BYs, so exact-timestamp ties resolve identically
    in every tier."""
    events = list(events)
    if ordered:
        events.sort(key=lambda e: (e.event_time, e.creation_time,
                                   e.event_id or ""))
    if event_names is None:
        event_names = sorted(
            {e.event for e in events if e.event not in SPECIAL_EVENTS})
    if not event_names:
        return columns_from_numeric_rows([], [], [], [])
    wanted = set(event_names)
    events = [e for e in events if e.event in wanted]
    code_of = {name: i for i, name in enumerate(event_names)}
    entity_uniques = sorted({e.entity_id for e in events})
    target_uniques = sorted(
        {e.target_entity_id for e in events
         if e.target_entity_id is not None})
    e_code = {s: i for i, s in enumerate(entity_uniques)}
    t_code = {s: i for i, s in enumerate(target_uniques)}
    inf = float("inf")
    rows = []
    for e in events:
        v = (numeric_or_none(e.properties.get_opt(value_key))
             if value_key else None)
        rows.append((
            e_code[e.entity_id],
            (t_code[e.target_entity_id]
             if e.target_entity_id is not None else -1),
            code_of[e.event],
            inf if v is None else v,
            e.event_time.timestamp(),
        ))
    return columns_from_numeric_rows(
        rows, entity_uniques, target_uniques, event_names)
