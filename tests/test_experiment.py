"""Experimentation plane (round 8): sticky splits, the Thompson bandit,
$reward through the ingest funnel, variant-scoped result caching, and
two live arms behind one /queries.json.

The unit half pins the routing math (deterministic digest, posterior
updates, config resolution) and the funnel contract ($reward validation,
SDK verb, variant-scoped invalidation). The e2e half deploys a real
two-variant PredictionServer in-process and asserts the contracts the
drills in experiment/gate.py enforce operationally: sticky receipts over
HTTP, both arms reachable, bandit routing fed by tailed rewards, and a
mid-traffic hot swap answering nothing but 200s."""

import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from datetime import datetime, timedelta, timezone

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import (
    Event,
    EventValidationError,
    validate_event,
)
from predictionio_tpu.experiment import ExperimentConfig, RewardTailer
from predictionio_tpu.experiment.bandit import (
    ThompsonBandit,
    sticky_buckets,
    sticky_variant,
)
from predictionio_tpu.ingest.invalidation import BUS, InvalidationBus
from predictionio_tpu.serving.result_cache import MISS, ResultCache
from predictionio_tpu.workflow.create_server import (
    PredictionServer,
    ServerConfig,
)
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)
from tests.test_recommendation_template import ingest_ratings, variant_dict

USERS = [f"u{i}" for i in range(200)]


def train_variant(storage, variant_name=None, iters=10, seed=1):
    """Train one servable arm of the rec-test engine. `variant_name`
    None trains the default arm; a name trains a second arm under the
    SAME engine id (the experiment deployment shape)."""
    from predictionio_tpu.controller import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    d = variant_dict(iters=iters)
    d["algorithms"][0]["params"]["seed"] = seed
    if variant_name is not None:
        d["variant"] = variant_name
    variant = EngineVariant.from_dict(d)
    engine = get_engine(variant.engine_factory)
    ep = extract_engine_params(engine, variant)
    return CoreWorkflow.run_train(engine, ep, variant,
                                  WorkflowContext(storage=storage, seed=1))


class TestStickyAssignment:
    def test_deterministic_and_order_independent(self):
        first = {u: sticky_variant(u, ["champ", "challenger"])
                 for u in USERS}
        again = {u: sticky_variant(u, ["challenger", "champ"])
                 for u in USERS}
        assert first == again  # declaration order must not matter
        assert set(first.values()) == {"champ", "challenger"}

    def test_weights_shift_the_split(self):
        heavy = [sticky_variant(u, ["a", "b"], [0.9, 0.1]) for u in USERS]
        share_a = heavy.count("a") / len(heavy)
        assert share_a > 0.75, f"0.9 weight got share {share_a}"
        # all-to-one pinning (the bench's router-isolation trick)
        assert {sticky_variant(u, ["a", "b"], [1, 0]) for u in USERS} == {"a"}

    def test_weight_validation(self):
        with pytest.raises(ValueError, match="weights"):
            sticky_buckets(["a", "b"], [1.0])
        with pytest.raises(ValueError, match="positive"):
            sticky_buckets(["a", "b"], [0.0, 0.0])
        with pytest.raises(ValueError, match="at least one"):
            sticky_buckets([])

    def test_mapping_identical_across_interpreters(self):
        """The property builtin hash() would break: a restarted worker
        (fresh PYTHONHASHSEED) must assign every user the same arm."""
        prog = ("import json, sys; "
                "from predictionio_tpu.experiment.bandit import "
                "sticky_variant; "
                "print(json.dumps({u: sticky_variant(u, ['champ', "
                "'challenger'], [0.7, 0.3]) for u in "
                "[f'u{i}' for i in range(64)]}))")
        outs = []
        for hashseed in ("0", "31337"):
            p = subprocess.run(
                [sys.executable, "-c", prog], text=True, capture_output=True,
                env={"PYTHONHASHSEED": hashseed, "JAX_PLATFORMS": "cpu",
                     "PATH": "/usr/bin:/bin",
                     "PYTHONPATH": ":".join(sys.path)},
                timeout=120)
            assert p.returncode == 0, p.stderr
            outs.append(json.loads(p.stdout))
        assert outs[0] == outs[1]
        # and both match this process's mapping
        assert outs[0] == {u: sticky_variant(u, ["champ", "challenger"],
                                             [0.7, 0.3])
                           for u in [f"u{i}" for i in range(64)]}


class TestThompsonBandit:
    def test_posterior_updates(self):
        b = ThompsonBandit(["a", "b"])
        assert b.posterior_mean("a") == 0.5  # Beta(1, 1) prior
        assert b.reward("a", 1.0)
        assert b.reward("a", 0.25)  # fractional update: α += r, β += 1−r
        snap = b.snapshot()["a"]
        assert snap["alpha"] == pytest.approx(2.25)
        assert snap["beta"] == pytest.approx(1.75)
        assert snap["rewards"] == 2
        assert b.reward("a", 7.0)  # clamped to [0, 1]
        assert b.snapshot()["a"]["alpha"] == pytest.approx(3.25)

    def test_unknown_variant_is_a_noop(self):
        b = ThompsonBandit(["a"])
        assert not b.reward("retired-arm", 1.0)
        assert b.posterior_mean("a") == 0.5

    def test_converges_to_better_arm(self):
        b = ThompsonBandit(["good", "bad"], seed=99)
        import random
        rng = random.Random(7)
        window = []
        for _ in range(600):
            v = b.choose()
            window.append(v)
            p = 0.9 if v == "good" else 0.1
            b.reward(v, 1.0 if rng.random() < p else 0.0)
        share = window[-200:].count("good") / 200
        assert share >= 0.8, f"bandit split only {share} to the better arm"


class TestExperimentConfig:
    def test_off_when_unset_or_single(self, monkeypatch):
        monkeypatch.delenv("PIO_EXPERIMENT_VARIANTS", raising=False)
        assert ExperimentConfig.from_env() is None
        monkeypatch.setenv("PIO_EXPERIMENT_VARIANTS", "only-one")
        assert ExperimentConfig.from_env() is None

    def test_env_roundtrip(self, monkeypatch):
        monkeypatch.setenv("PIO_EXPERIMENT_VARIANTS", "champ, challenger")
        monkeypatch.setenv("PIO_EXPERIMENT_MODE", "bandit")
        monkeypatch.setenv("PIO_EXPERIMENT_SEED", "42")
        monkeypatch.setenv("PIO_EXPERIMENT_APP_ID", "3")
        cfg = ExperimentConfig.from_env()
        assert cfg.variants == ("champ", "challenger")
        assert cfg.mode == "bandit" and cfg.seed == 42 and cfg.app_id == 3

    def test_bad_configs_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="mode"):
            ExperimentConfig(variants=("a", "b"), mode="roulette")
        with pytest.raises(ValueError, match="duplicate"):
            ExperimentConfig(variants=("a", "a"))
        monkeypatch.setenv("PIO_EXPERIMENT_VARIANTS", "a,b")
        monkeypatch.setenv("PIO_EXPERIMENT_WEIGHTS", "1.0")
        with pytest.raises(ValueError, match="WEIGHTS"):
            ExperimentConfig.from_env()


class TestRewardValidation:
    def mk(self, props):
        return Event(event="$reward", entity_type="user", entity_id="u1",
                     properties=DataMap(props))

    def test_well_formed_ok(self):
        validate_event(self.mk({"variant": "champ", "reward": 0.5}))
        validate_event(self.mk({"variant": "champ", "reward": 1}))

    def test_missing_or_bad_fields_rejected(self):
        for props in ({"reward": 0.5},                 # no variant
                      {"variant": "", "reward": 0.5},  # empty variant
                      {"variant": "c"},                # no reward
                      {"variant": "c", "reward": "hi"},
                      {"variant": "c", "reward": True},
                      {"variant": "c", "reward": 1.5},
                      {"variant": "c", "reward": -0.1}):
            with pytest.raises(EventValidationError):
                validate_event(self.mk(props))


@pytest.fixture()
def event_client(memory_storage):
    from predictionio_tpu.data.api import EventServer, EventServerConfig
    from predictionio_tpu.sdk import EventClient
    from predictionio_tpu.storage.base import AccessKey, App

    app_id = memory_storage.meta_apps().insert(App(id=0, name="ExpApp"))
    key = AccessKey.generate(app_id)
    memory_storage.meta_access_keys().insert(key)
    srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0),
                      memory_storage)
    srv.start()
    yield EventClient(access_key=key.key,
                      url=f"http://127.0.0.1:{srv.port}"), memory_storage, app_id
    srv.shutdown()


class TestRewardFunnel:
    def test_sdk_create_reward_roundtrip(self, event_client):
        client, storage, app_id = event_client
        eid = client.create_reward("u7", "challenger", 0.75)
        got = client.get_event(eid)
        assert got["event"] == "$reward" and got["entityId"] == "u7"
        assert got["properties"] == {"variant": "challenger", "reward": 0.75}
        # a caller-pinned id is the idempotency key: replaying it is a
        # DETECTED duplicate (the first send committed), not a new row
        from predictionio_tpu.sdk import PredictionIOError
        with pytest.raises(PredictionIOError, match="duplicate eventId"):
            client.create_reward("u7", "challenger", 0.75, event_id=eid)
        assert len(client.find_events(event="$reward")) == 1

    def test_sdk_create_reward_validates_server_side(self, event_client):
        from predictionio_tpu.sdk import PredictionIOError

        client, _, _ = event_client
        with pytest.raises(PredictionIOError, match="reward"):
            client.create_reward("u7", "challenger", 1.5)

    def test_reward_publishes_variant_scoped_invalidation(self, event_client):
        """$reward credits ONE arm, so its commit notification must be
        variant-scoped (other arms' cached answers were untouched);
        a plain data event stays unscoped (any arm may depend on it)."""
        client, _, _ = event_client
        calls = []

        def recorder(entity_ids, variant=None):
            calls.append((sorted(entity_ids), variant))

        BUS.subscribe(recorder)
        try:
            client.create_reward("u9", "challenger", 1.0)
            client.create_event(event="rate", entity_type="user",
                                entity_id="u9", target_entity_type="item",
                                target_entity_id="i1",
                                properties={"rating": 4})
        finally:
            BUS.unsubscribe(recorder)
        assert (["u9"], "challenger") in calls
        assert (["u9"], None) in calls

    def test_bus_serves_variant_blind_subscribers(self):
        """Pre-variant one-argument subscribers keep working: the bus
        detects the arity at subscribe time."""
        bus = InvalidationBus()
        old_style, new_style = [], []
        bus.subscribe(lambda ids: old_style.append(list(ids)))
        bus.subscribe(lambda ids, variant: new_style.append(
            (list(ids), variant)))
        bus.publish(["e1"], variant="champ")
        bus.publish(["e2"])
        assert old_style == [["e1"], ["e2"]]
        assert new_style == [(["e1"], "champ"), (["e2"], None)]


class TestResultCacheVariantIsolation:
    def test_variants_never_share_entries(self):
        cache = ResultCache(max_entries=16, ttl_s=60)
        q = {"user": "u1", "num": 3}
        cache.put(q, {"from": "a"}, variant="a")
        assert cache.get(q, variant="a") == {"from": "a"}
        assert cache.get(q, variant="b") is MISS
        cache.put(q, {"from": "b"}, variant="b")
        assert cache.get(q, variant="a") == {"from": "a"}  # b's put, a's key

    def test_invalidate_variant_drops_exactly_one_arm(self):
        cache = ResultCache(max_entries=16, ttl_s=60)
        q = {"user": "u1", "num": 3}
        cache.put(q, "A", variant="a")
        cache.put(q, "B", variant="b")
        cache.invalidate_variant("a")
        assert cache.get(q, variant="a") is MISS
        assert cache.get(q, variant="b") == "B"

    def test_variant_scoped_entity_invalidation(self):
        """The bus-message shape: a $reward for variant b must not cost
        variant a its cached answer for the same user."""
        cache = ResultCache(max_entries=16, ttl_s=60)
        q = {"user": "u1", "num": 3}
        cache.put(q, "A", variant="a")
        cache.put(q, "B", variant="b")
        cache.invalidate_entities(["u1"], variant="b")
        assert cache.get(q, variant="a") == "A"
        assert cache.get(q, variant="b") is MISS
        cache.invalidate_entities(["u1"])  # unscoped drops the rest
        assert cache.get(q, variant="a") is MISS


class TestRewardTailer:
    def _insert_reward(self, storage, app_id, user, variant, reward, t):
        storage.l_events().insert(
            Event(event="$reward", entity_type="user", entity_id=user,
                  properties=DataMap({"variant": variant, "reward": reward}),
                  event_time=t),
            app_id)

    def test_tail_applies_once_and_survives_junk(self, memory_storage):
        from predictionio_tpu.storage.base import App

        app_id = memory_storage.meta_apps().insert(App(id=0, name="TailApp"))
        bandit = ThompsonBandit(["a", "b"])
        tailer = RewardTailer(memory_storage, bandit, app_id=app_id)
        t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
        self._insert_reward(memory_storage, app_id, "u1", "a", 1.0, t0)
        self._insert_reward(memory_storage, app_id, "u2", "b", 0.0,
                            t0 + timedelta(seconds=1))
        # a hand-inserted malformed row must not wedge the loop
        self._insert_reward(memory_storage, app_id, "u3", "a", "junk",
                            t0 + timedelta(seconds=2))
        # a reward for an arm this deployment doesn't route is skipped
        self._insert_reward(memory_storage, app_id, "u4", "retired", 1.0,
                            t0 + timedelta(seconds=3))
        assert tailer.poll_once() == 2
        assert bandit.snapshot()["a"]["alpha"] == pytest.approx(2.0)
        assert bandit.snapshot()["b"]["beta"] == pytest.approx(2.0)
        # overlap re-reads must not double-apply
        assert tailer.poll_once() == 0
        assert bandit.snapshot()["a"]["alpha"] == pytest.approx(2.0)
        # only rows past the watermark apply on the next pass
        self._insert_reward(memory_storage, app_id, "u5", "b", 1.0,
                            t0 + timedelta(seconds=4))
        assert tailer.poll_once() == 1
        assert bandit.reward_count("b") == 2


def call(port, method, path, body=None):
    """HTTP helper that also returns headers (the variant receipt)."""
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"null"), \
                dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), dict(e.headers)


def start_two_variant_server(storage, mode="sticky", seed=None, app_id=1):
    config = ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                          engine_variant="rec-test")
    exp = ExperimentConfig(variants=("rec-test", "rec-test-b"), mode=mode,
                           seed=seed, app_id=app_id,
                           tail_interval_s=0.1)
    server = PredictionServer(config, storage, experiment=exp)
    server.start()
    return server


@pytest.fixture()
def two_variants(memory_storage):
    ingest_ratings(memory_storage)
    train_variant(memory_storage)                       # the champion
    train_variant(memory_storage, "rec-test-b", seed=2)  # the challenger
    return memory_storage


@pytest.mark.e2e
class TestTwoVariantServing:
    def test_sticky_receipts_cover_both_arms_and_stick(self, two_variants):
        server = start_two_variant_server(two_variants)
        try:
            seen = {}
            for u in range(64):
                for _ in range(2):  # the repeat must not move
                    status, body, headers = call(
                        server.port, "POST", "/queries.json",
                        {"user": f"u{u}", "num": 2})
                    assert status == 200 and "itemScores" in body
                    v = headers.get("X-PIO-Variant")
                    assert v in ("rec-test", "rec-test-b")
                    assert seen.setdefault(u, v) == v, f"user u{u} moved"
            assert set(seen.values()) == {"rec-test", "rec-test-b"}
            # ... and the mapping is the routing math, observed over HTTP
            for u, v in seen.items():
                assert sticky_variant(
                    f"u{u}", ["rec-test", "rec-test-b"]) == v
            # restartability: a FRESH server over the same store agrees
            server.shutdown()
            server = start_two_variant_server(two_variants)
            for u in (0, 7, 31, 63):
                _, _, headers = call(server.port, "POST", "/queries.json",
                                     {"user": f"u{u}", "num": 2})
                assert headers.get("X-PIO-Variant") == seen[u]
        finally:
            server.shutdown()

    def test_status_page_reports_experiment(self, two_variants):
        server = start_two_variant_server(two_variants)
        try:
            call(server.port, "POST", "/queries.json", {"user": "u0", "num": 2})
            status, body, _ = call(server.port, "GET", "/")
            assert status == 200
            exp = body["experiment"]
            assert exp["mode"] == "sticky"
            assert set(exp["instances"]) == {"rec-test", "rec-test-b"}
            assert exp["instances"]["rec-test"] != exp["instances"]["rec-test-b"]
        finally:
            server.shutdown()

    def test_bandit_routes_by_tailed_rewards(self, two_variants):
        t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
        server = start_two_variant_server(two_variants, mode="bandit",
                                          seed=1234)
        try:
            # durable rewards through the store: the challenger wins big
            le = two_variants.l_events()
            for i in range(40):
                le.insert(Event(event="$reward", entity_type="user",
                                entity_id=f"u{i}",
                                properties=DataMap({"variant": "rec-test-b",
                                                    "reward": 1.0}),
                                event_time=t0 + timedelta(seconds=i)), 1)
                le.insert(Event(event="$reward", entity_type="user",
                                entity_id=f"u{i}",
                                properties=DataMap({"variant": "rec-test",
                                                    "reward": 0.0}),
                                event_time=t0 + timedelta(seconds=i)), 1)
            assert server._tailer is not None
            server._tailer.poll_once()  # deterministic, no sleep-wait
            assert server.serving.bandit.posterior_mean("rec-test-b") > 0.9
            hits = []
            for i in range(100):
                _, _, headers = call(server.port, "POST", "/queries.json",
                                     {"user": f"u{i % 12}", "num": 2})
                hits.append(headers.get("X-PIO-Variant"))
            share = hits.count("rec-test-b") / len(hits)
            assert share >= 0.8, f"bandit sent only {share} to the winner"
        finally:
            server.shutdown()

    def test_hot_swap_mid_traffic_answers_only_200(self, two_variants):
        """The acceptance drill: retrain the challenger, /reload while 6
        clients hammer /queries.json — zero non-200, and the challenger
        ends up serving the NEW instance while the champion's stays."""
        server = start_two_variant_server(two_variants)
        try:
            _, before, _ = call(server.port, "GET", "/")
            old = before["experiment"]["instances"]
            new_b = train_variant(two_variants, "rec-test-b", iters=12,
                                  seed=3)
            stop = threading.Event()
            results = [{"n": 0, "bad": []} for _ in range(6)]

            def client(rec, i):
                while not stop.is_set():
                    status, _, headers = call(
                        server.port, "POST", "/queries.json",
                        {"user": f"u{i}", "num": 2})
                    if status != 200:
                        rec["bad"].append(status)
                    rec["n"] += 1

            threads = [threading.Thread(target=client, args=(rec, i))
                       for i, rec in enumerate(results)]
            for t in threads:
                t.start()
            try:
                status, _, _ = call(server.port, "POST", "/reload")
                assert status == 200
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            assert not any(r["bad"] for r in results), results
            assert all(r["n"] > 0 for r in results)
            _, after, _ = call(server.port, "GET", "/")
            now = after["experiment"]["instances"]
            assert now["rec-test-b"] == new_b.id != old["rec-test-b"]
        finally:
            server.shutdown()

    def test_traffic_share_and_snapshot(self, two_variants):
        server = start_two_variant_server(two_variants)
        try:
            for u in range(32):
                call(server.port, "POST", "/queries.json",
                     {"user": f"u{u}", "num": 2})
            shares = server.serving.traffic_share()
            assert set(shares) == {"rec-test", "rec-test-b"}
            assert sum(shares.values()) == pytest.approx(1.0)
            assert all(s > 0 for s in shares.values())
        finally:
            server.shutdown()
