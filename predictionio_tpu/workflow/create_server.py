"""CreateServer — the `pio deploy` prediction server.

Parity with «core/.../workflow/CreateServer.scala :: CreateServer,
MasterActor, ServerActor» (SURVEY.md §3.2 [U]): load the latest COMPLETED
EngineInstance, rebuild typed engine params from the stored instance row,
deserialize models, and serve:

    POST /queries.json  {"user": "1", "num": 4}  → PredictedResult JSON
    GET  /              → status page (engine info, instance id)
    POST /reload        → hot-swap to the newest COMPLETED instance
    POST /stop          → shut the server down

The reference supervises ServerActor with a MasterActor and hot-reloads on
re-deploy; here the served state is one immutable tuple swapped atomically
on /reload, and components are resolved once per load (not per query — the
query path is reflection-free).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Optional

from predictionio_tpu.experiment import (
    ExperimentConfig,
    RewardTailer,
    VariantRouter,
)
from predictionio_tpu.online import OnlineConfig, OnlinePlane
from predictionio_tpu.plugins import PluginRejection
from predictionio_tpu.serving import (
    DeadlineExceeded,
    ServingConfig,
    ServingPlane,
    ShedLoad,
)
from predictionio_tpu.telemetry import spans
from predictionio_tpu.telemetry.registry import REGISTRY
from predictionio_tpu.utils import fastjson
from predictionio_tpu.utils.faults import FaultInjected
from predictionio_tpu.utils.http import HttpService
from predictionio_tpu.utils.routing import Request, Response, Router

from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

log = logging.getLogger(__name__)

# The query hot path, separated from the HTTP envelope so engine time is
# distinguishable from request parsing/serialization in one scrape.
PREDICT_SECONDS = REGISTRY.histogram(
    "engine_predict_seconds",
    "Engine predict dispatch latency in seconds (one observation per "
    "batched dispatch; serving_batch_size gives queries per dispatch)")
QUERIES_FAILED = REGISTRY.counter(
    "engine_queries_failed_total", "Queries answered with a non-200 status")


class ServerConfig:
    def __init__(
        self,
        ip: str = "0.0.0.0",
        port: int = 8000,
        engine_id: str = "default",
        engine_version: str = "1",
        engine_variant: str = "default",
    ):
        self.ip = ip
        self.port = port
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant


class _ServedState:
    """Everything needed to answer queries — swapped atomically on reload."""

    def __init__(self, engine, engine_params, components, models,
                 instance: EngineInstance):
        self.engine = engine
        self.engine_params = engine_params
        self.components = components
        self.models = models
        self.instance = instance


def _row_block(raw: Optional[str]) -> dict:
    """Decode a stored component block. Rows written since round 5 carry
    `{"name": ..., "params": {...}}` (the component name must survive
    the round trip — see workflow_utils.engine_params_to_json); older
    rows stored the bare params dict, still decoded as an unnamed
    block."""
    d = json.loads(raw or "{}")
    if isinstance(d, dict) and "params" in d and set(d) <= {"name", "params"}:
        return d
    return {"params": d}


def variant_from_instance(instance: EngineInstance) -> EngineVariant:
    """Rebuild an EngineVariant from the params JSON stored on the
    EngineInstance row (`pio deploy` reads the row, not engine.json —
    SURVEY.md §3.2)."""
    return EngineVariant.from_dict({
        "id": instance.engine_id,
        "variant": instance.engine_variant,
        "engineFactory": instance.engine_factory,
        "datasource": _row_block(instance.data_source_params),
        "preparator": _row_block(instance.preparator_params),
        "algorithms": json.loads(instance.algorithms_params or "[]") or [{}],
        "serving": _row_block(instance.serving_params),
    })


def load_served_state(
    storage: Storage, config: ServerConfig
) -> _ServedState:
    instances = storage.meta_engine_instances()
    instance = instances.get_latest_completed(
        config.engine_id, config.engine_version, config.engine_variant
    )
    if instance is None:
        raise RuntimeError(
            f"No completed engine instance found for engine "
            f"{config.engine_id!r} v{config.engine_version} "
            f"variant {config.engine_variant!r}. Run `pio-tpu train` first."
        )
    variant = variant_from_instance(instance)
    engine = get_engine(variant.engine_factory)
    engine_params = extract_engine_params(engine, variant)
    blob = storage.model_data_models().get(instance.id)
    if blob is None:
        raise RuntimeError(f"Model blob for instance {instance.id} is missing.")
    models = engine.deserialize_models(blob.models, instance.id, engine_params)
    components = engine.components(engine_params)
    log.info("Deployed engine instance %s (trained %s)", instance.id,
             instance.start_time)
    return _ServedState(engine, engine_params, components, models, instance)


class PredictionServer(HttpService):
    """One serving process. Under `pio deploy --workers N`
    (workflow/worker_pool.py) N of these run pre-forked on one
    SO_REUSEPORT-shared port; `supervisor_pid` is then set and the
    /reload//stop verbs fan out through the supervisor's signals so one
    HTTP request reaches every worker — the «MasterActor» supervision
    role [U] (SURVEY.md §3.2) made multi-process."""

    def __init__(self, config: ServerConfig, storage: Optional[Storage] = None,
                 plugins=None, reuse_port: bool = False,
                 supervisor_pid: Optional[int] = None,
                 serving_config: Optional[ServingConfig] = None,
                 experiment: Optional[ExperimentConfig] = None,
                 online: Optional[OnlineConfig] = None):
        from predictionio_tpu.plugins import load_plugins_from_env

        self.config = config
        self.storage = storage or Storage.get()
        self.plugins = (plugins if plugins is not None
                        else load_plugins_from_env())
        self.supervisor_pid = supervisor_pid
        self._state_lock = threading.Lock()

        # Experiment posture rides PIO_EXPERIMENT_* (like PIO_SERVING_*)
        # so every pre-fork pool worker resolves the same variant set.
        self.experiment = (experiment if experiment is not None
                           else ExperimentConfig.from_env())
        self._variants = (tuple(self.experiment.variants)
                          if self.experiment is not None
                          else (config.engine_variant,))
        self._primary_variant = self._variants[0]
        self._variant_header_cache = {v: {"X-PIO-Variant": v}
                                      for v in self._variants}
        self._states = {v: load_served_state(self.storage,
                                             self._config_for(v))
                        for v in self._variants}
        worker_pid = os.getpid()
        server = self

        # The serving planes (admission + micro-batching) outlive
        # reloads: each variant's dispatch reads server._states at
        # dispatch time, so a batch coalesced across a /reload simply
        # scores on whichever state is current — same snapshot semantics
        # the single-query path had.
        def _make_dispatch(v):
            def _dispatch(queries):
                state = server._states[v]
                with spans.span("predictionserver.predict"), \
                        PREDICT_SECONDS.time():
                    return state.engine.predict_batch(
                        state.engine_params, state.models, queries,
                        components=state.components)
            return _dispatch

        def _make_degraded(v):
            def _degraded(query):
                state = server._states[v]
                return state.engine.degraded_predict(
                    state.engine_params, state.models, query,
                    components=state.components)
            return _degraded

        serving_cfg = serving_config or ServingConfig.from_env()
        self._planes = {
            v: ServingPlane(
                _make_dispatch(v), degraded_fn=_make_degraded(v),
                config=serving_cfg, name="predictionserver", variant=v,
                app=self._resolve_tenant_app(v))
            for v in self._variants
        }
        self._tailer: Optional[RewardTailer] = None
        if self.experiment is not None:
            # one router in the ServingPlane-shaped slot: same
            # handle_query contract, per-variant planes behind it
            self.serving = VariantRouter(self._planes, self.experiment)
            if self.serving.bandit is not None:
                self._tailer = RewardTailer(
                    self.storage, self.serving.bandit,
                    app_id=self.experiment.app_id,
                    interval_s=self.experiment.tail_interval_s)
                self._tailer.start()
        else:
            self.serving = self._planes[self._primary_variant]
        self._worker_pid = worker_pid

        # Online-learning plane (opt-in, PIO_ONLINE=1): tails rating
        # events out of the durable store, folds the dirty factor rows,
        # and hot-swaps the served state per variant — bandit arms keep
        # learning mid-experiment. A plane that fails to start must not
        # take serving down: the server just stays batch-fresh.
        self.online: Optional[OnlinePlane] = None
        online_cfg = online if online is not None else OnlineConfig.from_env()
        if online_cfg is not None:
            try:
                self.online = OnlinePlane(self, online_cfg)
                self.online.start()
            except Exception:  # noqa: BLE001
                log.exception("online plane failed to start; serving "
                              "continues without fold-in")
                self.online = None

        # Alert watchdog (opt-in, PIO_ALERTS=1): rules run against the
        # metrics history; firing/resolve edges become $alert events
        # through a dedicated group-commit writer into the event store.
        from predictionio_tpu.ingest import GroupCommitWriter, IngestConfig
        from predictionio_tpu.telemetry import alerts
        from predictionio_tpu.telemetry import history as metrics_history
        self._alert_writer: Optional[GroupCommitWriter] = None
        self.watchdog = alerts.AlertWatchdog.from_env(
            metrics_history.ensure_started(), source="predictionserver")
        if self.watchdog is not None:
            le = self.storage.l_events()
            self._alert_writer = GroupCommitWriter(
                insert_fn=le.insert, grouped_fn=le.insert_grouped,
                config=IngestConfig.from_env(), name="alerts")
            self.watchdog.emit = alerts.ingest_emitter(
                self._alert_writer,
                app_id=int(os.environ.get("PIO_ALERT_APP_ID", "0")))
            self.watchdog.start()

        # Route dispatch table, registered once at construction. The
        # query/reload/stop handlers block (device dispatch, storage
        # load), so the event loop runs them on its worker pool.
        router = Router()
        router.get("/", self._handle_status)
        router.post("/queries.json", self._handle_query, blocking=True)
        router.post("/reload", self._handle_reload, blocking=True)
        router.post("/stop", self._handle_stop, blocking=True)

        HttpService.__init__(self, config.ip, config.port,
                             router=router,
                             reuse_port=reuse_port,
                             server_name="predictionserver")

    def _resolve_tenant_app(self, variant: str) -> str:
        """The app id this variant's engine is bound to — the serving-side
        tenant root. PIO_TENANT_APP overrides; otherwise resolved from the
        served state's DataSource appName exactly like the online plane's
        context resolution. Empty string (unattributed) when neither
        resolves — serving must not fail over a missing tenant binding."""
        override = os.environ.get("PIO_TENANT_APP", "").strip()
        if override:
            return override
        try:
            state = self._states[variant]
            dsp = state.engine_params.data_source_params
            app_name = getattr(dsp, "appName", None)
            if not app_name:
                return ""
            app = self.storage.meta_apps().get_by_name(app_name)
            return str(app.id) if app is not None else ""
        except Exception:  # noqa: BLE001 — attribution is best-effort
            return ""

    def _config_for(self, variant: str) -> ServerConfig:
        return ServerConfig(
            ip=self.config.ip, port=self.config.port,
            engine_id=self.config.engine_id,
            engine_version=self.config.engine_version,
            engine_variant=variant)

    @property
    def _state(self) -> _ServedState:
        """Primary variant's served state (the only one outside
        experiment mode)."""
        return self._states[self._primary_variant]

    # -- route handlers ------------------------------------------------------
    def _handle_status(self, req: Request) -> Response:
        state = self._state
        payload = {
            "status": "alive",
            "engineId": self.config.engine_id,
            "engineVersion": self.config.engine_version,
            "engineVariant": self.config.engine_variant,
            "engineFactory": state.instance.engine_factory,
            "engineInstanceId": state.instance.id,
            "startTime": state.instance.start_time.isoformat(),
            # which pool worker answered — the observable receipt that
            # SO_REUSEPORT is really balancing
            "workerPid": self._worker_pid,
        }
        if self.experiment is not None:
            payload["experiment"] = dict(
                self.serving.snapshot(),
                instances={v: s.instance.id
                           for v, s in self._states.items()})
        if self.online is not None:
            payload["online"] = self.online.snapshot()
        return Response.json(200, payload)

    def _variant_headers(self, extra: Optional[dict] = None) -> Optional[dict]:
        """X-PIO-Variant on every experiment-mode response (200 and
        shed/deadline alike) — the client-observable assignment, and
        what the sticky-determinism drills read back. The no-extra case
        (every plain 200) reuses one shared dict per variant."""
        if self.experiment is not None:
            chosen = self.serving.last_variant
            if chosen:
                if not extra:
                    return self._variant_header_cache.get(chosen)
                headers = dict(extra)
                headers["X-PIO-Variant"] = chosen
                return headers
        return extra or None

    def _handle_query(self, req: Request) -> Response:
        retry_after = self.serving.config.admission.retry_after_s
        try:
            query = fastjson.loads(req.body or b"{}")
            result, degraded = self.serving.handle_query(
                query, req.headers)
            state = self._state
            if self.experiment is not None:
                # credit the prediction to the instance that produced it
                state = self._states.get(self.serving.last_variant, state)
            result = self.plugins.on_prediction(
                query, result, state.instance.id)
        except ShedLoad as e:
            # saturated and no degraded answer: an explicit, immediate
            # 429 beats queueing into collapse
            QUERIES_FAILED.inc()
            return Response.message(
                429, str(e), headers=self._variant_headers(
                    {"Retry-After": f"{e.retry_after_s:g}"}))
        except DeadlineExceeded as e:
            QUERIES_FAILED.inc()
            return Response.message(
                503, str(e), headers=self._variant_headers(
                    {"Retry-After": f"{retry_after:g}"}))
        except PluginRejection as e:
            QUERIES_FAILED.inc()
            return Response.message(403, str(e))
        except FaultInjected as e:
            # chaos-drill errors are server faults, not client ones: a
            # 500 spends SLO budget (a 400 would not), which is what the
            # supervisor's error-rate rule and the chaos gate watch for
            QUERIES_FAILED.inc()
            return Response.message(500, str(e))
        except Exception as e:
            QUERIES_FAILED.inc()
            log.warning("Query failed: %s", e)
            return Response.message(400, str(e))
        if degraded:
            headers = self._variant_headers({"X-PIO-Degraded": "1"})
        elif self.experiment is not None:
            headers = self._variant_header_cache.get(
                self.serving.last_variant)
        else:
            headers = None
        return Response(
            200, payload=result, encoder=fastjson.prediction_response,
            headers=headers)

    def _handle_reload(self, req: Request) -> Response:
        if self.supervisor_pid is not None:
            # pool mode: the kernel routed this request to ONE worker;
            # SIGHUP asks the supervisor for a ROLLING reload — each
            # worker (this one included) drains and swaps in turn, so
            # the pool never answers from zero workers mid-deploy
            import signal

            os.kill(self.supervisor_pid, signal.SIGHUP)
            return Response.message(
                200, "Rolling reload signaled to all workers")
        try:
            self.reload()
        except Exception as e:
            return Response.message(500, str(e))
        return Response.json(200, {
            "message": "Reloaded",
            "engineInstanceId": self._state.instance.id,
        })

    def _handle_stop(self, req: Request) -> Response:
        if self.supervisor_pid is not None:
            import signal

            resp = Response.message(200, "Shutting down all workers.")
            resp.on_sent = lambda: os.kill(self.supervisor_pid,
                                           signal.SIGTERM)
            return resp
        resp = Response.message(200, "Shutting down.")
        resp.on_sent = lambda: threading.Thread(
            target=self.shutdown, daemon=True).start()
        return resp

    def reload(self) -> None:
        """Swap every variant to its newest COMPLETED instance
        (idempotent, atomic per variant). Called from the /reload
        handler and, in pool mode, from the worker's SIGHUP handler.
        A variant whose reload fails keeps serving its current state —
        a half-trained challenger must not take down the champion."""
        errors = []
        with self._state_lock:
            for v in self._variants:
                try:
                    self._states[v] = load_served_state(
                        self.storage, self._config_for(v))
                except Exception as e:  # noqa: BLE001
                    log.exception("Reload failed for variant %s; keeping "
                                  "its current instance", v)
                    errors.append(e)
                    continue
                plane = self._planes.get(v)
                if plane is not None and plane.result_cache is not None:
                    # answers cached against the outgoing instance are
                    # stale the moment the swap lands
                    plane.result_cache.invalidate_variant(v)
                log.info("Reloaded engine instance %s (variant %s)",
                         self._states[v].instance.id, v)
        if errors and len(errors) == len(self._variants):
            raise errors[0]
        if self.online is not None:
            # outside the state lock: a fold pass holds its own lock
            # while swapping (which takes the state lock), so rebasing
            # under the state lock would deadlock against it. A fold
            # racing this reload is refused by the swapper's stale-state
            # check and replays against the new instances.
            self.online.rebase()

    def shutdown(self) -> None:
        """Graceful drain: the HTTP server stops accepting and finishes
        in-flight handlers first (their queued queries still dispatch),
        then the batcher's dispatcher thread is joined."""
        super().shutdown()
        if self.online is not None:
            self.online.stop()
        if self._tailer is not None:
            self._tailer.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._alert_writer is not None:
            self._alert_writer.close()
        self.serving.close()

    def health_check(self) -> bool:
        """The drain-then-reload re-admission check: a worker re-enters
        the SO_REUSEPORT group only if it is actually able to serve —
        a served state is loaded and the `/metrics` exposition renders
        (the supervisor runbook's probe)."""
        if not self._states:
            return False
        from predictionio_tpu.telemetry import slo as _slo

        _slo.refresh()
        return bool(REGISTRY.render())

    @property
    def instance_id(self) -> str:
        return self._state.instance.id


def create_server(config: Optional[ServerConfig] = None,
                  storage: Optional[Storage] = None) -> PredictionServer:
    return PredictionServer(config or ServerConfig(), storage)
