"""bin/pio launcher tests — parity with the reference's shell dispatch
(«bin/pio», «conf/pio-env.sh» — SURVEY.md §2.3 [U]): env file is sourced
before the console runs, args pass through verbatim."""

import os
import pathlib
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent
PIO = REPO / "bin" / "pio"


def _run(args, env_extra=None, cwd=None):
    env = dict(os.environ)
    env.pop("PIO_CONF_DIR", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [str(PIO), *args], capture_output=True, text=True, env=env, cwd=cwd
    )


def test_version_passthrough():
    r = _run(["version"])
    assert r.returncode == 0
    assert r.stdout.strip() == "0.1.0"


def test_env_file_sourced(tmp_path):
    # a conf dir whose pio-env.sh points storage at a tmp sqlite file;
    # `pio status` must create/see it (proves the file was sourced)
    conf = tmp_path / "conf"
    conf.mkdir()
    db = tmp_path / "store.db"
    (conf / "pio-env.sh").write_text(
        "export PIO_STORAGE_SOURCES_PIO_SQLITE_TYPE=sqlite\n"
        f"export PIO_STORAGE_SOURCES_PIO_SQLITE_PATH={db}\n"
        "export PIO_STORAGE_REPOSITORIES_METADATA_SOURCE=PIO_SQLITE\n"
        "export PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=PIO_SQLITE\n"
        "export PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=PIO_SQLITE\n"
    )
    r = _run(["status"], env_extra={"PIO_CONF_DIR": str(conf)})
    assert r.returncode == 0, r.stderr
    assert "all OK" in r.stdout
    assert db.exists()


def test_unknown_verb_fails():
    r = _run(["definitely-not-a-verb"])
    assert r.returncode != 0
