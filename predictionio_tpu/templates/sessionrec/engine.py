"""Session-based next-item engine template (DASE components).

The scenario-diversity frontier (ROADMAP item 4): every other served
template is factor- or frequency-based; this one is a small causal
self-attention next-item model — item embeddings plus 1–2
`ops.attention.dense_attention` blocks — trained through the normal
DataSource → Preparator → Algorithm path over per-user event sequences
from `data/view.py`'s ordered aggregation, and served through the
existing MicroBatcher.

Serving pads over TWO ragged axes on fixed ladders: the batcher's
power-of-two bucket ladder bounds the batch dimension, and the
sequence-tier ladder (`serving.batcher.seq_tiers_from_env`, knob
PIO_SERVING_SEQ_TIERS) bounds the history-length dimension — so the
jitted scorer's executable space is (batch tiers × sequence tiers),
each compiled once, instead of one compile per ragged length.

Pad positions are exact no-ops, which is what makes batched-vs-single
parity bitwise at every tier: histories right-pad, the causal mask
keeps every real position from attending past itself (a masked score is
`_NEG_INF`, whose softmax term underflows to exactly 0.0 in f32), the
readout gathers the LAST REAL position's state, and all other ops are
per-position or per-row. A history therefore scores identically at any
tier that fits it and in any batch that carries it.

Wire shapes:
    query:  {"user": "u1", "num": 4}            — served session window
            {"items": ["i1", "i2"], "num": 4}   — explicit session
    result: {"itemScores": [{"item": "i5", "score": 0.93}, ...]}
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from datetime import timezone
from typing import Dict, List, Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource as BaseDataSource,
    Engine,
    EngineFactory,
    FirstServing,
    Params,
    Preparator as BasePreparator,
    SanityCheck,
    WorkflowContext,
)
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.data.view import LBatchView
from predictionio_tpu.models.session_model import (
    SessionRecModel,
    recent_window,
)
from predictionio_tpu.serving.batcher import (
    pad_to_seq_tier,
    seq_tier_ladder,
    seq_tiers_from_env,
)

log = logging.getLogger(__name__)

Query = dict
PredictedResult = dict


@dataclasses.dataclass
class DataSourceParams(Params):
    appName: str = ""
    eventNames: list = dataclasses.field(
        default_factory=lambda: ["view", "buy"])
    evalK: int = 0  # >0 enables read_eval with k leave-last-item folds


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """Per-user canonical item sequences (the `recent_window` rule over
    the ordered event fold — keep-last dedup, (time, item) order)."""

    sequences: Dict[str, List[str]]  # user id → ordered item ids

    def sanity_check(self):
        if not any(len(s) >= 2 for s in self.sequences.values()):
            raise ValueError(
                "TrainingData has no user with a 2+ item sequence; ingest "
                "view/buy events first (next-item training needs at least "
                "one transition).")


class DataSource(BaseDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        """Per-user ordered sequences via `LBatchView.
        aggregate_by_entity_ordered` — the time-ordered per-entity fold
        (events sorted by event_time, creation_time) the reference's
        `aggregateByEntityOrdered` provided. The fold accumulates
        (item, event_time) pairs; `recent_window` then applies the one
        canonical window rule the online fold shares."""
        view = LBatchView(self.params.appName,
                          store=EventStore(ctx.storage))
        names = set(self.params.eventNames)

        def pred(e) -> bool:
            return (e.event in names
                    and e.entity_type == "user"
                    and (e.target_entity_type or "item") == "item"
                    and bool(e.target_entity_id))

        def op(acc, e):
            t = e.event_time
            if t is not None and t.tzinfo is None:
                t = t.replace(tzinfo=timezone.utc)
            return acc + ((str(e.target_entity_id), t),)

        folded = view.aggregate_by_entity_ordered(pred, (), op)
        sequences = {str(u): recent_window(pairs, 0)  # 0 = uncapped here;
                     for u, pairs in folded.items() if pairs}
        # the Algorithm caps to maxSeqLen so window length stays an
        # algorithm knob, not a data-shape property
        log.info("DataSource: %d users with sequences, app %r",
                 len(sequences), self.params.appName)
        return TrainingData(sequences=sequences)

    def read_eval(self, ctx: WorkflowContext):
        """k-fold leave-last-item-out: each fold holds out 1/k of the
        2+-item users; their training sequence drops its last item and
        the query replays the prefix asking the model to rank the
        held-out next item."""
        k = self.params.evalK
        if k <= 1:
            raise ValueError("DataSourceParams.evalK must be >= 2 for "
                             "evaluation")
        td = self.read_training(ctx)
        users = sorted(u for u, s in td.sequences.items() if len(s) >= 2)
        folds = []
        for fold in range(k):
            held = set(users[fold::k])
            seqs = {u: (list(s[:-1]) if u in held else list(s))
                    for u, s in td.sequences.items()}
            seqs = {u: s for u, s in seqs.items() if s}
            qa = [({"items": list(seqs[u]), "num": 10},
                   {"items": [td.sequences[u][-1]]})
                  for u in sorted(held) if seqs.get(u)]
            folds.append((TrainingData(sequences=seqs), qa))
        return folds


@dataclasses.dataclass
class PreparedData:
    item_ids: BiMap
    user_seqs: Dict[str, np.ndarray]  # user id → int32 embedding rows


class Preparator(BasePreparator):
    """Code items densely (sorted ids → deterministic rows) and encode
    each user's canonical sequence."""

    def prepare(self, ctx: WorkflowContext, td: TrainingData) -> PreparedData:
        items = sorted({i for s in td.sequences.values() for i in s})
        item_ids = BiMap.string_int(items)
        user_seqs = {
            u: item_ids.to_index(s).astype(np.int32)
            for u, s in sorted(td.sequences.items())
        }
        return PreparedData(item_ids=item_ids, user_seqs=user_seqs)


# -- jitted forward ----------------------------------------------------------

def _encode(params, seq, n_heads: int):
    """[B, L] padded item rows → [B, L, D] contextual states.

    Right-padded rows index the pad embedding (row V); causal
    dense_attention keeps every real position's state a function of
    real positions only, so the encoding of a history is invariant to
    the tier it was padded to (see module docstring)."""
    import jax
    import jax.numpy as jnp
    from predictionio_tpu.ops.attention import dense_attention

    emb = params["emb"]
    x = emb[seq] + params["pos"][: seq.shape[1]][None, :, :]
    b, l, d = x.shape
    for blk in params["blocks"]:
        q = (x @ blk["wq"]).reshape(b, l, n_heads, -1).transpose(0, 2, 1, 3)
        k = (x @ blk["wk"]).reshape(b, l, n_heads, -1).transpose(0, 2, 1, 3)
        v = (x @ blk["wv"]).reshape(b, l, n_heads, -1).transpose(0, 2, 1, 3)
        a = dense_attention(q, k, v, causal=True)
        x = x + a.transpose(0, 2, 1, 3).reshape(b, l, d) @ blk["wo"]
        x = x + (jax.nn.relu(x @ blk["w1"] + blk["b1"]) @ blk["w2"]
                 + blk["b2"])
    return x


@functools.lru_cache(maxsize=8)
def _scorer(n_heads: int):
    """The served next-item scorer, metered so every dispatch lands in
    the jit-cache inventory / device attribution and a ladder miss
    names its changed dimension in /debug/jit.json. Executable space:
    one compile per (batch tier, sequence tier) after warmup — args are
    (params pytree, seq [B, L], lengths [B]), so a sequence-ladder miss
    blames "arg1 dim1: <old>→<new>"."""
    from predictionio_tpu.utils.profiling import metered_jit

    def score(params, seq, lengths):
        import jax.numpy as jnp

        x = _encode(params, seq, n_heads)
        b, l, _ = x.shape
        idx = jnp.clip(lengths - 1, 0, l - 1)
        h = x[jnp.arange(b), idx]  # last REAL position per row
        n_items = params["emb"].shape[0] - 1
        return h @ params["emb"][:n_items].T  # tied output embedding

    return metered_jit(score, label="sessionrec.score")


@functools.lru_cache(maxsize=8)
def _train_step(n_heads: int, lr: float):
    """One full-batch Adam step on masked next-item cross-entropy."""
    from predictionio_tpu.utils.profiling import metered_jit

    def step(params, m, v, t, seq, lengths):
        import jax
        import jax.numpy as jnp

        def loss_fn(p):
            x = _encode(p, seq, n_heads)
            n_items = p["emb"].shape[0] - 1
            logits = x[:, :-1] @ p["emb"][:n_items].T  # [B, L-1, V]
            targets = jnp.minimum(seq[:, 1:], n_items - 1)
            mask = (jnp.arange(seq.shape[1] - 1)[None, :]
                    < (lengths - 1)[:, None]).astype(logits.dtype)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        t = t + 1.0
        tree_map = jax.tree_util.tree_map
        m = tree_map(lambda mm, g: 0.9 * mm + 0.1 * g, m, grads)
        v = tree_map(lambda vv, g: 0.999 * vv + 0.001 * g * g, v, grads)
        params = tree_map(
            lambda p, mm, vv: p - lr * (mm / (1.0 - 0.9 ** t))
            / (jnp.sqrt(vv / (1.0 - 0.999 ** t)) + 1e-8),
            params, m, v)
        return params, m, v, t, loss

    return metered_jit(step, label="sessionrec.train_step")


def _pad_batch_tier(n: int) -> int:
    """Power-of-two batch tier ≥ n (the scorer-side half of the bucket
    ladder: batch groups re-fragment after sequence-tier grouping, so
    the batch dim re-pads onto its own fixed ladder)."""
    t = 1
    while t < n:
        t <<= 1
    return t


def _serve_tiers(model: SessionRecModel) -> tuple:
    """Sequence tiers this model can serve: the env ladder clamped to
    the trained positional table (a tier the table can't cover would
    index past it)."""
    l_pos = int(np.asarray(model.params["pos"]).shape[0])
    tiers = tuple(t for t in seq_tiers_from_env(model.max_seq_len)
                  if t <= l_pos)
    return tiers or seq_tier_ladder(model.max_seq_len)


@dataclasses.dataclass
class SessionRecParams(Params):
    embedDim: int = 16
    numBlocks: int = 1
    numHeads: int = 2
    maxSeqLen: int = 32
    epochs: int = 30
    stepSize: float = 0.05
    seed: Optional[int] = None


class SessionRecAlgorithm(Algorithm):
    """Causal self-attention next-item model over session windows."""

    params_class = SessionRecParams
    checkpoint_tags = ("sessionrec",)

    def __init__(self, params: SessionRecParams):
        self.params = params

    def train(self, ctx: WorkflowContext,
              pd: PreparedData) -> SessionRecModel:
        import jax

        p = self.params
        seed = ctx.seed if p.seed is None else p.seed
        rng = np.random.default_rng(int(seed) if seed is not None else 0)
        n_items = len(pd.item_ids)
        d = int(p.embedDim)
        cap = int(p.maxSeqLen)
        # positional table spans the default ladder's top tier for this
        # window length — independent of the serve-time env so a model
        # never deploys with fewer positions than its own ladder needs
        l_pos = seq_tier_ladder(cap)[-1]

        def init_w(*shape):
            return (rng.standard_normal(shape) * 0.1).astype(np.float32)

        blocks = []
        for _ in range(int(p.numBlocks)):
            blocks.append({
                "wq": init_w(d, d), "wk": init_w(d, d),
                "wv": init_w(d, d), "wo": init_w(d, d),
                "w1": init_w(d, 2 * d),
                "b1": np.zeros(2 * d, np.float32),
                "w2": init_w(2 * d, d),
                "b2": np.zeros(d, np.float32),
            })
        params = {
            # row n_items is the sequence pad row (kept zero at init;
            # pads never reach the loss or the readout)
            "emb": np.concatenate(
                [init_w(n_items, d), np.zeros((1, d), np.float32)]),
            "pos": init_w(l_pos, d),
            "blocks": blocks,
        }

        seqs = [s[-cap:] for _, s in sorted(pd.user_seqs.items())
                if len(s) >= 2]
        n = len(seqs)
        if n:
            bt = _pad_batch_tier(n)
            seq = np.full((bt, l_pos), n_items, np.int32)
            lengths = np.zeros(bt, np.int32)
            for r, s in enumerate(seqs):
                seq[r, :len(s)] = s
                lengths[r] = len(s)
            step = _train_step(int(p.numHeads), float(p.stepSize))
            m = jax.tree_util.tree_map(np.zeros_like, params)
            v = jax.tree_util.tree_map(np.zeros_like, params)
            t = np.float32(0.0)
            loss = None
            for _ in range(int(p.epochs)):
                params, m, v, t, loss = step(params, m, v, t, seq, lengths)
            params = jax.tree_util.tree_map(np.asarray, params)
            log.info("SessionRec: trained %d sequences, %d items, final "
                     "loss %.4f", n, n_items,
                     float(loss) if loss is not None else float("nan"))

        windows = {
            u: tuple(pd.item_ids.from_index(s[-cap:]))
            for u, s in sorted(pd.user_seqs.items())
        }
        model = SessionRecModel(
            params=params, item_ids=pd.item_ids, user_windows=windows,
            session_vecs={}, max_seq_len=cap, n_heads=int(p.numHeads))
        model.session_vecs.update(
            {u: model.session_vec_of(w) for u, w in windows.items()})
        return model

    def predict(self, model: SessionRecModel,
                query: Query) -> PredictedResult:
        # the single path IS the batched path at batch 1: parity between
        # them is a code identity plus the tier-invariance the jitted
        # forward guarantees (asserted in tests/test_sessionrec_template)
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: SessionRecModel,
                      queries) -> list:
        out: list = [None] * len(queries)
        tiers = _serve_tiers(model)
        cap = min(model.max_seq_len, int(tiers[-1]))
        groups: Dict[int, list] = {}
        for pos, q in enumerate(queries):
            hist = q.get("items")
            if hist is None:
                u = q.get("user")
                hist = (model.user_windows.get(str(u), ())
                        if u is not None else ())
            rows = model.window_rows(hist)[-cap:]
            num = int(q.get("num", 10))
            if not rows or num <= 0:
                out[pos] = {"itemScores": []}
                continue
            tier = pad_to_seq_tier(len(rows), tiers)
            groups.setdefault(tier, []).append((pos, rows, num))
        if not groups:
            return out
        score = _scorer(model.n_heads)
        pad_row = model.n_items
        for tier, entries in groups.items():
            b = len(entries)
            bt = _pad_batch_tier(b)
            seq = np.full((bt, tier), pad_row, np.int32)
            lengths = np.zeros(bt, np.int32)
            for r, (_, rows, _) in enumerate(entries):
                seq[r, :len(rows)] = rows
                lengths[r] = len(rows)
            if bt > b:
                # batch padding duplicates the last real row; its
                # results are never read (the batcher's _pad idiom)
                seq[b:] = seq[b - 1]
                lengths[b:] = lengths[b - 1]
            logits = np.asarray(score(model.params, seq, lengths))
            for r, (pos, rows, num) in enumerate(entries):
                s = logits[r].copy()
                seen = np.unique(np.asarray(rows, np.int32))
                s[seen] = -np.inf  # never re-recommend the window
                k = min(num, s.shape[0] - len(seen))
                if k <= 0:
                    out[pos] = {"itemScores": []}
                    continue
                top = np.argpartition(-s, k - 1)[:k]
                top = top[np.argsort(-s[top])]
                items = model.item_ids.from_index(top)
                out[pos] = {"itemScores": [
                    {"item": i, "score": float(s[j])}
                    for i, j in zip(items, top)]}
        return out


class SessionRecEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            data_source_class_map=DataSource,
            preparator_class_map=Preparator,
            algorithm_class_map={"attention": SessionRecAlgorithm},
            serving_class_map=FirstServing,
        )
