"""Lead Scoring template: session first-view features → conversion
probability (softmax regression in the upstream RandomForest's role)."""

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.leadscoring.LeadScoringEngine"


def ingest_sessions(storage, app_name="LeadApp"):
    """Planted structure: landing page "promo" converts ~90%, "home" ~10%,
    independent of the other features."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    rng = np.random.default_rng(7)
    n = 0
    for lp, rate in (("promo", 0.9), ("home", 0.1)):
        for k in range(60):
            sid = f"s{n}"
            n += 1
            le.insert(Event(
                event="view", entity_type="user", entity_id=f"u{n}",
                properties=DataMap({
                    "sessionId": sid, "landingPageId": lp,
                    "referrerId": f"r{k % 3}",
                    "browser": ["Chrome", "Firefox"][k % 2]})), app_id)
            if rng.random() < rate:
                le.insert(Event(
                    event="buy", entity_type="user", entity_id=f"u{n}",
                    target_entity_type="item", target_entity_id="i1",
                    properties=DataMap({"sessionId": sid})), app_id)
    return app_id


def variant_dict(app_name="LeadApp"):
    return {
        "id": "lead-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "leadscoring", "params": {
            "iterations": 300, "stepSize": 0.2, "regParam": 0.01}}],
    }


class TestLeadScoring:
    def test_train_and_score_separates_pages(self, memory_storage):
        ingest_sessions(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        hi = engine.predict(ep, models, {
            "landingPageId": "promo", "referrerId": "r0",
            "browser": "Chrome"})["score"]
        lo = engine.predict(ep, models, {
            "landingPageId": "home", "referrerId": "r0",
            "browser": "Chrome"})["score"]
        assert 0.0 <= lo < hi <= 1.0
        assert hi > 0.6 and lo < 0.4  # planted 0.9 vs 0.1 rates

    def test_unseen_features_fall_back_to_base_rate(self, memory_storage):
        ingest_sessions(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        models = engine.train(ctx, ep)
        s = engine.predict(ep, models, {
            "landingPageId": "never-seen", "referrerId": "nope",
            "browser": "Netscape"})["score"]
        # the honest prior: overall training conversion rate (~0.5 here)
        assert 0.3 < s < 0.7
        # partially-known queries still use the model
        s2 = engine.predict(ep, models, {
            "landingPageId": "promo", "referrerId": "nope",
            "browser": "Netscape"})["score"]
        assert s2 > 0.5

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptyLead"))
        variant = EngineVariant.from_dict(variant_dict("EmptyLead"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no sessions"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)


class TestAUCMetric:
    @staticmethod
    def _auc(pairs):
        from predictionio_tpu.controller.metrics import AUC

        return AUC().evaluate_all(
            [({}, {"score": s}, {"label": y}) for s, y in pairs])

    def test_auc_perfect_and_random_and_ties(self):
        assert self._auc(
            [(0.9, 1), (0.8, 1), (0.2, 0), (0.1, 0)]) == 1.0  # separable
        assert self._auc(
            [(0.1, 1), (0.2, 1), (0.8, 0), (0.9, 0)]) == 0.0  # all wrong
        # all-tied scores → AUC 0.5 via tie correction
        assert self._auc(
            [(0.5, 1), (0.5, 0), (0.5, 1), (0.5, 0)]) == 0.5
        # one-class fold is undefined
        import math

        assert math.isnan(self._auc([(0.7, 1)]))

    def test_auc_calculate_is_per_point_undefined(self):
        """AUC has no per-point score: calculate returns None (the
        Optional contract's excluded value), never a bogus float."""
        from predictionio_tpu.controller.metrics import AUC

        assert AUC().calculate({}, {"score": 0.9}, {"label": 1}) is None

    def test_auc_against_sklearn_formula(self):
        import numpy as np

        rng = np.random.default_rng(0)
        scores = rng.random(200)
        labels = (rng.random(200) < 0.4).astype(int)
        got = self._auc([(float(s), int(y))
                         for s, y in zip(scores, labels)])
        # reference: probability a random positive outranks a random
        # negative (ties count half)
        pos = scores[labels == 1]
        neg = scores[labels == 0]
        cmp = (pos[:, None] > neg[None, :]).sum() + \
            0.5 * (pos[:, None] == neg[None, :]).sum()
        want = cmp / (len(pos) * len(neg))
        assert got == pytest.approx(want, abs=1e-12)


class TestLeadScoringEvaluation:
    def test_eval_grid_auc(self, memory_storage):
        ingest_sessions(memory_storage)
        from predictionio_tpu.controller import WorkflowContext
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.templates.leadscoring.evaluation import (
            LeadScoringEvaluation, RegGridGenerator,
        )

        ctx = WorkflowContext(storage=memory_storage, seed=1)
        gen = RegGridGenerator("LeadApp", eval_k=3, reg_params=(0.01, 0.1))
        result = MetricEvaluator.evaluate(
            ctx, LeadScoringEvaluation(), gen.engine_params_list)
        # planted 0.9-vs-0.1 structure: AUC must be far above chance
        for r in result.all_results:
            assert r.scores[result.metric_name] > 0.75
        assert result.best in result.all_results


class TestLeadScoringCheckpoint:
    """Round 5: `ctx.checkpoint_dir` plumbs into this template's
    `logreg_train` — interrupted Adam runs resume bitwise-identically
    (the workflow/segmented contract, SURVEY.md §5)."""

    def test_interrupted_resume_matches_uninterrupted(
            self, memory_storage, tmp_path, caplog):
        import logging

        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        ingest_sessions(memory_storage)

        def train(iters, ckpt):
            v = variant_dict()
            v["algorithms"][0]["params"]["iterations"] = iters
            variant = EngineVariant.from_dict(v)
            engine = get_engine(variant.engine_factory)
            ep = extract_engine_params(engine, variant)
            ctx = WorkflowContext(
                storage=memory_storage, seed=1,
                checkpoint_dir=str(tmp_path / "ck") if ckpt else None,
                checkpoint_every=10)
            return engine.train(ctx, ep)[0]

        want = train(40, ckpt=False)
        train(20, ckpt=True)  # the "interrupted" run
        cm = CheckpointManager(str(tmp_path / "ck" / "lr"))
        assert cm.latest_step() == 20
        with caplog.at_level(logging.INFO):
            got = train(40, ckpt=True)
        assert any("resumed from checkpoint step 20" in r.getMessage()
                   for r in caplog.records)
        assert cm.latest_step() == 40
        np.testing.assert_array_equal(got.lr.weights, want.lr.weights)
        np.testing.assert_array_equal(got.lr.bias, want.lr.bias)
