"""RewardTailer: $reward events out of the store, into the posteriors.

Rewards do NOT take a side channel to the bandit. Clients POST `$reward`
through /events.json like any other event, the group-commit write plane
makes it durable, and this tailer polls the event store and folds what
it finds into the ThompsonBandit's Beta posteriors. That buys three
properties a direct in-memory update cannot:

- **durability** — a reward survives a worker crash; the posterior is
  reconstructed from the store, not from process memory;
- **convergent workers** — every pool worker tails the same store, so
  all of them settle on the same split regardless of which process
  accepted the HTTP POST;
- **restart recovery** — a fresh tailer replays the full $reward
  history first (first poll has no watermark), so a redeployed server
  resumes the experiment where it left off instead of back at the
  uniform prior.

Polling is watermark + overlap: each poll asks for events from slightly
before the last seen event time (re-reading the overlap costs a few
duplicate rows; the `_seen` id map makes re-applying them impossible),
because group-commit batches can land with event times that interleave
with an in-flight poll.
"""

from __future__ import annotations

import logging
import threading
from datetime import timedelta
from typing import Optional

from predictionio_tpu.experiment.bandit import ThompsonBandit
from predictionio_tpu.experiment.metrics import (
    EXPERIMENT_POSTERIOR_MEAN,
    EXPERIMENT_REWARDS,
)

log = logging.getLogger(__name__)

# how far behind the watermark each poll re-reads; must exceed the gap
# between a commit's event_time and its visibility in the store
OVERLAP = timedelta(seconds=2.0)


class RewardTailer:
    """Poll the durable event store for $reward events and apply them."""

    def __init__(self, storage, bandit: ThompsonBandit,
                 app_id: int = 1, channel_id: Optional[int] = None,
                 interval_s: float = 0.5):
        self.storage = storage
        self.bandit = bandit
        self.app_id = app_id
        self.channel_id = channel_id
        self.interval_s = interval_s
        self._since = None  # event-time watermark; None → full replay
        self._seen: dict = {}  # applied-event key → event_time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _event_key(e) -> object:
        if e.event_id:
            return e.event_id
        return (e.entity_id, e.event_time, repr(e.properties.to_dict()))

    def poll_once(self) -> int:
        """One tail pass. Returns the number of rewards newly applied."""
        start = self._since - OVERLAP if self._since is not None else None
        events = self.storage.l_events().find(
            self.app_id, channel_id=self.channel_id,
            start_time=start, event_names=["$reward"])
        applied = 0
        for e in events:
            key = self._event_key(e)
            if key in self._seen:
                continue
            self._seen[key] = e.event_time
            if self._since is None or e.event_time > self._since:
                self._since = e.event_time
            if self._apply(e):
                applied += 1
        self._prune_seen()
        return applied

    def _apply(self, e) -> bool:
        props = e.properties.to_dict()
        variant = props.get("variant")
        try:
            reward = float(props.get("reward"))
        except (TypeError, ValueError):
            # validate_event rejects these at ingest; a hand-inserted
            # row must not wedge the tail loop
            log.warning("skipping malformed $reward %s", e.event_id)
            return False
        if not self.bandit.reward(variant, reward):
            return False
        EXPERIMENT_REWARDS.labels(variant=variant).inc()
        EXPERIMENT_POSTERIOR_MEAN.labels(variant=variant).set(
            self.bandit.posterior_mean(variant))
        return True

    def _prune_seen(self) -> None:
        # only keys inside the overlap window can recur in a future poll
        if self._since is None or len(self._seen) < 4096:
            return
        cutoff = self._since - 2 * OVERLAP
        self._seen = {k: t for k, t in self._seen.items() if t >= cutoff}

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="reward-tailer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the tail loop must survive
                log.exception("reward tail pass failed; retrying")
            self._stop.wait(self.interval_s)
