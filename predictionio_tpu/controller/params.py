"""Component parameter classes + JSON extraction.

Parity with «core/.../controller/Params.scala» and
«core/.../workflow/WorkflowUtils.scala :: extractParams» (SURVEY.md §2.1
[U]). The reference extracts engine.json `params` blocks into Scala case
classes via json4s reflection; here `Params` subclasses are dataclasses and
extraction is `params_from_dict`.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Type, TypeVar

log = logging.getLogger(__name__)

P = TypeVar("P", bound="Params")


class Params:
    """Marker base class for component parameters. Subclasses should be
    ``@dataclasses.dataclass``-decorated."""


@dataclasses.dataclass
class EmptyParams(Params):
    pass


class ParamsError(ValueError):
    """Raised when an engine.json params block doesn't match the Params class."""


def params_from_dict(cls: Type[P], d: dict[str, Any]) -> P:
    """Instantiate a Params dataclass from a JSON dict.

    Unknown keys are an error (matching the reference's strict json4s
    extraction — a typo in engine.json should not silently train with
    defaults); missing keys fall back to dataclass defaults, and missing
    keys without defaults raise.
    """
    if d is None:
        d = {}
    if not dataclasses.is_dataclass(cls):
        if d:
            raise ParamsError(
                f"{cls.__name__} is not a dataclass but params {sorted(d)} were given"
            )
        return cls()
    # _ALIASES lets a Params class accept JSON keys that aren't valid Python
    # identifiers (e.g. engine.json's "lambda" → field "lambda_").
    aliases: dict[str, str] = getattr(cls, "_ALIASES", {})
    if aliases:
        d = {aliases.get(k, k): v for k, v in d.items()}
    field_names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - field_names
    if unknown:
        raise ParamsError(
            f"Unknown parameter(s) {sorted(unknown)} for {cls.__name__} "
            f"(accepted: {sorted(field_names)})"
        )
    try:
        return cls(**d)
    except TypeError as e:
        raise ParamsError(f"Cannot build {cls.__name__} from {d!r}: {e}") from e


def params_to_dict(params: Params) -> dict[str, Any]:
    if dataclasses.is_dataclass(params):
        return dataclasses.asdict(params)
    return {}
