"""Shared HTTP base (utils/http.py) — error-channel ownership.

A framework that silences its access log must own its error channel
too: handler exceptions route through `logging`, never raw tracebacks
on stderr (socketserver's default `handle_error` prints there, which
polluted the round-4 suite run from a fault drill — VERDICT r4 weak #4).
"""

import http.client
import logging

from predictionio_tpu.utils.http import HttpService, JsonRequestHandler


class _BoomHandler(JsonRequestHandler):
    def do_GET(self):
        if self.path == "/boom":
            raise RuntimeError("handler bug")
        self.send_json(200, {"ok": True})


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        return conn.getresponse().read()
    finally:
        conn.close()


def test_handler_exception_logs_not_stderr(capfd, caplog):
    from predictionio_tpu.telemetry.middleware import HTTP_ERRORS

    svc = HttpService("127.0.0.1", 0, _BoomHandler, server_name="boomsvc")
    errors_before = HTTP_ERRORS.labels(server="boomsvc").value
    svc.start()
    try:
        # handler bugs are warnings (counted, traced), not errors
        with caplog.at_level(logging.WARNING, logger="predictionio_tpu.http"):
            try:
                _get(svc.port, "/boom")
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # the connection dying is fine; stderr noise is not
            # healthy requests still served after the crashed one
            assert b"true" in _get(svc.port, "/ok")
    finally:
        svc.shutdown()
    err = capfd.readouterr().err
    assert "Traceback" not in err
    assert "Exception occurred during processing of request" not in err
    crash_records = [r for r in caplog.records
                     if "exception processing request" in r.message]
    assert crash_records, "handler bug must reach logging"
    assert any(r.exc_info for r in crash_records), \
        "traceback belongs in the logging record"
    # the record carries the request's trace id, not the "-" placeholder
    assert all("trace=-" not in r.getMessage() for r in crash_records)
    assert HTTP_ERRORS.labels(server="boomsvc").value == errors_before + 1


def test_short_body_times_out_408_not_forever(monkeypatch):
    """A client that promises Content-Length N and sends fewer bytes must
    get a 408 within the read timeout, not pin a server thread forever
    (the pre-event-loop read_body blocked indefinitely on the socket)."""
    import json
    import socket
    import time

    monkeypatch.setenv("PIO_HTTP_READ_TIMEOUT_S", "0.5")

    class _Echo(JsonRequestHandler):
        def do_POST(self):
            body = self.read_body()
            self.send_json(200, {"n": len(body)})

    svc = HttpService("127.0.0.1", 0, _Echo, server_name="shortbody")
    svc.start()
    try:
        s = socket.create_connection(("127.0.0.1", svc.port), timeout=10)
        t0 = time.monotonic()
        s.sendall(b"POST /x HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: 100\r\n\r\nonly-a-few-bytes")
        # read to EOF: the 408 must arrive AND the server must close the
        # connection (a half-read body cannot be reframed)
        raw = b""
        s.settimeout(10)
        while True:
            chunk = s.recv(4096)
            if not chunk:
                break
            raw += chunk
        elapsed = time.monotonic() - t0
        assert b" 408 " in raw.split(b"\r\n", 1)[0], raw[:200]
        assert 0.3 <= elapsed < 5.0, elapsed
        _head, _, body = raw.partition(b"\r\n\r\n")
        assert b"timeout" in json.loads(body)["message"].lower().encode()
        s.close()
        # a well-framed request on a fresh connection still serves
        conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
        conn.request("POST", "/x", b"12345",
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read())["n"] == 5
        conn.close()
    finally:
        svc.shutdown()


def test_client_disconnect_is_not_an_error(capfd, caplog):
    """A client dropping mid-request (routine under kill drills and load
    ladders) is debug noise, not an error record."""
    svc = HttpService("127.0.0.1", 0, _BoomHandler)
    svc.start()
    try:
        with caplog.at_level(logging.ERROR, logger="predictionio_tpu.http"):
            import socket
            s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
            s.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            s.close()  # drop without reading the reply
            assert b"true" in _get(svc.port, "/ok")
    finally:
        svc.shutdown()
    err = capfd.readouterr().err
    assert "Traceback" not in err
    assert not [r for r in caplog.records if r.levelno >= logging.ERROR]
