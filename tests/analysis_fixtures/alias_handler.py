"""Fixture: a /queries.json handler registered through a local alias
(`h = self._handle_query`). The resolver must chase the assignment so
the admission gate still sees the direct-dispatch violation inside."""


class AliasedAPI:
    def router(self, r):
        h = self._handle_query
        r.post("/queries.json", h, blocking=True)
        return r

    def _handle_query(self, req):
        return self.engine.predict(req)
