// Native bulk event export for predictionio_tpu.
//
// «tools/.../export/EventsToFile.scala» [U] streams the event store to a
// JSON-lines file via a Spark job; the rebuild's Python path builds one
// Event object + DataMap per row and re-serializes — ~30k rows/s and
// O(n) memory (find() materializes every row). This TU walks the SQLite
// table once (same dlopen'd C-ABI pattern as pio_scan.cpp) and SPLICES
// the stored JSON columns into each output line:
//
//   - `properties` and `tags` are stored as the exact text
//     `DataMap.to_json()` / `json.dumps(tags)` wrote at insert
//     (sort_keys properties, ensure_ascii — pure printable ASCII), and
//     `json.loads` → `json.dumps` round-trips that text byte-identically
//     (key order preserved, same separators), so the stored text IS what
//     the Python exporter would emit;
//   - `event_time` / `creation_time` are stored in `format_time`'s
//     canonical fixed-width UTC form, which parse→format round-trips to
//     itself;
//   - remaining string columns are escaped exactly like
//     `json.dumps(ensure_ascii=True)` (\uXXXX + surrogate pairs).
//
// Field order matches Event.to_dict: event, entityType, entityId,
// eventTime, properties, creationTime, eventId, targetEntityType,
// targetEntityId, tags (when non-empty), prId (when present).
//
// All-or-nothing fidelity contract: on ANY surprise (unloadable sqlite,
// NULL in a NOT NULL column, invalid UTF-8, suspicious stored JSON) the
// function returns nonzero and the caller re-runs the whole export
// through the Python path — unlike pio_import.cpp there is no per-line
// fallback, because a partial output file is useless.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include <dlfcn.h>

namespace {

// -- minimal sqlite3 C API surface (stable ABI, declared locally) -------
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
constexpr int kSqliteOk = 0;
constexpr int kSqliteRow = 100;
constexpr int kOpenReadonly = 0x00000001;
constexpr int kColNull = 5;

struct SqliteApi {
    int (*open_v2)(const char*, sqlite3**, int, const char*);
    int (*close_v2)(sqlite3*);
    int (*prepare_v2)(sqlite3*, const char*, int, sqlite3_stmt**,
                      const char**);
    int (*step)(sqlite3_stmt*);
    int (*finalize)(sqlite3_stmt*);
    int (*bind_int64)(sqlite3_stmt*, int, long long);
    int (*column_type)(sqlite3_stmt*, int);
    const unsigned char* (*column_text)(sqlite3_stmt*, int);
    int (*column_bytes)(sqlite3_stmt*, int);
    const char* (*errmsg)(sqlite3*);
    bool ok = false;
};

const SqliteApi& sqlite_api() {
    static SqliteApi api = [] {
        SqliteApi a;
        void* h = dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_GLOBAL);
        if (!h) h = dlopen("libsqlite3.so", RTLD_NOW | RTLD_GLOBAL);
        if (!h) return a;
        auto sym = [&](const char* name) { return dlsym(h, name); };
        a.open_v2 = reinterpret_cast<decltype(a.open_v2)>(
            sym("sqlite3_open_v2"));
        a.close_v2 = reinterpret_cast<decltype(a.close_v2)>(
            sym("sqlite3_close_v2"));
        a.prepare_v2 = reinterpret_cast<decltype(a.prepare_v2)>(
            sym("sqlite3_prepare_v2"));
        a.step = reinterpret_cast<decltype(a.step)>(sym("sqlite3_step"));
        a.finalize = reinterpret_cast<decltype(a.finalize)>(
            sym("sqlite3_finalize"));
        a.bind_int64 = reinterpret_cast<decltype(a.bind_int64)>(
            sym("sqlite3_bind_int64"));
        a.column_type = reinterpret_cast<decltype(a.column_type)>(
            sym("sqlite3_column_type"));
        a.column_text = reinterpret_cast<decltype(a.column_text)>(
            sym("sqlite3_column_text"));
        a.column_bytes = reinterpret_cast<decltype(a.column_bytes)>(
            sym("sqlite3_column_bytes"));
        a.errmsg = reinterpret_cast<decltype(a.errmsg)>(sym("sqlite3_errmsg"));
        a.ok = a.open_v2 && a.close_v2 && a.prepare_v2 && a.step &&
               a.finalize && a.bind_int64 && a.column_type &&
               a.column_text && a.column_bytes && a.errmsg;
        return a;
    }();
    return api;
}

thread_local std::string g_error;

// Append `s` (UTF-8, length n) to out as a Python-json.dumps
// (ensure_ascii=True) double-quoted string. Returns false on invalid
// UTF-8 or codepoints > U+10FFFF.
bool append_json_string(std::string& out, const unsigned char* s,
                        int n) {
    static const char* hex = "0123456789abcdef";
    out += '"';
    int i = 0;
    while (i < n) {
        unsigned char c = s[i];
        if (c < 0x80) {
            switch (c) {
                case '"': out += "\\\""; break;
                case '\\': out += "\\\\"; break;
                case '\b': out += "\\b"; break;
                case '\f': out += "\\f"; break;
                case '\n': out += "\\n"; break;
                case '\r': out += "\\r"; break;
                case '\t': out += "\\t"; break;
                default:
                    if (c < 0x20) {
                        out += "\\u00";
                        out += hex[c >> 4];
                        out += hex[c & 0xf];
                    } else {
                        out += static_cast<char>(c);
                    }
            }
            ++i;
            continue;
        }
        // multi-byte UTF-8 → codepoint
        int extra;
        uint32_t cp;
        if ((c & 0xE0) == 0xC0) { extra = 1; cp = c & 0x1F; }
        else if ((c & 0xF0) == 0xE0) { extra = 2; cp = c & 0x0F; }
        else if ((c & 0xF8) == 0xF0) { extra = 3; cp = c & 0x07; }
        else return false;
        if (i + extra >= n) return false;
        for (int k = 1; k <= extra; ++k) {
            unsigned char cc = s[i + k];
            if ((cc & 0xC0) != 0x80) return false;
            cp = (cp << 6) | (cc & 0x3F);
        }
        i += extra + 1;
        if (cp > 0x10FFFF || (cp >= 0xD800 && cp <= 0xDFFF)) return false;
        auto emit4 = [&](uint32_t u) {
            out += "\\u";
            out += hex[(u >> 12) & 0xf];
            out += hex[(u >> 8) & 0xf];
            out += hex[(u >> 4) & 0xf];
            out += hex[u & 0xf];
        };
        if (cp < 0x10000) {
            emit4(cp);
        } else {  // surrogate pair, like Python's ensure_ascii
            cp -= 0x10000;
            emit4(0xD800 + (cp >> 10));
            emit4(0xDC00 + (cp & 0x3FF));
        }
    }
    out += '"';
    return true;
}

struct Col {
    const unsigned char* text;
    int bytes;
    bool is_null;
};

Col get_col(const SqliteApi& api, sqlite3_stmt* st, int idx) {
    Col c;
    c.is_null = api.column_type(st, idx) == kColNull;
    c.text = c.is_null ? nullptr : api.column_text(st, idx);
    c.bytes = c.is_null ? 0 : api.column_bytes(st, idx);
    return c;
}

}  // namespace

extern "C" {

const char* pio_export_error() { return g_error.c_str(); }

// Export app/channel events as JSON lines, byte-identical to the Python
// exporter. channel_id < 0 selects channel IS NULL. Returns 0 on
// success with *out_count set; nonzero = caller must use the Python
// path (g_error says why).
int pio_export_events(const char* db_path, const char* out_path,
                      long long app_id, long long channel_id,
                      long long* out_count) {
    const SqliteApi& api = sqlite_api();
    if (!api.ok) {
        g_error = "sqlite3 C API unavailable";
        return 1;
    }
    sqlite3* db = nullptr;
    if (api.open_v2(db_path, &db, kOpenReadonly, nullptr) != kSqliteOk) {
        g_error = db ? api.errmsg(db) : "cannot open db";
        if (db) api.close_v2(db);
        return 2;
    }
    // SELECT column order mirrors the schema; ORDER BY matches
    // storage/sqlite.py find() so line order is identical
    std::string sql =
        "SELECT id, event, entity_type, entity_id, target_entity_type, "
        "target_entity_id, properties, event_time, tags, pr_id, "
        "creation_time FROM events WHERE app_id=? AND ";
    sql += (channel_id < 0) ? "channel_id IS NULL" : "channel_id=?";
    sql += " ORDER BY event_time ASC, creation_time ASC";
    sqlite3_stmt* st = nullptr;
    if (api.prepare_v2(db, sql.c_str(), -1, &st, nullptr) != kSqliteOk) {
        g_error = api.errmsg(db);
        api.close_v2(db);
        return 3;
    }
    api.bind_int64(st, 1, app_id);
    if (channel_id >= 0) api.bind_int64(st, 2, channel_id);

    FILE* out = std::fopen(out_path, "wb");
    if (!out) {
        g_error = "cannot open output file";
        api.finalize(st);
        api.close_v2(db);
        return 4;
    }

    long long count = 0;
    int rc_out = 0;
    std::string line;
    line.reserve(1024);
    int rc;
    while ((rc = api.step(st)) == kSqliteRow) {
        Col id = get_col(api, st, 0);
        Col event = get_col(api, st, 1);
        Col etype = get_col(api, st, 2);
        Col eid = get_col(api, st, 3);
        Col ttype = get_col(api, st, 4);
        Col tid = get_col(api, st, 5);
        Col props = get_col(api, st, 6);
        Col etime = get_col(api, st, 7);
        Col tags = get_col(api, st, 8);
        Col prid = get_col(api, st, 9);
        Col ctime = get_col(api, st, 10);
        if (id.is_null || event.is_null || etype.is_null || eid.is_null ||
            props.is_null || etime.is_null || tags.is_null ||
            ctime.is_null || props.bytes < 2 || tags.bytes < 2 ||
            props.text[0] != '{' || tags.text[0] != '[') {
            g_error = "unexpected NULL / malformed stored JSON";
            rc_out = 5;
            break;
        }
        line.clear();
        line += "{\"event\": ";
        bool ok = append_json_string(line, event.text, event.bytes);
        line += ", \"entityType\": ";
        ok = ok && append_json_string(line, etype.text, etype.bytes);
        line += ", \"entityId\": ";
        ok = ok && append_json_string(line, eid.text, eid.bytes);
        line += ", \"eventTime\": ";
        ok = ok && append_json_string(line, etime.text, etime.bytes);
        line += ", \"properties\": ";
        line.append(reinterpret_cast<const char*>(props.text), props.bytes);
        line += ", \"creationTime\": ";
        ok = ok && append_json_string(line, ctime.text, ctime.bytes);
        line += ", \"eventId\": ";
        ok = ok && append_json_string(line, id.text, id.bytes);
        if (!ttype.is_null) {
            line += ", \"targetEntityType\": ";
            ok = ok && append_json_string(line, ttype.text, ttype.bytes);
        }
        if (!tid.is_null) {
            line += ", \"targetEntityId\": ";
            ok = ok && append_json_string(line, tid.text, tid.bytes);
        }
        if (!(tags.bytes == 2 && tags.text[1] == ']')) {
            line += ", \"tags\": ";
            line.append(reinterpret_cast<const char*>(tags.text),
                        tags.bytes);
        }
        if (!prid.is_null) {
            line += ", \"prId\": ";
            ok = ok && append_json_string(line, prid.text, prid.bytes);
        }
        if (!ok) {
            g_error = "invalid UTF-8 in stored text";
            rc_out = 6;
            break;
        }
        line += "}\n";
        if (std::fwrite(line.data(), 1, line.size(), out) != line.size()) {
            g_error = "short write to output file";
            rc_out = 7;
            break;
        }
        ++count;
    }
    if (rc_out == 0 && rc != 101 /* SQLITE_DONE */) {
        g_error = api.errmsg(db);
        rc_out = 8;
    }
    api.finalize(st);
    api.close_v2(db);
    if (std::fclose(out) != 0 && rc_out == 0) {
        g_error = "close failed";
        rc_out = 9;
    }
    if (rc_out != 0) std::remove(out_path);
    *out_count = count;
    return rc_out;
}

}  // extern "C"
