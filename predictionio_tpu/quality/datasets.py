"""Deterministic planted-factor MovieLens-like datasets with held-out splits.

No real MovieLens data ships on this image and there is no network, so
quality parity is measured on synthetic data whose *structure* matches the
real thing where it matters for ALS:

- a planted low-rank latent model + user/item biases + gaussian noise,
  quantized to half-star ratings (ML-20M's 0.5–5.0 scale);
- zipf item popularity and lognormal user activity (real rating logs are
  heavy-tailed on both axes — uniform draws would understate the ragged
  bucketing the solvers face);
- noise tuned so the best achievable held-out RMSE lands in the
  literature-anchor band for real ML-20M (~0.78–0.85, BASELINE.md
  "External anchors") — i.e. the recoverable-signal regime is realistic,
  not a noiseless matrix-completion toy.

Both ALS implementations (quality/mllib_als.py and ops/als.py) see the
exact same triplets and the exact same split, so metric deltas measure
implementation differences only.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class RatingSplit:
    """COO triplets, split once; `n_users`/`n_items` cover both halves."""

    train_u: np.ndarray
    train_i: np.ndarray
    train_r: np.ndarray
    test_u: np.ndarray
    test_i: np.ndarray
    test_r: np.ndarray
    n_users: int
    n_items: int

    @property
    def n_train(self) -> int:
        return len(self.train_u)

    @property
    def n_test(self) -> int:
        return len(self.test_u)


# named scales: (n_users, n_items, n_ratings) matching the ML-* shapes the
# driver configs cite (BASELINE.json configs 1 and 5)
SCALES = {
    "100k": (943, 1682, 100_000),
    "2m": (13_850, 2_700, 2_000_000),
    "20m": (138_500, 27_000, 20_000_000),
}


def _sample_pairs(rng, n_users, n_items, n_target):
    """Heavy-tailed (user, item) pairs, deduplicated: lognormal user
    activity × zipf item popularity. Oversamples then unique-ifies until
    the target count is met."""
    user_w = rng.lognormal(0.0, 1.0, n_users)
    user_p = user_w / user_w.sum()
    # cumulative-inverse sampling: rng.choice(p=...) is O(n) per draw batch
    # but fine at these sizes; searchsorted keeps it vectorized
    user_cdf = np.cumsum(user_p)
    pairs = np.zeros(0, np.int64)
    need = n_target
    while need > 0:
        m = int(need * 1.4) + 1024
        # clip: float cumsum can leave cdf[-1] a hair under 1.0, and a draw
        # above it would index one past the last user
        u = np.minimum(np.searchsorted(user_cdf, rng.random(m)),
                       n_users - 1).astype(np.int64)
        i = (rng.zipf(1.3, m) % n_items).astype(np.int64)
        new = np.unique(np.concatenate([pairs, u * n_items + i]))
        pairs = new
        need = n_target - len(pairs)
    rng.shuffle(pairs)
    pairs = pairs[:n_target]
    return (pairs // n_items).astype(np.int32), (pairs % n_items).astype(np.int32)


def synth_explicit(
    scale: str = "100k",
    rank_true: int = 32,
    noise: float = 0.78,
    test_frac: float = 0.1,
    seed: int = 0,
) -> RatingSplit:
    """Half-star ratings from a planted model:
    r = clip(round₂(μ + b_u + b_i + s·⟨u*, v*⟩ + ε), 0.5, 5).

    With `noise=0.78` the best achievable held-out RMSE is ≈0.80 at
    ML-100K scale (measured via quality/parity.py), matching the
    real-ML-20M literature anchor band.
    """
    n_users, n_items, n_ratings = SCALES[scale]
    rng = np.random.default_rng(seed)
    ui, ii = _sample_pairs(rng, n_users, n_items, n_ratings)

    U = rng.standard_normal((n_users, rank_true)) / np.sqrt(rank_true)
    V = rng.standard_normal((n_items, rank_true)) / np.sqrt(rank_true)
    bu = rng.normal(0.0, 0.35, n_users)
    bi = rng.normal(0.0, 0.35, n_items)
    latent_scale = 0.6 * np.sqrt(rank_true)  # latent-term std ≈ 0.6
    r_cont = (3.55 + bu[ui] + bi[ii]
              + latent_scale * np.einsum("ij,ij->i", U[ui], V[ii])
              + rng.normal(0.0, noise, n_ratings))
    r = np.clip(np.round(r_cont * 2.0) / 2.0, 0.5, 5.0).astype(np.float32)

    n_test = int(n_ratings * test_frac)
    perm = rng.permutation(n_ratings)
    te, tr = perm[:n_test], perm[n_test:]
    return RatingSplit(ui[tr], ii[tr], r[tr], ui[te], ii[te], r[te],
                       n_users, n_items)


def synth_implicit(
    scale: str = "100k",
    rank_true: int = 32,
    test_frac: float = 0.1,
    seed: int = 0,
) -> RatingSplit:
    """Binary interactions with planted preference structure: candidate
    pairs are drawn from the popularity/activity model, then accepted with
    probability σ(s·⟨u*, v*⟩), so a user's accepted items cluster in their
    latent neighborhood — rankable structure, unlike pure-popularity
    draws. Values are all 1.0 (view/buy counts collapse to presence);
    the split is a per-pair random hold-out and MAP@K is computed against
    the held-out positives with train items excluded."""
    n_users, n_items, n_ratings = SCALES[scale]
    rng = np.random.default_rng(seed + 1)
    U = rng.standard_normal((n_users, rank_true)) / np.sqrt(rank_true)
    V = rng.standard_normal((n_items, rank_true)) / np.sqrt(rank_true)
    latent_scale = 1.6 * np.sqrt(rank_true)

    user_w = rng.lognormal(0.0, 1.0, n_users)
    user_cdf = np.cumsum(user_w / user_w.sum())
    pairs = np.zeros(0, np.int64)
    while len(pairs) < n_ratings:
        m = int((n_ratings - len(pairs)) * 3.2) + 4096
        u = np.minimum(np.searchsorted(user_cdf, rng.random(m)),
                       n_users - 1).astype(np.int64)
        i = (rng.zipf(1.3, m) % n_items).astype(np.int64)
        score = latent_scale * np.einsum("ij,ij->i", U[u], V[i])
        keep = rng.random(m) < 1.0 / (1.0 + np.exp(-score))
        pairs = np.unique(np.concatenate([pairs, u[keep] * n_items + i[keep]]))
    rng.shuffle(pairs)
    pairs = pairs[:n_ratings]
    ui = (pairs // n_items).astype(np.int32)
    ii = (pairs % n_items).astype(np.int32)
    r = np.ones(len(pairs), np.float32)

    n_test = int(len(pairs) * test_frac)
    perm = rng.permutation(len(pairs))
    te, tr = perm[:n_test], perm[n_test:]
    return RatingSplit(ui[tr], ii[tr], r[tr], ui[te], ii[te], r[te],
                       n_users, n_items)
