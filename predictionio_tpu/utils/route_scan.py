"""Shared AST helpers for the CI gates: resolve router registrations.

The serving/ingest/hotpath gates hold invariants about *the handler that
serves a route* ("the /queries.json handler must call handle_query",
"no bare json.dumps on the hot path"). Before the event-loop transport,
routes lived inside `do_*` methods and a gate could scan those directly;
now they are plain functions registered on a `Router` at construction:

    router.post("/queries.json", self._handle_query, blocking=True)
    r.add_prefix("POST", "/webhooks/", ".json", self._handle_webhook, ...)

This module finds those registration calls in a parsed module and
resolves the registered callables back to their FunctionDef (or Lambda)
nodes, so the gates can keep asserting on the handler bodies without
importing anything.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

# Router registration spellings: method name → (HTTP verb or None for
# "first arg is the verb", index of the path argument, index of the
# handler argument).
_VERB_METHODS = {"get": "GET", "post": "POST", "delete": "DELETE",
                 "put": "PUT"}


def _handler_name(node: ast.AST) -> Optional[str]:
    """The registered callable's terminal name: `self._handle_query` and
    `_handle_query` both resolve to "_handle_query"; lambdas return
    "<lambda>"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def registrations(tree: ast.AST) -> Iterator[Tuple[str, str, str, ast.AST]]:
    """Yield (http_method, path, handler_name, handler_node) for every
    Router registration call in the module. `path` is the exact path for
    get/post/delete/add and "<prefix>*<suffix>" for add_prefix."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr in _VERB_METHODS and len(node.args) >= 2:
            path = _const_str(node.args[0])
            name = _handler_name(node.args[1])
            # require a leading-slash path AND a resolvable handler so
            # unrelated `.get("/x", default)` dict lookups don't match
            if path and path.startswith("/") and name:
                yield _VERB_METHODS[attr], path, name, node.args[1]
        elif attr == "add" and len(node.args) >= 3:
            method = _const_str(node.args[0])
            path = _const_str(node.args[1])
            name = _handler_name(node.args[2])
            if method and path and path.startswith("/") and name:
                yield method.upper(), path, name, node.args[2]
        elif attr == "add_prefix" and len(node.args) >= 4:
            method = _const_str(node.args[0])
            prefix = _const_str(node.args[1])
            suffix = _const_str(node.args[2])
            name = _handler_name(node.args[3])
            if method and prefix and prefix.startswith("/") and name:
                yield (method.upper(), f"{prefix}*{suffix or ''}", name,
                       node.args[3])


def function_defs(tree: ast.AST) -> dict:
    """name → FunctionDef for every function in the module (module level
    and inside classes; last definition wins on collisions)."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def handlers_for(tree: ast.AST, path: str,
                 method: Optional[str] = None) -> List[ast.AST]:
    """FunctionDef/Lambda nodes registered for `path` (exact match on
    the registered path; prefix routes match their "<prefix>*<suffix>"
    spelling), optionally filtered by HTTP method."""
    defs = function_defs(tree)
    out: List[ast.AST] = []
    for m, p, name, handler_node in registrations(tree):
        if p != path or (method is not None and m != method.upper()):
            continue
        if isinstance(handler_node, ast.Lambda):
            out.append(handler_node)
        elif name in defs:
            out.append(defs[name])
    return out


def attr_calls(fn: ast.AST) -> set:
    """Attribute-call names inside a function body (x.y() → "y")."""
    calls = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            calls.add(node.func.attr)
    return calls


def reachable_functions(tree: ast.AST, roots: List[ast.AST],
                        max_depth: int = 4) -> List[ast.AST]:
    """The same-module call closure of `roots`: the root handlers plus
    every module-local function they (transitively) call by terminal
    name. Cross-module calls are out of scope — gates assert per-file."""
    defs = function_defs(tree)
    seen_names: set = set()
    out: List[ast.AST] = []
    frontier = list(roots)
    for _ in range(max_depth):
        next_frontier: List[ast.AST] = []
        for fn in frontier:
            out.append(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = None
                if isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                elif isinstance(node.func, ast.Name):
                    name = node.func.id
                if name and name in defs and name not in seen_names:
                    seen_names.add(name)
                    next_frontier.append(defs[name])
        if not next_frontier:
            break
        frontier = next_frontier
    return out
