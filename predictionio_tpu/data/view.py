"""Batch views — pre-aggregated snapshots of an app's event stream.

Parity with the reference's 0.9.x batch-view layer
(«data/.../data/view/{LBatchView,PBatchView}.scala :: LBatchView,
PBatchView, writeToPropsMap» — SURVEY.md §2.2 [U]): a view is bound to an
(app, channel, time-window) and offers (a) the raw ordered event stream,
(b) `$set/$unset/$delete`-folded property maps per entity type, and (c) an
ordered per-entity fold for custom aggregations (the reference's
`aggregateByEntityOrdered`).

TPU-native twist: where the reference's `PBatchView` returns RDDs, our
parallel view returns **columnar numpy batches** (`EventColumns`) —
integer-coded entity/event ids plus a float property column — ready for
`jax.device_put` onto a sharded mesh axis. That is the device-feeding
analogue of "events as a distributed dataset": the expensive string→int
work happens once, host-side, and everything after it is dense.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from typing import Callable, Optional, Sequence, TypeVar

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.columnar import SPECIAL_EVENTS as _SPECIAL
from predictionio_tpu.data.columnar import EventColumns
from predictionio_tpu.data.datamap import PropertyMap, aggregate_properties
from predictionio_tpu.data.events import Event
from predictionio_tpu.data.store import EventStore

T = TypeVar("T")



def _ordered(events: Sequence[Event]) -> list[Event]:
    return sorted(events,
                  key=lambda e: (e.event_time, e.creation_time,
                                 e.event_id or ""))


class LBatchView:
    """Local (host-side) batch view over one app/channel/time-window.

    Mirrors «LBatchView» [U]: the event list is fetched once and cached;
    all aggregations below run over that snapshot.
    """

    def __init__(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        store: Optional[EventStore] = None,
    ):
        self.app_name = app_name
        self.channel_name = channel_name
        self.start_time = start_time
        self.until_time = until_time
        self._store = store or EventStore()
        self._events: Optional[list[Event]] = None

    @property
    def events(self) -> list[Event]:
        """The window's events, ordered by (event_time, creation_time)."""
        if self._events is None:
            self._events = _ordered(
                self._store.find(
                    app_name=self.app_name,
                    channel_name=self.channel_name,
                    start_time=self.start_time,
                    until_time=self.until_time,
                )
            )
        return self._events

    def aggregate_properties(self, entity_type: str) -> dict[str, PropertyMap]:
        """`writeToPropsMap` [U]: folded `$set/$unset/$delete` entity state."""
        return aggregate_properties(
            [
                e
                for e in self.events
                if e.entity_type == entity_type and e.event in _SPECIAL
            ]
        )

    def aggregate_by_entity_ordered(
        self,
        predicate: Callable[[Event], bool],
        init: T,
        op: Callable[[T, Event], T],
    ) -> dict[str, T]:
        """`aggregateByEntityOrdered` [U]: time-ordered per-entity fold of
        the events matching `predicate` — e.g. last-N-actions features or
        Markov-chain transition counts."""
        out: dict[str, T] = {}
        for e in self.events:
            if not predicate(e):
                continue
            out[e.entity_id] = op(out.get(e.entity_id, init), e)
        return out


class PBatchView(LBatchView):
    """Parallel batch view: columnar/device-feeding variant of `LBatchView`.

    Replaces the reference `PBatchView`'s RDD outputs [U] with dense numpy
    columns; callers `jax.device_put` the columns with a `NamedSharding`
    over the mesh's `data` axis (see parallel/distributed.py) to get the
    sharded-dataset semantics the RDD provided.
    """

    def to_columns(
        self,
        event_names: Optional[list[str]] = None,
        value_key: Optional[str] = None,
    ) -> EventColumns:
        """Columnar form of the view's window.

        While the view's event snapshot is unmaterialized, the scan is
        pushed down to the storage backend (`LEvents.find_columnar`: SQL
        window-function id coding / the C++ reader — no per-event Python
        at any scale). Once `self.events` has been accessed, the columns
        are folded from that cached snapshot instead, preserving the
        view's one-snapshot coherence with `aggregate_properties` et al.
        under concurrent ingestion.
        """
        if self._events is not None:
            from predictionio_tpu.data.columnar import columns_from_events

            return columns_from_events(self._events, event_names, value_key)
        return self._store.find_columnar(
            app_name=self.app_name,
            channel_name=self.channel_name,
            start_time=self.start_time,
            until_time=self.until_time,
            event_names=event_names,
            value_key=value_key,
        )

    def property_matrix(
        self, entity_type: str, keys: list[str]
    ) -> tuple[np.ndarray, BiMap]:
        """Dense (n_entities × len(keys)) float32 matrix of folded numeric
        properties (NaN where unset) + entity BiMap — the feature-matrix
        analogue of `writeToPropsMap` for classification-style templates."""
        props = self.aggregate_properties(entity_type)
        bimap = BiMap.string_int(sorted(props))
        mat = np.full((len(bimap), len(keys)), np.nan, np.float32)
        for eid, p in props.items():
            row = bimap[eid]
            for j, k in enumerate(keys):
                v = p.get_opt(k)
                if v is not None:
                    mat[row, j] = float(v)
        return mat, bimap
