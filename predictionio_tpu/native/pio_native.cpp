// Native host-side data loader for predictionio_tpu.
//
// The reference delegates its hot host paths to the JVM/Spark (RDD
// shuffles, HBase scans — SURVEY.md §2.5: its only native code lives in
// dependencies like netlib/netty). The TPU rebuild's equivalent hot host
// path is the ragged-COO → padded-dense-bucket transform that feeds the
// device (ops/als.py::bucket_ragged): O(nnz) work per train that was a
// Python loop. This file implements it in C++ behind a two-phase C ABI
// (plan → caller allocates numpy buffers → fill), bound via ctypes
// (predictionio_tpu/native/__init__.py) with the numpy implementation as
// fallback. Output is bit-identical to the Python path:
//   - buckets ordered by ascending capacity (power-of-two, >= min_cap)
//   - rows within a bucket ordered by ascending row id
//   - entries within a row sorted by column id (stable; truncation to
//     max_cap keeps the first entries in original order, then sorts)
//   - row count padded to a multiple of row_multiple with sentinel
//     row id == n_rows and zeroed cols/vals/mask
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py; no deps).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <vector>

namespace {

// Cap ladder: min_cap, then ceil(prev*growth/8)*8 — growth 2.0 reproduces
// the round-1 power-of-two caps exactly; smaller growth (e.g. 1.5) trades
// more bucket shapes (compile time) for less padding in the gather
// (measured 1.08x epoch at 2M rank-64, BASELINE.md). The arithmetic is
// IEEE double, identical to the numpy path's — bit-identical caps.
std::vector<int64_t> build_ladder(int64_t max_count, int64_t min_cap,
                                  double growth) {
    std::vector<int64_t> ladder{min_cap};
    while (ladder.back() < max_count) {
        int64_t next =
            static_cast<int64_t>(std::ceil(ladder.back() * growth / 8.0)) * 8;
        if (next <= ladder.back()) next = ladder.back() + 8;
        ladder.push_back(next);
    }
    return ladder;
}

int64_t ladder_cap(const std::vector<int64_t>& ladder, int64_t count,
                   int64_t max_cap) {
    int64_t c = count < 1 ? 1 : count;
    auto it = std::lower_bound(ladder.begin(), ladder.end(), c);
    int64_t cap = it == ladder.end() ? ladder.back() : *it;
    if (max_cap > 0 && cap > max_cap) cap = max_cap;
    return cap;
}

struct Plan {
    std::vector<int64_t> counts;        // per row id, truncated to max_cap
    std::vector<int64_t> ladder;        // cap ladder (growth-dependent)
    std::vector<int64_t> caps;          // distinct caps ascending
    std::vector<int64_t> rpads;         // padded row count per bucket
    std::vector<int64_t> nrows_real;    // real rows per bucket
};

// returns false if any row id is outside [0, n_rows) — the caller then
// falls back to the numpy path rather than silently dropping entries
// (keeps behavior identical with and without a toolchain)
bool build_plan(const int32_t* rows, int64_t n, int32_t n_rows,
                int64_t row_multiple, int64_t max_cap, int64_t min_cap,
                double growth, Plan& plan) {
    plan.counts.assign(static_cast<size_t>(n_rows) + 1, 0);
    int64_t max_count = 1;
    for (int64_t k = 0; k < n; ++k) {
        int32_t r = rows[k];
        if (r < 0 || r >= n_rows) return false;
        plan.counts[r] += 1;
    }
    for (int32_t r = 0; r < n_rows; ++r) {
        if (max_cap > 0 && plan.counts[r] > max_cap) plan.counts[r] = max_cap;
        if (plan.counts[r] > max_count) max_count = plan.counts[r];
    }
    plan.ladder = build_ladder(max_count, min_cap, growth);
    std::map<int64_t, int64_t> rows_per_cap;  // ordered: caps ascending
    for (int32_t r = 0; r < n_rows; ++r) {
        if (plan.counts[r] == 0) continue;
        rows_per_cap[ladder_cap(plan.ladder, plan.counts[r], max_cap)] += 1;
    }
    plan.caps.clear();
    plan.rpads.clear();
    plan.nrows_real.clear();
    for (const auto& kv : rows_per_cap) {
        int64_t r = kv.second;
        int64_t rm = row_multiple > 0 ? row_multiple : 1;
        plan.caps.push_back(kv.first);
        plan.rpads.push_back(((r + rm - 1) / rm) * rm);
        plan.nrows_real.push_back(r);
    }
    return true;
}

}  // namespace

extern "C" {

// Phase 1: returns the number of buckets (or -1 on out-of-range row ids);
// writes per-bucket capacity and padded row count into out_caps/out_rpads
// (each sized >= 63).
int64_t pio_plan_buckets(const int32_t* rows, int64_t n, int32_t n_rows,
                         int64_t row_multiple, int64_t max_cap,
                         int64_t min_cap, double growth, int64_t* out_caps,
                         int64_t* out_rpads) {
    Plan plan;
    if (!build_plan(rows, n, n_rows, row_multiple, max_cap, min_cap, growth,
                    plan))
        return -1;
    // the caller allocates 63-slot output buffers (the old power-of-two
    // bound); a small growth factor on heavy-tailed data can exceed that
    // — bail to the numpy path rather than write past the buffers
    if (plan.caps.size() > 63) return -1;
    for (size_t b = 0; b < plan.caps.size(); ++b) {
        out_caps[b] = plan.caps[b];
        out_rpads[b] = plan.rpads[b];
    }
    return static_cast<int64_t>(plan.caps.size());
}

// Phase 2: fill caller-allocated flat buffers.
//   rows_out: [sum(rpads)] int32
//   cols_out/vals_out/mask_out: [sum(rpads[b] * caps[b])]
// Layout: buckets in ascending-cap order, concatenated.
// Returns 0 on success, -1 if the derived plan disagrees with the
// caller's buffer layout (caller bug).
int64_t pio_fill_buckets(const int32_t* rows, const int32_t* cols,
                         const float* vals, int64_t n, int32_t n_rows,
                         int64_t row_multiple, int64_t max_cap,
                         int64_t min_cap, double growth, int64_t n_buckets,
                         const int64_t* caps, const int64_t* rpads,
                         int32_t* rows_out, int32_t* cols_out,
                         float* vals_out, float* mask_out) {
    Plan plan;
    if (!build_plan(rows, n, n_rows, row_multiple, max_cap, min_cap, growth,
                    plan))
        return -1;
    if (static_cast<int64_t>(plan.caps.size()) != n_buckets) return -1;
    for (int64_t b = 0; b < n_buckets; ++b) {
        if (plan.caps[b] != caps[b] || plan.rpads[b] != rpads[b]) return -1;
    }

    // flat offsets; bucket lookup is by cap value (caps ascending)
    std::vector<int64_t> row_off(n_buckets), elem_off(n_buckets);
    int64_t ro = 0, eo = 0;
    for (int64_t b = 0; b < n_buckets; ++b) {
        row_off[b] = ro;
        elem_off[b] = eo;
        ro += rpads[b];
        eo += rpads[b] * caps[b];
    }
    auto bucket_of_cap = [&](int64_t cap) -> int64_t {
        auto it = std::lower_bound(plan.caps.begin(), plan.caps.end(), cap);
        if (it == plan.caps.end() || *it != cap) return -1;
        return static_cast<int64_t>(it - plan.caps.begin());
    };

    // sentinel-fill rows_out; zero the element buffers
    for (int64_t i = 0; i < ro; ++i) rows_out[i] = n_rows;
    std::memset(cols_out, 0, static_cast<size_t>(eo) * sizeof(int32_t));
    std::memset(vals_out, 0, static_cast<size_t>(eo) * sizeof(float));
    std::memset(mask_out, 0, static_cast<size_t>(eo) * sizeof(float));

    // slot of each real row within its bucket: ascending row id order
    std::vector<int64_t> row_slot(static_cast<size_t>(n_rows), -1);
    std::vector<int64_t> next_slot(n_buckets, 0);
    std::vector<int64_t> row_bucket(static_cast<size_t>(n_rows), -1);
    for (int32_t r = 0; r < n_rows; ++r) {
        if (plan.counts[r] == 0) continue;
        int64_t b = bucket_of_cap(
            ladder_cap(plan.ladder, plan.counts[r], max_cap));
        if (b < 0) return -1;
        row_bucket[r] = b;
        row_slot[r] = next_slot[b]++;
        rows_out[row_off[b] + row_slot[r]] = r;
    }

    // scatter entries in original order (stable), truncating at count cap
    std::vector<int64_t> filled(static_cast<size_t>(n_rows), 0);
    for (int64_t k = 0; k < n; ++k) {
        int32_t r = rows[k];
        if (r < 0 || r >= n_rows) continue;
        if (filled[r] >= plan.counts[r]) continue;  // max_cap truncation
        int64_t b = row_bucket[r];
        int64_t idx = elem_off[b] + row_slot[r] * caps[b] + filled[r];
        cols_out[idx] = cols[k];
        vals_out[idx] = vals[k];
        mask_out[idx] = 1.0f;
        filled[r] += 1;
    }

    // sort each padded row by column id (stable, matching numpy argsort
    // kind="stable"): Gram/RHS sums are order-invariant and monotonic
    // gather indices are ~20x faster on TPU than random ones
    {
        std::vector<int64_t> perm;
        std::vector<int32_t> tc;
        std::vector<float> tv, tm;
        for (int64_t b = 0; b < n_buckets; ++b) {
            const int64_t cap = caps[b];
            perm.resize(static_cast<size_t>(cap));
            tc.resize(static_cast<size_t>(cap));
            tv.resize(static_cast<size_t>(cap));
            tm.resize(static_cast<size_t>(cap));
            for (int64_t rr = 0; rr < rpads[b]; ++rr) {
                const int64_t base = elem_off[b] + rr * cap;
                for (int64_t j = 0; j < cap; ++j) perm[j] = j;
                // perm starts as the identity, so tie-breaking on the
                // index under plain sort IS the stable order — without
                // stable_sort's per-call temp-buffer allocation
                std::sort(perm.begin(), perm.end(),
                          [&](int64_t x, int64_t y) {
                              const int32_t cx = cols_out[base + x];
                              const int32_t cy = cols_out[base + y];
                              return cx != cy ? cx < cy : x < y;
                          });
                for (int64_t j = 0; j < cap; ++j) {
                    tc[j] = cols_out[base + perm[j]];
                    tv[j] = vals_out[base + perm[j]];
                    tm[j] = mask_out[base + perm[j]];
                }
                std::memcpy(cols_out + base, tc.data(),
                            static_cast<size_t>(cap) * sizeof(int32_t));
                std::memcpy(vals_out + base, tv.data(),
                            static_cast<size_t>(cap) * sizeof(float));
                std::memcpy(mask_out + base, tm.data(),
                            static_cast<size_t>(cap) * sizeof(float));
            }
        }
    }
    return 0;
}

}  // extern "C"
