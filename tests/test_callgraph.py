"""The project call graph (analysis/callgraph.py) and the whole-program
lock graph built on it (analysis/lockgraph.py): symbol/alias/method
resolution, bounded reachability with witness chains, lock-identity
resolution including constructor injection, and cycle detection."""

import os
import textwrap

from predictionio_tpu.analysis import callgraph, engine, lockgraph
from predictionio_tpu.analysis.engine import Project

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, src):
    (tmp_path / name).write_text(textwrap.dedent(src))


class TestSymbolTable:
    def test_functions_methods_and_nested_get_qualnames(self, tmp_path):
        _write(tmp_path, "m.py", """
            def top():
                def inner():
                    pass
                return inner

            class C:
                def meth(self):
                    pass
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        fids = set(cg.funcs)
        assert "m.py::top" in fids
        assert "m.py::top.<locals>.inner" in fids
        assert "m.py::C.meth" in fids
        assert cg.funcs["m.py::C.meth"].cls == "C"
        assert "m.py::C" in cg.classes

    def test_graph_is_cached_per_project(self):
        proj = Project(FIXTURES)
        assert callgraph.get(proj) is callgraph.get(proj)


class TestResolution:
    def test_cross_module_import_and_alias(self, tmp_path):
        _write(tmp_path, "db.py", """
            def query():
                pass
        """)
        _write(tmp_path, "app.py", """
            import db
            from db import query as q

            def via_module():
                db.query()

            def via_alias():
                q()
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        assert [s.callee for s in cg.edges["app.py::via_module"]] == \
            ["db.py::query"]
        assert [s.callee for s in cg.edges["app.py::via_alias"]] == \
            ["db.py::query"]

    def test_self_method_through_base_class(self, tmp_path):
        _write(tmp_path, "base.py", """
            class Base:
                def helper(self):
                    pass
        """)
        _write(tmp_path, "impl.py", """
            from base import Base

            class Impl(Base):
                def run(self):
                    self.helper()
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        assert [s.callee for s in cg.edges["impl.py::Impl.run"]] == \
            ["base.py::Base.helper"]

    def test_self_attr_typed_field_method(self, tmp_path):
        _write(tmp_path, "store.py", """
            class Store:
                def load(self):
                    pass
        """)
        _write(tmp_path, "plane.py", """
            from store import Store

            class Plane:
                def __init__(self):
                    self.store = Store()
                def serve(self):
                    self.store.load()
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        callees = {s.callee for s in cg.edges["plane.py::Plane.serve"]}
        assert "store.py::Store.load" in callees

    def test_class_call_resolves_to_init(self, tmp_path):
        _write(tmp_path, "m.py", """
            class Thing:
                def __init__(self):
                    pass

            def make():
                return Thing()
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        assert [s.callee for s in cg.edges["m.py::make"]] == \
            ["m.py::Thing.__init__"]


class TestReachability:
    def test_witness_chain_spans_modules(self):
        cg = callgraph.get(Project(FIXTURES))
        root = "xmod_routes.py::XModAPI._handle_report"
        hits = {fs.fid: chain for fs, chain in cg.reachable(root)}
        assert root in hits and hits[root] == ()
        chain = hits["xmod_db.py::fetch_rows"]
        assert [fid for fid, _line in chain] == \
            [root, "xmod_helper.py::load_report"]
        rendered = cg.render_chain(chain, cg.funcs["xmod_db.py::fetch_rows"])
        assert "XModAPI._handle_report (xmod_routes.py:" in rendered
        assert rendered.endswith("fetch_rows")

    def test_max_depth_bounds_the_closure(self, tmp_path):
        _write(tmp_path, "chain.py", """
            def f0():
                f1()
            def f1():
                f2()
            def f2():
                f3()
            def f3():
                pass
        """)
        cg = callgraph.get(Project(str(tmp_path)))
        shallow = {fs.name for fs, _ in cg.reachable("chain.py::f0",
                                                     max_depth=2)}
        assert shallow == {"f0", "f1", "f2"}
        deep = {fs.name for fs, _ in cg.reachable("chain.py::f0")}
        assert deep == {"f0", "f1", "f2", "f3"}


class TestLockGraph:
    def test_fixture_inversion_is_a_cycle(self):
        lg = lockgraph.get(Project(FIXTURES))
        cycles = lg.cycles()
        assert any(
            all(any(name in lbl for lbl in cyc)
                for name in ("_lock_a", "_lock_b"))
            for cyc in cycles), cycles

    def test_cross_module_lock_edge(self, tmp_path):
        _write(tmp_path, "stock.py", """
            import threading

            class Stock:
                def __init__(self):
                    self._lock = threading.Lock()
                def adjust(self):
                    with self._lock:
                        pass
        """)
        _write(tmp_path, "orders.py", """
            import threading
            from stock import Stock

            class Orders:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.stock = Stock()
                def place(self):
                    with self._lock:
                        self.stock.adjust()
        """)
        lg = lockgraph.get(Project(str(tmp_path)))
        assert ("orders.py:Orders._lock", "stock.py:Stock._lock") in \
            lg.edge_set()
        assert lg.cycles() == []

    def test_constructor_injected_lock_resolves_to_true_site(self):
        # DeltaSwapper holds a lock handed in by its creator; the graph
        # must resolve it to PredictionServer._state_lock, not guess
        proj = Project(REPO_ROOT, subdirs=engine.DEFAULT_SUBDIRS)
        lg = lockgraph.get(proj)
        inners = {b for (a, b) in lg.edge_set()
                  if "OnlinePlane._fold_lock" in a}
        assert any("PredictionServer._state_lock" in b for b in inners), \
            sorted(lg.edge_set())

    def test_live_tree_has_no_lock_cycle(self):
        proj = Project(REPO_ROOT, subdirs=engine.DEFAULT_SUBDIRS)
        assert lockgraph.get(proj).cycles() == []
