"""Route dispatch tables for the HTTP services.

The pre-event-loop servers resolved routes with `self.path ==` chains
inside `do_GET`/`do_POST` — re-parsed per request, untypeable by the CI
gates, and welded to BaseHTTPRequestHandler. A `Router` is the
replacement: handlers are plain functions `fn(Request) -> Response`
registered once at server construction with their route template and a
`blocking` flag (True = the body may block on the device/storage, so the
event loop runs it on its worker pool instead of the loop thread).

One dispatch table serves BOTH transports:

- the selector event loop (utils/httploop.py) — the default;
- a thin `JsonRequestHandler` adapter (`handler_from_router`) — the
  `PIO_HTTP_LOOP=0` escape hatch, instrumented by the classic class
  middleware, so a transport regression never strands a deploy.

Handlers deal only in `Request`/`Response`; everything socket-shaped
stays in the transports.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from predictionio_tpu.utils import fastjson


class Headers:
    """Case-insensitive read-only header view (keys stored lowercase).

    Quacks like the email.message.Message the old handlers read from:
    `.get(name, default)` with case-insensitive names."""

    __slots__ = ("_d",)

    def __init__(self, d: Optional[dict] = None):
        self._d = d if d is not None else {}

    def get(self, name: str, default=None):
        return self._d.get(name.lower(), default)

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._d

    def __iter__(self):
        return iter(self._d)

    def items(self):
        return self._d.items()

    def __repr__(self):
        return f"Headers({self._d!r})"


EMPTY_HEADERS = Headers({})


class Request:
    """One parsed HTTP request, transport-independent.

    `_t_recv/_t_parsed/_t_queued` are monotonic stamps the event loop
    writes so the middleware can record http.parse / http.dispatch spans
    on the handler's timeline without the parser knowing about spans."""

    __slots__ = ("method", "target", "path", "headers", "body",
                 "_params", "_t_recv", "_t_parsed", "_t_queued")

    def __init__(self, method: str, target: str, headers: Headers,
                 body: bytes, path: Optional[str] = None):
        self.method = method
        self.target = target          # raw request target incl. query
        self.path = path if path is not None else urlparse(target).path
        self.headers = headers
        self.body = body
        self._params: Optional[dict] = None
        self._t_recv = 0.0
        self._t_parsed = 0.0
        self._t_queued = 0.0

    @property
    def params(self) -> dict:
        """First-value query parameters (the old `_query()` helper)."""
        if self._params is None:
            qs = parse_qs(urlparse(self.target).query)
            self._params = {k: v[0] for k, v in qs.items()}
        return self._params


class Response:
    """One response: status + headers + a body that is either prebuilt
    bytes or a payload rendered lazily by `render_body()` — lazily so the
    transport can time encoding as its own flight-recorder span and so
    interned static bodies skip encoding entirely."""

    __slots__ = ("status", "body", "payload", "encoder", "headers",
                 "content_type", "close", "on_sent")

    def __init__(self, status: int, *, body: Optional[bytes] = None,
                 payload=None, encoder: Optional[Callable] = None,
                 headers: Optional[dict] = None,
                 content_type: str = "application/json; charset=utf-8",
                 close: bool = False):
        self.status = status
        self.body = body
        self.payload = payload
        self.encoder = encoder
        self.headers = headers
        self.content_type = content_type
        self.close = close          # force Connection: close after sending
        self.on_sent: Optional[Callable] = None   # runs after the bytes hit the socket

    @classmethod
    def json(cls, status: int, payload, headers: Optional[dict] = None,
             encoder: Optional[Callable] = None) -> "Response":
        return cls(status, payload=payload, headers=headers, encoder=encoder)

    @classmethod
    def message(cls, status: int, message: str,
                headers: Optional[dict] = None) -> "Response":
        """`{"message": ...}` through the interned-body cache."""
        return cls(status, body=fastjson.message_body(message),
                   headers=headers)

    @classmethod
    def html(cls, status: int, html_body: str) -> "Response":
        return cls(status, body=html_body.encode(),
                   content_type="text/html; charset=utf-8")

    def render_body(self) -> bytes:
        if self.body is None:
            if self.encoder is not None:
                self.body = self.encoder(self.payload)
            else:
                self.body = fastjson.dumps_bytes(self.payload)
        return self.body


class Route:
    __slots__ = ("fn", "template", "blocking")

    def __init__(self, fn: Callable[[Request], Response], template: str,
                 blocking: bool):
        self.fn = fn
        self.template = template
        self.blocking = blocking


class Router:
    """Pre-parsed dispatch table: exact paths resolve with one dict
    lookup, prefix routes (`/events/<id>.json`) with a short scan.
    Registered once at server construction — never rebuilt per request."""

    def __init__(self):
        self._exact: Dict[Tuple[str, str], Route] = {}
        self._prefix: Dict[str, List[Tuple[str, str, Route]]] = {}
        self._methods: set = set()

    # -- registration ------------------------------------------------------
    def add(self, method: str, path: str, fn, *, blocking: bool = False,
            template: Optional[str] = None) -> None:
        method = method.upper()
        self._methods.add(method)
        self._exact[(method, path)] = Route(fn, template or path, blocking)

    def add_prefix(self, method: str, prefix: str, suffix: str, fn, *,
                   template: str, blocking: bool = False) -> None:
        method = method.upper()
        self._methods.add(method)
        self._prefix.setdefault(method, []).append(
            (prefix, suffix, Route(fn, template, blocking)))

    def get(self, path: str, fn, **kw) -> None:
        self.add("GET", path, fn, **kw)

    def post(self, path: str, fn, **kw) -> None:
        self.add("POST", path, fn, **kw)

    def delete(self, path: str, fn, **kw) -> None:
        self.add("DELETE", path, fn, **kw)

    # -- dispatch ----------------------------------------------------------
    def handles_method(self, method: str) -> bool:
        return method in self._methods

    def lookup(self, method: str, path: str) -> Optional[Route]:
        route = self._exact.get((method, path))
        if route is not None:
            return route
        for prefix, suffix, r in self._prefix.get(method, ()):
            if path.startswith(prefix) and path.endswith(suffix):
                return r
        return None


def path_param(path: str, prefix: str, suffix: str) -> str:
    """Decode the variable segment of a prefix route
    (`/events/<id>.json` → id)."""
    return unquote(path[len(prefix):len(path) - len(suffix)])


NOT_FOUND = Response(404, body=fastjson.message_body("Not Found"))


def _fallback_404(req: Request) -> Response:
    return NOT_FOUND


FALLBACK_404 = Route(_fallback_404, "<other>", False)


def handler_from_router(router: Router, include_body_methods=("POST", "PUT",
                                                              "DELETE")):
    """Build a JsonRequestHandler subclass that dispatches through
    `router` — the threaded escape-hatch transport (PIO_HTTP_LOOP=0).
    The classic class middleware instruments the generated do_* methods,
    so telemetry/trace/flight-recorder behavior matches the old
    hand-written handlers."""
    from urllib.parse import urlparse as _urlparse

    from predictionio_tpu.utils.http import JsonRequestHandler

    def _dispatch(self, method: str) -> None:
        body = self.read_body() if method in include_body_methods else b""
        target = self.path
        path = _urlparse(target).path
        route = router.lookup(method, path) or FALLBACK_404
        req = Request(method, target, Headers(
            {k.lower(): v for k, v in self.headers.items()}), body,
            path=path)
        resp = route.fn(req)
        payload_bytes = resp.render_body()
        self.send_response(resp.status)
        self.send_header("Content-Type", resp.content_type)
        self.send_header("Content-Length", str(len(payload_bytes)))
        if resp.headers:
            for k, v in resp.headers.items():
                self.send_header(k, str(v))
        if resp.close:
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(payload_bytes)
        if resp.on_sent is not None:
            self.wfile.flush()
            resp.on_sent()

    ns = {}
    for method in sorted(router._methods):
        def do(self, _m=method):
            _dispatch(self, _m)
        do.__name__ = f"do_{method}"
        ns[f"do_{method}"] = do
    return type("RouterHandler", (JsonRequestHandler,), ns)
