"""FoldIn: incremental single-side ALS solves against fixed opposing factors.

ALS alternation already solves each side's rows independently — one row's
normal equations (Σ_j y_j y_jᵀ + λ·n·I) x = Σ_j r_j y_j never read another
row of the same side. Fold-in exploits that: when events touch a handful
of users/items, re-solve exactly those rows against the *fixed* opposite
factors instead of retraining. The solve here is literally one
`ops.als._solve_buckets_device` half-epoch restricted to the dirty rows —
same `bucket_ragged` capacity ladder and per-row column sort, same masked
f32-accumulated Gram einsum, same weighted regularization and solver — so
a folded row is bit-identical to what a fresh half-epoch against the same
opposing factors would produce (the parity tests assert `array_equal`).

Never-seen entity ids get appended rows: the BiMap grows at the end (old
codes keep their factor rows), the factor matrix gains zero rows, and the
next solve fills them. A zero opposing row contributes nothing to a
neighbor's normal equations, so cold items referenced from a user's
history before their own fold are simply ignored — matching what a
retrain without that item would have served.

Hot rows are NOT segment-split here (train's `bucket_ragged_split`): a
fold batch touches few rows, so one bucket per cap is cheap, and
splitting would change f32 partial-sum association vs the parity
reference.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.models.als_model import ALSModel
from predictionio_tpu.online.metrics import (
    ONLINE_COLD_START_ROWS,
    ONLINE_ROWS_FOLDED,
)
from predictionio_tpu.ops.als import (
    ALSConfig,
    _bucket_chunk_rows,
    _solve_buckets_device,
    bucket_ragged,
    resolve_solver,
)
from predictionio_tpu.telemetry import device as device_telemetry


# fold batches chunk into row-tier-ladder solves — see solve_rows
MAX_ROWS_PER_SOLVE = 128


@functools.lru_cache(maxsize=16)
def _fold_solver(cfg: ALSConfig):
    """One jitted half-epoch solve per (resolved) config; XLA's own jit
    cache handles the per-bucket-shape retraces under it. metered_jit
    (not bare jax.jit) so every fold solve lands in the jit-cache
    inventory and the device clock's attribution — a retrace storm here
    names its changed tier in /debug/jit.json instead of surfacing as
    ingest-backlog mush."""
    from predictionio_tpu.utils.profiling import metered_jit

    def run(opposing, buckets_dev, out_rows):
        return _solve_buckets_device(opposing, out_rows, buckets_dev, cfg)

    return metered_jit(run, label="foldin.solve",
                       static_argnames=("out_rows",))


def solve_rows(opposing: np.ndarray,
               entries: Sequence[Tuple[np.ndarray, np.ndarray]],
               cfg: ALSConfig) -> np.ndarray:
    """Solve the normal equations of `len(entries)` independent rows
    against fixed `opposing` [V, K] factors.

    `entries[i]` is `(cols, vals)` — opposing-row ids and ratings of the
    i-th dirty row's FULL history. Returns `[len(entries), K]` float
    factors (cfg.dtype). A row with an empty history solves to zeros
    (its bucket row is all padding), same as an eventless row in train.
    """
    cfg = resolve_solver(cfg)
    n = len(entries)
    if n == 0:
        return np.zeros((0, opposing.shape[-1]), dtype=opposing.dtype)
    if n > MAX_ROWS_PER_SOLVE:
        # rows are independent (that's the whole fold-in premise), so a
        # huge backlog batch chunks into fixed-ladder solves instead of
        # minting a fresh executable shape for its exact size
        return np.concatenate([
            solve_rows(opposing, entries[i:i + MAX_ROWS_PER_SOLVE], cfg)
            for i in range(0, n, MAX_ROWS_PER_SOLVE)])
    rows = np.concatenate([
        np.full(len(c), i, dtype=np.int32)
        for i, (c, _) in enumerate(entries)] or
        [np.zeros(0, np.int32)])
    cols = np.concatenate([np.asarray(c, np.int32) for c, _ in entries])
    vals = np.concatenate([np.asarray(v, np.float32) for _, v in entries])
    buckets = bucket_ragged(rows, cols, vals, n_rows=n,
                            cap_growth=cfg.cap_growth)
    k = opposing.shape[-1]
    # the opposing factor matrix grows a few rows per cold append, and
    # its row count is a traced shape — unpadded, EVERY post-append fold
    # would recompile. Padding rows are never gathered (history cols all
    # point below the real row count), so they change no bit of any
    # solve.
    vtier = 8
    while vtier < opposing.shape[0]:
        vtier *= 4
    if vtier > opposing.shape[0]:
        opposing = np.concatenate([
            opposing,
            np.zeros((vtier - opposing.shape[0], k), opposing.dtype)])
    # A long-lived fold stream must not mint solver shapes forever: a
    # fresh (bucket shapes, out_rows) combination costs an XLA retrace
    # (~0.35 s on CPU uncontended, several seconds under serving load,
    # vs ~1 ms warm — measured; it is the difference between draining an
    # ingest backlog and drowning in it). So every solve collapses to
    # ONE bucket on a coarse ladder: all ragged buckets pad to the
    # power-of-4 cap tier {8, 32, 128, …} of the WIDEST history and
    # merge (a masked pad entry adds an exact-zero term to the Gram sum,
    # so rows stay bit-identical to their own-capacity solve), and the
    # row count — one bucket row per entry — pads to the matching
    # power-of-4 tier with scratch rows that scatter to the sliced-off
    # row `n`. With the MAX_ROWS_PER_SOLVE chunking above, the whole
    # executable space is {8, 32, 128} row tiers × the log-sized cap
    # ladder, each compiled once per server lifetime.
    cap_max = max(b.cols.shape[1] for b in buckets)
    tcap = 8
    while tcap < cap_max:
        tcap *= 4
    parts = []
    for b in buckets:
        wpad = tcap - b.cols.shape[1]
        bc, bv, bm = b.cols, b.vals, b.mask
        if wpad:
            bc = np.pad(bc, ((0, 0), (0, wpad)))
            bv = np.pad(bv, ((0, 0), (0, wpad)))
            bm = np.pad(bm, ((0, 0), (0, wpad)))
        parts.append((b.rows, bc, bv, bm))
    br = np.concatenate([p[0] for p in parts])
    bc = np.concatenate([p[1] for p in parts])
    bv = np.concatenate([p[2] for p in parts])
    bm = np.concatenate([p[3] for p in parts])
    # bucket_ragged pads each bucket's rows to a multiple of 8 with
    # scratch rows (id = n, mask 0); after a merge that leftover varies
    # with how the ladder happened to group histories, which would leak
    # data-dependent row counts into the executable shape. Strip it —
    # a scratch row only scatter-adds zero into the sliced-off row `n`
    # — leaving exactly one bucket row per entry, then re-pad onto the
    # deterministic tier for `n`.
    real = br != n
    br, bc, bv, bm = br[real], bc[real], bv[real], bm[real]
    target = 8
    while target < n:
        target *= 4
    # then to a chunk multiple so _solve_buckets_device's chunk walk
    # covers the bucket exactly (same arithmetic as put_buckets)
    chunk = _bucket_chunk_rows(target, tcap, k, 8)
    pad = (target - n) + ((-target) % chunk)
    if pad:
        br = np.concatenate([br, np.full(pad, n, np.int32)])
        bc = np.concatenate([bc, np.zeros((pad, tcap), bc.dtype)])
        bv = np.concatenate([bv, np.zeros((pad, tcap), bv.dtype)])
        bm = np.concatenate([bm, np.zeros((pad, tcap), bm.dtype)])
    # out_rows is a STATIC jit arg (it shapes the scatter target), so it
    # rides the same row tier: solve into a padded output and slice.
    # Bucket padding rows scatter into row `n` — inside the padded range
    # now, but that scratch row is sliced off with the rest of the pad.
    run = _fold_solver(cfg)
    # device attribution: fold solves bill to the online plane, tiered by
    # the row ladder the executable space is keyed on
    with device_telemetry.attribution("online.foldin", tier=str(target)):
        out = run(np.ascontiguousarray(opposing),
                  ((br, bc, bv, bm, None),), out_rows=target)
    return np.asarray(out[:n])


class SeenOverlay:
    """Immutable seen-items view: a base SeenItems/dict plus per-row
    overrides for folded users. Overlay-on-overlay flattens, so repeated
    fold passes don't build a lookup chain."""

    __slots__ = ("_base", "_delta")

    def __init__(self, base, delta: Dict[int, np.ndarray]):
        if isinstance(base, SeenOverlay):
            merged = dict(base._delta)
            merged.update(delta)
            base, delta = base._base, merged
        self._base = base
        self._delta = delta

    def get(self, user_row: int, default=None):
        hit = self._delta.get(user_row)
        if hit is not None:
            return hit
        if not self._base:
            return default
        return self._base.get(user_row, default)

    def __len__(self) -> int:
        return (len(self._base) if self._base else 0) + len(self._delta)

    def __bool__(self) -> bool:
        return True


def extend_bimap(bimap: BiMap, ids: Sequence[str]) -> Tuple[BiMap, List[str]]:
    """Append never-seen ids with the next dense codes. Existing codes are
    untouched (factor rows stay valid); returns (bimap', appended_ids)."""
    new = [i for i in ids if i not in bimap]
    if not new:
        return bimap, []
    fwd = bimap.to_dict()
    for i in new:
        fwd[i] = len(fwd)
    return BiMap(fwd), new


def _pad_rows(factors: np.ndarray, n_rows: int) -> np.ndarray:
    if factors.shape[0] >= n_rows:
        return factors
    pad = np.zeros((n_rows - factors.shape[0], factors.shape[1]),
                   dtype=factors.dtype)
    return np.concatenate([factors, pad])


@dataclasses.dataclass
class FoldStats:
    folded_users: int = 0
    folded_items: int = 0
    new_users: int = 0
    new_items: int = 0


def fold_model(model: ALSModel, cfg: ALSConfig,
               user_hist: Dict[str, List[Tuple[str, float]]],
               item_hist: Optional[Dict[str, List[Tuple[str, float]]]] = None,
               ) -> Tuple[ALSModel, FoldStats]:
    """Fold dirty users (and optionally items) into a NEW ALSModel.

    `user_hist[user_id]` is the user's full `(item_id, value)` history —
    full, not delta, so replaying a batch after a crash re-solves to the
    identical factors (idempotence is what makes the tailer's
    at-least-once delivery safe). Users fold first against the current
    item factors, then items against the *updated* user factors — the
    same alternation order as a training epoch. The input model is never
    mutated; serving keeps reading the old immutable state until the
    caller swaps.
    """
    item_hist = item_hist or {}
    stats = FoldStats()

    # grow the id spaces first so every history row has a factor row to
    # point at (zero rows until their own side solves)
    new_user_ids = set(user_hist)
    new_item_ids = set(item_hist)
    for h in user_hist.values():
        new_item_ids.update(i for i, _ in h)
    for h in item_hist.values():
        new_user_ids.update(u for u, _ in h)
    user_ids, added_users = extend_bimap(model.user_ids, sorted(new_user_ids))
    item_ids, added_items = extend_bimap(model.item_ids, sorted(new_item_ids))
    user_factors = _pad_rows(np.asarray(model.user_factors), len(user_ids))
    item_factors = _pad_rows(np.asarray(model.item_factors), len(item_ids))
    stats.new_users, stats.new_items = len(added_users), len(added_items)
    if added_users:
        ONLINE_COLD_START_ROWS.labels(side="user").inc(len(added_users))
    if added_items:
        ONLINE_COLD_START_ROWS.labels(side="item").inc(len(added_items))

    def entries(hist, col_map):
        out = []
        for _, pairs in hist:
            cols = np.asarray([col_map[i] for i, _ in pairs], np.int32)
            vals = np.asarray([v for _, v in pairs], np.float32)
            out.append((cols, vals))
        return out

    seen_delta: Dict[int, np.ndarray] = {}
    if user_hist:
        hist = sorted(user_hist.items())
        u_rows = np.asarray([user_ids[u] for u, _ in hist], np.int32)
        solved = solve_rows(item_factors, entries(hist, item_ids), cfg)
        user_factors = user_factors.copy()
        user_factors[u_rows] = solved.astype(user_factors.dtype)
        stats.folded_users = len(hist)
        ONLINE_ROWS_FOLDED.labels(side="user").inc(len(hist))
        for (u, pairs), row in zip(hist, u_rows):
            seen_delta[int(row)] = np.unique(np.asarray(
                [item_ids[i] for i, _ in pairs], np.int32))
    if item_hist:
        hist = sorted(item_hist.items())
        i_rows = np.asarray([item_ids[i] for i, _ in hist], np.int32)
        solved = solve_rows(user_factors, entries(hist, user_ids), cfg)
        item_factors = item_factors.copy()
        item_factors[i_rows] = solved.astype(item_factors.dtype)
        stats.folded_items = len(hist)
        ONLINE_ROWS_FOLDED.labels(side="item").inc(len(hist))

    seen = model.seen
    if seen_delta:
        seen = SeenOverlay(seen, seen_delta)
    folded = dataclasses.replace(
        model, user_factors=user_factors, item_factors=item_factors,
        user_ids=user_ids, item_ids=item_ids, seen=seen)
    return folded, stats


# -- FoldModel protocol -------------------------------------------------------
# The online plane folds MODEL FAMILIES, not ALS specifically: a fold
# handle owns everything family-specific (what a "fold" recomputes, from
# which slice of the histories) while the plane keeps everything
# family-agnostic (tailing, watermarks, history gathering, delta-swap,
# lineage, freshness). A handle implements:
#
#     family: str                      # metric label ("als", "sessionrec")
#     fold(model, user_hist, item_hist) -> (new_model, stats)
#
# where `user_hist[user]` / `item_hist[item]` are the entity's FULL
# keep-last history as [(opposing_id, value, event_time)] triples — full,
# not delta, so any handle's fold is idempotent under the tailer's
# at-least-once replay. Handles must never mutate the input model
# (serving reads the old immutable state until the swap).


class FoldModel:
    """Protocol base for online fold handles (duck-typed; subclassing is
    optional and exists for isinstance-based documentation/tests)."""

    family: str = ""

    def fold(self, model, user_hist, item_hist):  # pragma: no cover - protocol
        raise NotImplementedError


def _strip_times(hist: Optional[Dict[str, list]]) -> Dict[str, list]:
    """[(id, value, t)] → [(id, value)], order preserved — exactly the
    pairs `fold_model` always consumed, so the adapter changes no bit of
    the ALS fold inputs."""
    if not hist:
        return {}
    return {k: [(o, v) for o, v, _ in triples]
            for k, triples in hist.items()}


class ALSFold(FoldModel):
    """The ALS family as a fold handle: a thin adapter over `fold_model`
    (which stays the public, signature-stable entry point) — it only
    drops the event times the generalized history form carries, because
    an ALS re-solve is a pure function of (opposing id, value) pairs."""

    family = "als"

    def __init__(self, cfg: ALSConfig):
        self.cfg = cfg

    def fold(self, model: ALSModel, user_hist, item_hist):
        return fold_model(model, self.cfg, _strip_times(user_hist),
                          _strip_times(item_hist))
