"""Event schema + validation — mirrors the reference's EventValidation
coverage (SURVEY.md §4.1)."""

import pytest

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import (
    Event,
    EventValidationError,
    parse_time,
    validate_event,
)


class TestEventSerde:
    def test_roundtrip(self):
        e = Event(
            event="buy",
            entity_type="user",
            entity_id="u1",
            target_entity_type="item",
            target_entity_id="i1",
            properties=DataMap({"qty": 3}),
            tags=["t1"],
            pr_id="p1",
        )
        e2 = Event.from_dict(e.to_dict())
        assert e2.event == "buy"
        assert e2.target_entity_id == "i1"
        assert e2.properties.to_dict() == {"qty": 3}
        assert e2.tags == ["t1"]
        assert e2.event_time == e.event_time

    def test_missing_required_field(self):
        with pytest.raises(EventValidationError):
            Event.from_dict({"event": "buy", "entityType": "user"})

    def test_iso_z_time(self):
        e = Event.from_dict({
            "event": "rate", "entityType": "user", "entityId": "1",
            "eventTime": "2026-01-02T03:04:05.000Z",
        })
        assert e.event_time == parse_time("2026-01-02T03:04:05+00:00")

    def test_numeric_entity_id_coerced(self):
        e = Event.from_dict({"event": "rate", "entityType": "user", "entityId": 42})
        assert e.entity_id == "42"


class TestValidation:
    def mk(self, **kw):
        defaults = dict(event="rate", entity_type="user", entity_id="u1")
        defaults.update(kw)
        return Event(**defaults)

    def test_plain_event_ok(self):
        validate_event(self.mk())

    def test_set_ok(self):
        validate_event(self.mk(event="$set", properties=DataMap({"a": 1})))

    def test_unknown_dollar_event_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.mk(event="$frobnicate"))

    def test_special_event_with_target_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.mk(event="$set", target_entity_type="item",
                                   target_entity_id="i1"))

    def test_unset_empty_properties_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.mk(event="$unset"))

    def test_delete_with_properties_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self.mk(event="$delete", properties=DataMap({"a": 1})))

    def test_pio_prefix_reserved(self):
        with pytest.raises(EventValidationError):
            validate_event(self.mk(event="pio_thing"))
        with pytest.raises(EventValidationError):
            validate_event(self.mk(entity_type="pio_user"))
        with pytest.raises(EventValidationError):
            validate_event(self.mk(properties=DataMap({"pio_x": 1})))


class TestBiMap:
    def test_dense_indices_in_first_appearance_order(self):
        bm = BiMap.string_int(["b", "a", "b", "c"])
        assert bm.to_dict() == {"b": 0, "a": 1, "c": 2}

    def test_inverse(self):
        bm = BiMap.string_int(["x", "y"])
        assert bm.inverse()[1] == "y"

    def test_vectorized(self):
        bm = BiMap.string_int(["x", "y", "z"])
        idx = bm.to_index(["z", "x"])
        assert idx.tolist() == [2, 0]
        assert bm.from_index(idx) == ["z", "x"]

    def test_duplicate_values_rejected(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})
