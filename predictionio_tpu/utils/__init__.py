"""Shared utilities: HTTP service scaffolding, logging helpers."""
