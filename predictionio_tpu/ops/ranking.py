"""Top-k scoring + ranking metrics (MAP@k, precision@k, NDCG@k).

The serving/eval math of the Recommendation templates: score = U Vᵀ with
seen-item exclusion, then top-k. Batched over users in chunks sized so the
[chunk, n_items] score tile stays within a ~1 GiB budget (small runs score
in one tile; ML-20M-scale runs never materialize the full n_users ×
n_items matrix — SURVEY.md §6 tracks MAP@10 on ML-20M).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

# batches up to this size score on the host (serving path); larger go to
# the accelerator (eval/bulk path)
SERVE_HOST_MAX_BATCH = 64


@functools.lru_cache(maxsize=16)
def _topk_fn(k: int, masked: bool):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.telemetry.registry import capped_label
    from predictionio_tpu.utils.profiling import metered_jit

    def score_topk(u_vecs, item_factors, ex_rows=None, ex_cols=None):
        # u_vecs [B, K]; item_factors [N, K]; exclusions as COO indices
        # (ex_rows[e], ex_cols[e]) scattered to -inf ON DEVICE — a dense
        # [B, N] host mask would ship ~1 GB per ML-20M-scale chunk
        # through the tunnel (measured: it, not the matmul, capped
        # batchpredict at ~145 qps); the index form ships ~8 bytes per
        # seen item. Padding entries carry ex_rows == B (out of range)
        # and vanish under mode="drop".
        scores = u_vecs @ item_factors.T
        if masked:
            scores = scores.at[ex_rows, ex_cols].set(-jnp.inf, mode="drop")
        top_scores, top_idx = jax.lax.top_k(scores, k)
        return top_scores, top_idx

    # compile activity per (k, masked) variant is visible on /metrics —
    # a recompile storm here (unstable batch shapes defeating the bucket
    # ladder) used to be diagnosable only as a serving latency cliff.
    # k is caller-controlled (the query's "num"), so the label passes
    # through its own capped group: the first few distinct k values keep
    # per-k series, the long tail collapses to score_topk_k<other>
    # instead of minting one /metrics series per requested k.
    return metered_jit(
        score_topk,
        label=f"ranking.score_topk_k{capped_label('ranking_topk_k', k, cap=8)}")


def _exclusion_coo(ids, exclude, n_rows: int):
    """Per-chunk COO exclusion indices, padded to a power of two so chunk
    batches reuse compiles: (ex_rows [E], ex_cols [E]) int32, padding
    rows = n_rows (dropped by the scatter)."""
    rows, cols = [], []
    for i, uid in enumerate(ids):
        ex = exclude.get(int(uid))
        if ex is not None and len(ex):
            cols.append(np.asarray(ex, dtype=np.int32))
            rows.append(np.full(len(ex), i, dtype=np.int32))
    n = sum(len(r) for r in rows)
    cap = 1 << max(0, (n - 1).bit_length())
    ex_rows = np.full(cap, n_rows, dtype=np.int32)
    ex_cols = np.zeros(cap, dtype=np.int32)
    if n:
        ex_rows[:n] = np.concatenate(rows)
        ex_cols[:n] = np.concatenate(cols)
    return ex_rows, ex_cols


def recommend_topk(
    user_factors: np.ndarray,
    item_factors: np.ndarray,
    user_ids: np.ndarray,
    k: int,
    exclude: Optional[dict[int, np.ndarray]] = None,
    chunk: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Top-k items for each user id. `exclude` maps user id → item-id array
    to hide (the 'unseen only' contract of the reference templates).

    chunk: users scored per device dispatch. Default (None) auto-sizes to
    a ~1 GiB [chunk, n_items] score tile; pass an explicit value to bound
    memory — it is honored as-is."""
    n_items = item_factors.shape[0]
    k = min(k, n_items)
    if k <= 0 or len(user_ids) == 0:
        return (np.zeros((len(user_ids), 0), np.float32),
                np.zeros((len(user_ids), 0), np.int32))
    masked = bool(exclude)
    # device-resident factors (the grid-eval path keeps trained factors on
    # chip — ops/als_grid host_factors=False): always take the device
    # branch; the host fast path's in-place numpy masking can't touch a
    # jax array, and a readback would defeat the point of residency
    on_device = not (isinstance(user_factors, np.ndarray)
                     and isinstance(item_factors, np.ndarray))
    if len(user_ids) <= SERVE_HOST_MAX_BATCH and not on_device:
        # Serving fast path: tiny batches score in numpy on the host. A
        # device round trip costs more than the dot product at any catalog
        # size that fits serving, and it keeps the prediction server off
        # the accelerator entirely — a deployed server must not hold the
        # (single-tenant) TPU that a concurrent `pio train` needs.
        #
        # Scored per row (gemv), NOT as one [B,K]@[K,N] gemm: BLAS gemm
        # blocks the reduction differently per shape, so a user's scores
        # would shift in the last ulp with the batch they arrived in —
        # and the serving micro-batcher promises batched ≡ sequential
        # bitwise. Per-row gemv is batch-size-invariant; at serving
        # batch sizes (≤ SERVE_HOST_MAX_BATCH) the gemv loop is still
        # hundreds of microseconds against a millisecond-scale request.
        it_t = item_factors.T
        scores = np.empty((len(user_ids), n_items),
                          dtype=np.result_type(user_factors, item_factors))
        for i, uid in enumerate(user_ids):
            scores[i] = user_factors[uid] @ it_t
        if masked:
            for i, uid in enumerate(user_ids):
                ex = exclude.get(int(uid))
                if ex is not None and len(ex):
                    scores[i, ex] = -np.inf
        idx = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        part = np.take_along_axis(scores, idx, axis=1)
        order = np.argsort(-part, axis=1)
        # pin dtypes to the device path's (float32 scores, int32 indices)
        return (
            np.take_along_axis(part, order, axis=1).astype(np.float32),
            np.take_along_axis(idx, order, axis=1).astype(np.int32),
        )
    import jax

    fn = _topk_fn(k, masked)
    # ship the item table once — a numpy arg would re-transfer it on every
    # chunk call (measured: that transfer, not the matmul, dominated
    # ML-20M-scale MAP@10). Chunks grow with the user count, bounded so
    # the [chunk, n_items] score tile stays ~1 GB.
    item_dev = jax.device_put(item_factors)
    if chunk is None:
        # no floor: a floor of 1024 would blow the ~1 GiB tile bound past
        # ~262k items (at 10M items the [1024, n_items] tile is ~40 GB)
        chunk = max(1, (1 << 28) // max(n_items, 1))
    chunk = min(chunk, len(user_ids))
    all_scores, all_idx = [], []
    for s in range(0, len(user_ids), chunk):
        ids = user_ids[s : s + chunk]
        u = user_factors[ids]
        if masked:
            ex_rows, ex_cols = _exclusion_coo(ids, exclude, len(ids))
            ts, ti = fn(u, item_dev, ex_rows, ex_cols)
        else:
            ts, ti = fn(u, item_dev)
        all_scores.append(np.asarray(ts))
        all_idx.append(np.asarray(ti))
    return np.concatenate(all_scores), np.concatenate(all_idx)


def average_precision_at_k(predicted, actual: set, k: int) -> float:
    """AP@k for one user (the MAP building block the reference's
    Recommendation template evaluation uses [U]). Works on int row indices
    or string item ids — elements are compared as-is against `actual`."""
    if not actual:
        return 0.0
    hits = 0
    score = 0.0
    for i, p in enumerate(predicted[:k]):
        p = p.item() if isinstance(p, np.generic) else p
        if p in actual:
            hits += 1
            score += hits / (i + 1.0)
    return score / min(len(actual), k)


def map_at_k(
    user_factors: np.ndarray,
    item_factors: np.ndarray,
    test_user_items: dict[int, set],
    k: int = 10,
    exclude: Optional[dict[int, np.ndarray]] = None,
) -> float:
    """Mean AP@k over users with test items."""
    user_ids = np.asarray(sorted(test_user_items), dtype=np.int32)
    if len(user_ids) == 0:
        return float("nan")
    _, top_idx = recommend_topk(user_factors, item_factors, user_ids, k, exclude)
    aps = [
        average_precision_at_k(top_idx[i], test_user_items[int(uid)], k)
        for i, uid in enumerate(user_ids)
    ]
    return float(np.mean(aps))
