"""Commit-notification bus: ingest write plane → serving result cache.

The serving plane's optional per-user result cache answers /queries.json
from memory; this bus is what keeps it read-your-writes. Every durable
commit path in the write plane (inline lone commit, grouped commit,
per-item fallback, and the batch route's direct insert_batch) publishes
the entity ids of the committed events; subscribers (the result cache)
drop whatever they hold for those entities.

Deliberately minimal:

- process-local. The cache and the write plane live in the same process
  per SO_REUSEPORT worker; a worker's cache can go stale only for writes
  landing on a *different* worker, which is why the cache also carries a
  short TTL (PIO_HTTP_RESULT_CACHE_TTL_S) as the cross-process bound.
- zero hot-path cost when unused: publishers check `has_subscribers`
  (one attribute read) before building the entity-id list, so ingest
  pays nothing unless a result cache is actually enabled.
- subscriber errors are contained: a broken subscriber cannot fail a
  commit that is already durable.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterable, List

log = logging.getLogger(__name__)


class InvalidationBus:
    __slots__ = ("_subs", "_lock")

    def __init__(self):
        self._subs: List[Callable[[Iterable[str]], None]] = []
        self._lock = threading.Lock()

    @property
    def has_subscribers(self) -> bool:
        return bool(self._subs)

    def subscribe(self, fn: Callable[[Iterable[str]], None]) -> None:
        with self._lock:
            if fn not in self._subs:
                # replace the list instead of mutating it so publish()
                # iterates a stable snapshot without taking the lock
                self._subs = self._subs + [fn]

    def unsubscribe(self, fn: Callable[[Iterable[str]], None]) -> None:
        # equality, not identity: bound methods (cache.invalidate_entities,
        # list.append) are fresh objects on every attribute access, and
        # subscribe's dedup (`fn not in ...`) already compares by equality
        with self._lock:
            self._subs = [s for s in self._subs if s != fn]

    def publish(self, entity_ids: Iterable[str]) -> None:
        """Fan committed entity ids out to every subscriber. Called by
        the write plane AFTER the commit is durable — a subscriber that
        invalidates on this signal can never cache ahead of storage."""
        for fn in self._subs:
            try:
                fn(entity_ids)
            except Exception:
                log.exception("invalidation subscriber failed")


# One bus per process: the write plane publishes here unconditionally,
# whichever server object owns it; caches subscribe at construction.
BUS = InvalidationBus()
