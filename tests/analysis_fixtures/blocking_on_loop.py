"""Fixture: a non-blocking route whose handler reaches sqlite and
time.sleep — both must be flagged by loop-blocking-call. The
blocking=True route doing the same things is legal (worker pool)."""

import sqlite3
import time


class FixtureAPI:
    def router(self, r):
        r.get("/fast.json", self._handle_fast)
        r.post("/slow.json", self._handle_slow, blocking=True)
        return r

    def _handle_fast(self, req):
        conn = sqlite3.connect(":memory:")
        conn.execute("select 1")
        self._settle()
        return req

    def _settle(self):
        time.sleep(0.01)

    def _handle_slow(self, req):
        time.sleep(0.5)
        return req
