"""Event server ↔ ingest write plane integration (ISSUE r7): concurrent
single-event POSTs coalesce through GroupCommitWriter, 201 means the row
is already committed and readable, saturation answers 429 + Retry-After,
webhooks ride the same plane, and the ingest_* families render."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.api import EventServer, EventServerConfig
from predictionio_tpu.ingest import IngestConfig
from predictionio_tpu.storage.base import AccessKey, App
from predictionio_tpu.telemetry.registry import parse_prometheus


def _serve(storage, ingest_config=None):
    app_id = storage.meta_apps().insert(App(id=0, name="IngestApp"))
    key = AccessKey.generate(app_id)
    storage.meta_access_keys().insert(key)
    srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0, stats=True),
                      storage, ingest_config=ingest_config)
    srv.start()
    return srv, key.key, app_id


def call(srv, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{srv.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null"), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), e.headers


def _rate(i):
    return {"event": "rate", "entityType": "user", "entityId": f"u{i}",
            "targetEntityType": "item", "targetEntityId": f"i{i}"}


class TestIngestPlaneOverHttp:
    def test_concurrent_201s_are_immediately_readable(self, memory_storage):
        srv, key, _ = _serve(memory_storage)
        failures = []

        def client(base):
            try:
                for i in range(6):
                    status, body, _ = call(
                        srv, "POST", f"/events.json?accessKey={key}",
                        _rate(base * 100 + i))
                    if status != 201:
                        failures.append(("status", status, body))
                        continue
                    # read-your-writes: the 201 promises a committed row
                    st, got, _ = call(
                        srv, "GET",
                        f"/events/{body['eventId']}.json?accessKey={key}")
                    if st != 200:
                        failures.append(("readback", st, body["eventId"]))
            except BaseException as e:  # noqa: BLE001
                failures.append(("exc", e))

        try:
            threads = [threading.Thread(target=client, args=(b,))
                       for b in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive()
        finally:
            srv.shutdown()
        assert failures == []

    def test_saturation_sheds_429_with_retry_after(self, memory_storage):
        srv, key, _ = _serve(
            memory_storage,
            ingest_config=IngestConfig(max_queue=1, retry_after_s=0.5))
        # slow the storage down so the 1-slot budget saturates; the
        # plane's fns are plain attributes for exactly this kind of drill
        real_insert = srv.ingest.insert_fn
        real_grouped = srv.ingest.grouped_fn
        srv.ingest.insert_fn = lambda e, a, c=None: (
            time.sleep(0.02), real_insert(e, a, c))[1]
        srv.ingest.grouped_fn = lambda items: (
            time.sleep(0.02), real_grouped(items))[1]
        tally = {}
        retry_afters = []
        lock = threading.Lock()

        def client(base):
            for i in range(4):
                status, _, headers = call(
                    srv, "POST", f"/events.json?accessKey={key}",
                    _rate(base * 100 + i))
                with lock:
                    tally[status] = tally.get(status, 0) + 1
                    if status == 429:
                        retry_afters.append(headers.get("Retry-After"))

        try:
            threads = [threading.Thread(target=client, args=(b,))
                       for b in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        finally:
            srv.shutdown()
        # graceful degradation: nothing but acks and sheds
        assert set(tally) <= {201, 429}, tally
        assert tally.get(201) and tally.get(429), tally
        assert retry_afters and all(float(h) > 0 for h in retry_afters)

    def test_webhook_rides_the_write_plane(self, memory_storage):
        from predictionio_tpu.ingest.writer import COMMITS

        srv, key, _ = _serve(memory_storage)
        before = COMMITS.labels().value
        try:
            status, body, _ = call(
                srv, "POST", f"/webhooks/segmentio.json?accessKey={key}",
                {"type": "track", "event": "signup", "userId": "u9"})
            assert status == 201
            st, got, _ = call(
                srv, "GET",
                f"/events/{body['eventId']}.json?accessKey={key}")
            assert st == 200
        finally:
            srv.shutdown()
        assert COMMITS.labels().value == before + 1

    def test_grouping_off_still_serves(self, memory_storage):
        srv, key, _ = _serve(memory_storage,
                             ingest_config=IngestConfig(grouping=False))
        try:
            status, body, _ = call(
                srv, "POST", f"/events.json?accessKey={key}", _rate(1))
            assert status == 201
            st, _, _ = call(
                srv, "GET",
                f"/events/{body['eventId']}.json?accessKey={key}")
            assert st == 200
        finally:
            srv.shutdown()

    def test_batch_route_bypasses_plane_but_still_works(self, memory_storage):
        srv, key, _ = _serve(memory_storage)
        try:
            status, body, _ = call(
                srv, "POST", f"/batch/events.json?accessKey={key}",
                [_rate(i) for i in range(5)])
            assert status == 200
            assert all(r["status"] == 201 for r in body)
        finally:
            srv.shutdown()

    def test_metrics_expose_ingest_families(self, memory_storage):
        srv, key, _ = _serve(memory_storage)
        try:
            assert call(srv, "POST", f"/events.json?accessKey={key}",
                        _rate(1))[0] == 201
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics") as resp:
                assert resp.status == 200
                text = resp.read().decode()
        finally:
            srv.shutdown()
        for family in ("ingest_group_size", "ingest_commit_seconds",
                       "ingest_commits_total", "ingest_shed_total",
                       "ingest_in_flight", "ingest_queue_depth"):
            assert f"# TYPE {family} " in text, family
        samples = parse_prometheus(text)
        assert any(v >= 1 for v in samples["ingest_commits_total"].values())
