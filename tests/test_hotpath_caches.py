"""Hot-path encode/cache layer: fastjson envelope parity, the per-user
result cache, and the ingest→serving invalidation bus.

The one invariant everything here hangs off: the fast paths must be
byte-identical to the generic compact encoder (the serving A/B bench
asserts bitwise-equal answers across transports), and a committed write
must be visible to the very next query from the same user.
"""

import json
import threading

import pytest

from predictionio_tpu.ingest.invalidation import InvalidationBus
from predictionio_tpu.serving.result_cache import MISS, ResultCache
from predictionio_tpu.utils import fastjson


def _stock(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


class TestFastjson:
    @pytest.mark.parametrize("obj", [
        {"a": 1, "b": [1.5, None, True], "c": {"d": "é"}},
        {"itemScores": [{"item": "i1", "score": 4.25}]},
        {"message": "queue saturated (8/8 in flight)"},
        [],
        {"nested": {"deep": [{"x": 1e-9}, {"y": -3.0}]}},
    ])
    def test_dumps_bytes_matches_stock_compact(self, obj):
        assert fastjson.dumps_bytes(obj) == _stock(obj)

    def test_loads_round_trip(self):
        obj = {"user": "u1", "num": 4, "scores": [1.5, 2.0]}
        assert fastjson.loads(fastjson.dumps_bytes(obj)) == obj
        assert fastjson.loads(fastjson.dumps(obj)) == obj
        with pytest.raises(ValueError):
            fastjson.loads(b"{nope")

    def test_event_id_envelope_bitwise(self):
        eid = "3f2a77c09e1b4c8d"
        assert fastjson.event_id_response(eid) == _stock({"eventId": eid})
        # non-plain ids fall back to the generic encoder, still correct
        weird = 'id"with\\specials\n'
        assert fastjson.event_id_response(weird) == _stock({"eventId": weird})

    @pytest.mark.parametrize("result", [
        {"itemScores": []},
        {"itemScores": [{"item": "i1", "score": 4.5},
                        {"item": "i2", "score": 0.125}]},
        {"itemScores": [{"item": "i1", "score": 3}]},        # int score
        {"itemScores": [{"item": "i1", "score": 1e-17}]},    # repr path
        {"itemScores": [{"item": "a b!~[]", "score": 0.5}]},
    ])
    def test_prediction_envelope_bitwise(self, result):
        assert fastjson.prediction_response(result) == _stock(result)

    @pytest.mark.parametrize("result", [
        {"itemScores": [{"item": "i1", "score": float("nan")}]},
        {"itemScores": [{"item": "unié", "score": 1.0}]},
        {"itemScores": [{"item": "i1", "score": 1.0, "extra": 2}]},
        {"itemScores": [{"item": "i1", "score": True}]},
        {"itemScores": "not-a-list"},
        {"other": 1},
    ])
    def test_prediction_fallback_still_generic(self, result):
        # shapes the fragment path declines must match the C encoder too
        # (NaN renders as the non-standard 'NaN' either way)
        expect = json.dumps(result, separators=(",", ":")).encode()
        assert fastjson.prediction_response(result) == expect

    def test_message_body_interned_and_bitwise(self):
        msg = "Shutting down."
        assert fastjson.message_body(msg) == _stock({"message": msg})
        assert fastjson.message_body(msg) is fastjson.message_body(msg)


class TestResultCache:
    def test_hit_miss_and_user_keying(self):
        c = ResultCache(max_entries=8, ttl_s=60.0)
        q1 = {"user": "u1", "num": 3}
        assert c.get(q1) is MISS
        c.put(q1, {"r": 1})
        assert c.get(q1) == {"r": 1}
        # a different query (even same user) is its own entry
        assert c.get({"user": "u1", "num": 4}) is MISS

    def test_ttl_expiry(self, monkeypatch):
        c = ResultCache(max_entries=8, ttl_s=0.01)
        q = {"user": "u1"}
        c.put(q, "r")
        import time
        time.sleep(0.03)
        assert c.get(q) is MISS

    def test_lru_eviction_bounded(self):
        c = ResultCache(max_entries=3, ttl_s=60.0)
        for i in range(5):
            c.put({"user": f"u{i}"}, i)
        assert len(c) == 3
        assert c.get({"user": "u0"}) is MISS          # evicted
        assert c.get({"user": "u4"}) == 4             # newest survives

    def test_invalidate_entities_is_per_user(self):
        c = ResultCache(max_entries=8, ttl_s=60.0)
        c.put({"user": "u1", "num": 3}, "a")
        c.put({"user": "u1", "num": 4}, "b")
        c.put({"user": "u2", "num": 3}, "c")
        c.invalidate_entities(["u1"])
        assert c.get({"user": "u1", "num": 3}) is MISS
        assert c.get({"user": "u1", "num": 4}) is MISS
        assert c.get({"user": "u2", "num": 3}) == "c"

    def test_anonymous_entries_invalidated_by_any_commit(self):
        # a query with no user key can depend on any entity → any commit
        # must drop it
        c = ResultCache(max_entries=8, ttl_s=60.0)
        c.put({"num": 10}, "top10")
        c.invalidate_entities(["whoever"])
        assert c.get({"num": 10}) is MISS

    def test_unencodable_query_never_cached(self):
        c = ResultCache(max_entries=8, ttl_s=60.0)
        q = {"user": "u1", "weird": object()}
        c.put(q, "r")          # silently uncacheable
        assert c.get(q) is MISS


class TestInvalidationBus:
    def test_publish_reaches_subscribers(self):
        bus = InvalidationBus()
        got = []
        bus.subscribe(got.append)
        assert bus.has_subscribers
        bus.publish(["u1", "u2"])
        assert got == [["u1", "u2"]]
        bus.unsubscribe(got.append)
        assert not bus.has_subscribers

    def test_subscriber_exception_contained(self):
        bus = InvalidationBus()
        got = []

        def boom(_ids):
            raise RuntimeError("subscriber bug")

        bus.subscribe(boom)
        bus.subscribe(got.append)
        bus.publish(["u1"])    # must not raise, must reach the healthy sub
        assert got == [["u1"]]

    def test_writer_publishes_committed_entity_ids(self):
        """GroupCommitWriter must publish entity ids on the process bus
        after a durable commit — grouped AND inline paths."""
        import itertools

        from predictionio_tpu.data.events import Event
        from predictionio_tpu.ingest.invalidation import BUS
        from predictionio_tpu.ingest.writer import (
            GroupCommitWriter, IngestConfig,
        )

        published = []
        BUS.subscribe(published.append)
        ids = itertools.count(1)
        try:
            for grouping in (True, False):
                writer = GroupCommitWriter(
                    insert_fn=lambda e, a, c=None: str(next(ids)),
                    grouped_fn=lambda items: [str(next(ids)) for _ in items],
                    config=IngestConfig(grouping=grouping),
                    name="bustest")
                try:
                    writer.submit(
                        Event(event="rate", entity_type="user",
                              entity_id=f"user-{grouping}",
                              target_entity_type="item",
                              target_entity_id="i1"),
                        app_id=1)
                finally:
                    writer.close()
            flat = [eid for batch in published for eid in batch]
            assert "user-True" in flat and "user-False" in flat
        finally:
            BUS.unsubscribe(published.append)


def test_bus_unsubscribe_under_concurrent_publish():
    """Copy-on-write subscriber list: unsubscribing mid-publish-storm
    must neither deadlock nor raise."""
    bus = InvalidationBus()
    seen = []
    bus.subscribe(seen.append)
    stop = threading.Event()

    def storm():
        while not stop.is_set():
            bus.publish(["u"])

    t = threading.Thread(target=storm)
    t.start()
    try:
        for _ in range(50):
            bus.subscribe(len)          # churn the list
            bus.unsubscribe(len)
    finally:
        stop.set()
        t.join(5)
    assert seen  # publishes reached the stable subscriber
