"""Metrics for offline evaluation.

Parity with «core/.../controller/Metric.scala» (SURVEY.md §2.1 [U]):
`Metric` (calculate per (query, predicted, actual) point + aggregate),
`AverageMetric`, `OptionAverageMetric` (skips None points), `StdevMetric`,
`SumMetric`, `ZeroMetric`.
"""

from __future__ import annotations

import abc
import math
from typing import Any, Generic, Optional, Sequence, TypeVar

Q = TypeVar("Q")
R = TypeVar("R")
A = TypeVar("A")


class Metric(abc.ABC, Generic[Q, R, A]):
    #: higher is better by default; metrics like RMSE set False
    higher_is_better: bool = True

    @abc.abstractmethod
    def calculate(self, query: Q, predicted: R, actual: A) -> Optional[float]:
        """Score one evaluation point. None = excluded (OptionAverage)."""

    def aggregate(self, scores: Sequence[Optional[float]]) -> float:
        """Combine per-point scores into the metric value."""
        vals = [s for s in scores if s is not None]
        if not vals:
            return float("nan")
        return sum(vals) / len(vals)

    @property
    def name(self) -> str:
        return type(self).__name__

    def compare(self, a: float, b: float) -> int:
        """>0 if a better than b."""
        if math.isnan(a):
            return -1
        if math.isnan(b):
            return 1
        d = a - b if self.higher_is_better else b - a
        return (d > 0) - (d < 0)


class AverageMetric(Metric[Q, R, A], abc.ABC):
    """Mean of per-point scores (None treated as 0 contribution excluded —
    the reference's AverageMetric requires all points; keep the tolerant
    aggregate, matching observed template usage)."""


class OptionAverageMetric(Metric[Q, R, A], abc.ABC):
    """Mean over points where calculate() returns a value [U]."""


class SumMetric(Metric[Q, R, A], abc.ABC):
    def aggregate(self, scores: Sequence[Optional[float]]) -> float:
        return float(sum(s for s in scores if s is not None))


class StdevMetric(Metric[Q, R, A], abc.ABC):
    def aggregate(self, scores: Sequence[Optional[float]]) -> float:
        vals = [s for s in scores if s is not None]
        if len(vals) < 2:
            return 0.0
        mean = sum(vals) / len(vals)
        return math.sqrt(sum((v - mean) ** 2 for v in vals) / (len(vals) - 1))


class ZeroMetric(Metric[Any, Any, Any]):
    """Always 0 — placeholder secondary metric [U]."""

    def calculate(self, query, predicted, actual) -> float:
        return 0.0
