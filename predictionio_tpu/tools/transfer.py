"""Import/export: JSON-lines event files ↔ event store.

Parity with «tools/.../tools/imprt/FileToEvents.scala» and
«tools/.../tools/export/EventsToFile.scala» (SURVEY.md §2.3 [U]). The file
format is one event JSON object per line, the same wire shape as the event
API, so a file exported here can be imported by a reference installation
and vice versa.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from predictionio_tpu.data.events import Event, EventValidationError, validate_event
from predictionio_tpu.storage.registry import Storage

log = logging.getLogger(__name__)


def _resolve_app(storage: Storage, app_name: str, channel_name: Optional[str]):
    app = storage.meta_apps().get_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist.")
    channel_id = None
    if channel_name:
        channels = {c.name: c
                    for c in storage.meta_channels().get_by_app_id(app.id)}
        if channel_name not in channels:
            raise ValueError(f"Channel {channel_name!r} does not exist for app "
                             f"{app_name!r}.")
        channel_id = channels[channel_name].id
    return app.id, channel_id


def file_to_events(
    input_path: str,
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> tuple[int, int]:
    """Import events; returns (imported, skipped). Invalid lines are
    skipped with a warning, matching the reference's tolerant import."""
    storage = storage or Storage.get()
    app_id, channel_id = _resolve_app(storage, app_name, channel_name)
    le = storage.l_events()
    imported = skipped = 0
    batch: list[Event] = []
    CHUNK = 5000  # one transaction per chunk (~20× the per-row-commit rate)
    with open(input_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                event = Event.from_dict(json.loads(line))
                validate_event(event)
                # fresh ids: exported files keep eventId for traceability,
                # but ids are store-unique, so re-import must not reuse them
                event.event_id = None
                batch.append(event)
            except (json.JSONDecodeError, EventValidationError, ValueError,
                    TypeError, KeyError) as e:
                skipped += 1
                log.warning("import: skipping line %d: %s", lineno, e)
                continue
            if len(batch) >= CHUNK:
                imported += len(le.insert_batch(batch, app_id, channel_id))
                batch.clear()
    if batch:
        imported += len(le.insert_batch(batch, app_id, channel_id))
    return imported, skipped


def events_to_file(
    output_path: str,
    app_name: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> int:
    """Export all of an app's events as JSON lines; returns the count."""
    storage = storage or Storage.get()
    app_id, channel_id = _resolve_app(storage, app_name, channel_name)
    events = storage.l_events().find(app_id=app_id, channel_id=channel_id)
    n = 0
    with open(output_path, "w") as f:
        for event in events:
            f.write(json.dumps(event.to_dict()) + "\n")
            n += 1
    return n
