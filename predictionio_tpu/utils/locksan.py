"""Runtime lock-order sanitizer (``PIO_LOCKSAN=1``).

The static lock graph (`analysis/lockgraph.py`) claims the whole
program has one consistent lock acquisition order. This module keeps
that claim honest at runtime: when installed, ``threading.Lock()`` and
``threading.RLock()`` return instrumented wrappers that record, per
thread, which lock was *held* when another was *acquired* — a
process-global ordered-acquisition graph, dumpable at
``/debug/locks.json`` and cross-checked by the analysis gate:

- a **dynamic cycle** is an observed deadlock-shaped order — always a
  bug;
- a **dynamic edge missing from the static graph** (and not reviewed
  in ``conf/lockorder-baseline.json``) is a static-resolution bug —
  the analyzer failed to see a call path the process just took.

Lock identity is the **creation site** ``(file, line)`` of the
``Lock()``/``RLock()`` call, relative to the repo root — exactly the
anchor the static graph attaches to each lock definition, so the two
graphs join on it. All instances born at one site share an identity
(same granularity as the static model), which is why site-level
self-edges are not recorded: sibling-instance nesting is
indistinguishable from reentrancy here.

Scope: only locks *created after* :func:`install` through the
``threading.Lock``/``threading.RLock`` module attributes are wrapped.
``from threading import Lock`` aliases bound earlier, and stdlib
internals that call ``_thread.allocate_lock`` directly, stay raw —
repo code consistently spells ``threading.Lock()``, which is the
surface we audit. Overhead is one dict update per cold acquisition;
production stays unpatched (``PIO_LOCKSAN`` unset ⇒ import is free).

``threading.Condition`` works with wrapped locks: the wrapper exposes
``_release_save``/``_acquire_restore``/``_is_owned`` so ``wait()``
keeps the held-stack bookkeeping balanced while it parks.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

Site = Tuple[str, int]      # (repo-relative file, creation line)

_HERE = os.path.abspath(__file__)
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(_HERE)))

_orig_lock = threading.Lock
_orig_rlock = threading.RLock

_installed = False
_tls = threading.local()
# bookkeeping mutex is always a RAW lock — the sanitizer never records
# itself
_mutex = _orig_lock()
_sites: Dict[Site, Dict[str, object]] = {}
_edges: Dict[Tuple[Site, Site], int] = {}
_acquires_total = 0


_THREADING_FILE = os.path.abspath(threading.__file__)


def _creation_site() -> Tuple[Site, bool]:
    """(site, in_repo) for the frame that called threading.Lock().
    Frames inside threading.py itself are skipped so the RLock a
    ``threading.Condition()`` creates internally is attributed to the
    Condition call in repo code — the site the static graph knows."""
    depth = 2
    while True:
        try:
            frame = sys._getframe(depth)
        except ValueError:
            return (("<unknown>", 0), False)
        fname = os.path.abspath(frame.f_code.co_filename)
        if fname != _HERE and fname != _THREADING_FILE:
            break
        depth += 1
    rel = os.path.relpath(fname, _ROOT).replace(os.sep, "/")
    if rel.startswith(".."):
        return ((fname, frame.f_lineno), False)
    return ((rel, frame.f_lineno), True)


def _held_stack() -> List["_SanLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _record_acquire(obj: "_SanLock") -> None:
    if getattr(_tls, "busy", False):
        return
    _tls.busy = True
    try:
        held = _held_stack()
        reentrant = any(h is obj for h in held)
        if not reentrant:
            global _acquires_total
            with _mutex:
                _acquires_total += 1
                info = _sites.get(obj.site)
                if info is not None:
                    info["acquires"] = int(info["acquires"]) + 1  # type: ignore[arg-type]
                outer_sites = []
                for h in held:
                    if h.site != obj.site and h.site not in outer_sites:
                        outer_sites.append(h.site)
                for s in outer_sites:
                    key = (s, obj.site)
                    _edges[key] = _edges.get(key, 0) + 1
        held.append(obj)
    finally:
        _tls.busy = False


def _record_release(obj: "_SanLock") -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i] is obj:
            del held[i]
            return


class _SanLock:
    """Instrumented Lock/RLock: inner primitive + order bookkeeping."""

    def __init__(self, inner, site: Site, kind: str, in_repo: bool):
        self._inner = inner
        self.site = site
        self.kind = kind
        self.in_repo = in_repo

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self)
        return got

    def release(self) -> None:
        self._inner.release()
        _record_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition protocol — keep the held stack balanced across wait()
    def _release_save(self):
        saver = getattr(self._inner, "_release_save", None)
        n = 0
        held = getattr(_tls, "held", [])
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                n += 1
        state = saver() if saver is not None else self._inner.release()
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        restorer = getattr(self._inner, "_acquire_restore", None)
        if restorer is not None:
            restorer(state)
        else:
            self._inner.acquire()
        _record_acquire(self)
        held = _held_stack()
        for _ in range(max(0, n - 1)):
            held.append(self)

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        # plain Lock fallback, mirroring threading.Condition's own
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return (f"<locksan.{self.kind} site={self.site[0]}:{self.site[1]} "
                f"{self._inner!r}>")


def _register_site(site: Site, kind: str, in_repo: bool) -> None:
    with _mutex:
        if site not in _sites:
            _sites[site] = {"file": site[0], "line": site[1],
                            "kind": kind, "in_repo": in_repo,
                            "acquires": 0}


def _make_lock():
    site, in_repo = _creation_site()
    _register_site(site, "Lock", in_repo)
    return _SanLock(_orig_lock(), site, "Lock", in_repo)


def _make_rlock():
    site, in_repo = _creation_site()
    _register_site(site, "RLock", in_repo)
    return _SanLock(_orig_rlock(), site, "RLock", in_repo)


def install() -> None:
    """Patch ``threading.Lock``/``threading.RLock``. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_lock          # type: ignore[misc,assignment]
    threading.RLock = _make_rlock        # type: ignore[misc,assignment]
    if hasattr(os, "register_at_fork"):
        os.register_at_fork(after_in_child=reset)
    _installed = True


def uninstall() -> None:
    """Restore the raw primitives (already-wrapped locks keep working)."""
    global _installed
    threading.Lock = _orig_lock          # type: ignore[misc]
    threading.RLock = _orig_rlock        # type: ignore[misc]
    _installed = False


def enabled() -> bool:
    return _installed


def maybe_install() -> bool:
    """Install iff ``PIO_LOCKSAN`` is set to a truthy value."""
    if os.environ.get("PIO_LOCKSAN", "").lower() in ("1", "true", "yes"):
        install()
    return _installed


def reset() -> None:
    """Drop recorded edges/counters (sites persist — the locks still
    exist). Used by tests and the post-fork child."""
    global _acquires_total
    with _mutex:
        _edges.clear()
        _acquires_total = 0
        for info in _sites.values():
            info["acquires"] = 0


def snapshot() -> Tuple[Dict[Site, Dict[str, object]],
                        Dict[Tuple[Site, Site], int], int]:
    with _mutex:
        return (dict(_sites), dict(_edges), _acquires_total)


def edges(repo_only: bool = True) -> Dict[Tuple[Site, Site], int]:
    """Observed ordered-acquisition edges; by default only those whose
    endpoints are both repo creation sites (what the static graph can
    ever know about)."""
    sites, es, _ = snapshot()
    if not repo_only:
        return es
    return {k: v for k, v in es.items()
            if bool(sites.get(k[0], {}).get("in_repo"))
            and bool(sites.get(k[1], {}).get("in_repo"))}


def cycles(repo_only: bool = True) -> List[List[Site]]:
    """Simple cycles in the observed order graph (DFS, deterministic)."""
    es = edges(repo_only)
    adj: Dict[Site, List[Site]] = {}
    for a, b in es:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    for v in adj.values():
        v.sort()
    out: List[List[Site]] = []
    seen_cycles = set()
    for start in sorted(adj):
        stack: List[Tuple[Site, List[Site]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(path + [start])
                elif nxt in adj and nxt not in path and nxt > start:
                    stack.append((nxt, path + [nxt]))
    out.sort()
    return out


def _sync_metrics() -> None:
    # imported lazily so a bare `import locksan` stays dependency-free
    from predictionio_tpu.telemetry.registry import REGISTRY
    sites, es, total = snapshot()
    c = REGISTRY.counter(
        "locksan_acquires_total",
        "cold lock acquisitions observed by the lock sanitizer")
    # counters only move forward; publish the delta since last sync
    prev = getattr(_sync_metrics, "_published", 0)
    if total > prev:
        c.inc(total - prev)
        _sync_metrics._published = total  # type: ignore[attr-defined]
    REGISTRY.gauge(
        "locksan_lock_sites",
        "distinct lock creation sites seen by the sanitizer").set(
        float(len(sites)))
    REGISTRY.gauge(
        "locksan_order_edges",
        "distinct dynamic lock-order edges recorded").set(float(len(es)))
    REGISTRY.gauge(
        "locksan_cycles_detected",
        "cycles currently present in the dynamic lock-order graph").set(
        float(len(cycles(repo_only=False))))


def _fmt_site(site: Site) -> str:
    return f"{site[0]}:{site[1]}"


def payload() -> Dict[str, object]:
    """The ``/debug/locks.json`` body (also refreshes locksan_* gauges)."""
    sites, es, total = snapshot()
    try:
        _sync_metrics()
    except Exception:
        pass
    return {
        "enabled": _installed,
        "acquires_total": total,
        "sites": [dict(info, site=_fmt_site(s))
                  for s, info in sorted(sites.items())],
        "edges": [{"from": _fmt_site(a), "to": _fmt_site(b), "count": n}
                  for (a, b), n in sorted(es.items())],
        "cycles": [[_fmt_site(s) for s in cyc] for cyc in cycles()],
    }
