"""Shared HTTP base (utils/http.py) — error-channel ownership.

A framework that silences its access log must own its error channel
too: handler exceptions route through `logging`, never raw tracebacks
on stderr (socketserver's default `handle_error` prints there, which
polluted the round-4 suite run from a fault drill — VERDICT r4 weak #4).
"""

import http.client
import logging

from predictionio_tpu.utils.http import HttpService, JsonRequestHandler


class _BoomHandler(JsonRequestHandler):
    def do_GET(self):
        if self.path == "/boom":
            raise RuntimeError("handler bug")
        self.send_json(200, {"ok": True})


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        return conn.getresponse().read()
    finally:
        conn.close()


def test_handler_exception_logs_not_stderr(capfd, caplog):
    from predictionio_tpu.telemetry.middleware import HTTP_ERRORS

    svc = HttpService("127.0.0.1", 0, _BoomHandler, server_name="boomsvc")
    errors_before = HTTP_ERRORS.labels(server="boomsvc").value
    svc.start()
    try:
        # handler bugs are warnings (counted, traced), not errors
        with caplog.at_level(logging.WARNING, logger="predictionio_tpu.http"):
            try:
                _get(svc.port, "/boom")
            except (http.client.HTTPException, ConnectionError, OSError):
                pass  # the connection dying is fine; stderr noise is not
            # healthy requests still served after the crashed one
            assert b"true" in _get(svc.port, "/ok")
    finally:
        svc.shutdown()
    err = capfd.readouterr().err
    assert "Traceback" not in err
    assert "Exception occurred during processing of request" not in err
    crash_records = [r for r in caplog.records
                     if "exception processing request" in r.message]
    assert crash_records, "handler bug must reach logging"
    assert any(r.exc_info for r in crash_records), \
        "traceback belongs in the logging record"
    # the record carries the request's trace id, not the "-" placeholder
    assert all("trace=-" not in r.getMessage() for r in crash_records)
    assert HTTP_ERRORS.labels(server="boomsvc").value == errors_before + 1


def test_client_disconnect_is_not_an_error(capfd, caplog):
    """A client dropping mid-request (routine under kill drills and load
    ladders) is debug noise, not an error record."""
    svc = HttpService("127.0.0.1", 0, _BoomHandler)
    svc.start()
    try:
        with caplog.at_level(logging.ERROR, logger="predictionio_tpu.http"):
            import socket
            s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
            s.sendall(b"GET /ok HTTP/1.1\r\nHost: x\r\n\r\n")
            s.close()  # drop without reading the reply
            assert b"true" in _get(svc.port, "/ok")
    finally:
        svc.shutdown()
    err = capfd.readouterr().err
    assert "Traceback" not in err
    assert not [r for r in caplog.records if r.levelno >= logging.ERROR]
