"""Lead Scoring template — conversion probability from session features.

Parity with the upstream gallery template
«template-scala-parallel-leadscoring» [U]: a visit's first-view
attributes (landing page, referrer, browser) predict whether the session
converts; the upstream's RandomForest is substituted with the
framework's jitted softmax regression (documented in the engine module).
"""

from predictionio_tpu.templates.leadscoring.engine import (
    DataSource,
    DataSourceParams,
    LeadScoringAlgorithm,
    LeadScoringEngine,
    LeadScoringModel,
    LeadScoringParams,
    Preparator,
    PreparedData,
    Query,
    Session,
    TrainingData,
)

__all__ = [
    "LeadScoringEngine",
    "LeadScoringModel",
    "LeadScoringAlgorithm",
    "LeadScoringParams",
    "DataSource",
    "DataSourceParams",
    "Preparator",
    "PreparedData",
    "TrainingData",
    "Session",
    "Query",
]
