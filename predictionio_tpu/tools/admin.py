"""Admin server — REST app/key CRUD on :7071.

Parity with «tools/.../tools/admin/AdminServer.scala» (SURVEY.md §2.3 [U],
marked experimental upstream). All mutations go through the shared
CommandClient so console and admin semantics stay identical. Routes:

    GET    /                      → {"status": "alive"}
    GET    /cmd/app               → list apps
    POST   /cmd/app               → create app  {"name": ..., "description": ...}
    DELETE /cmd/app/<name>        → delete app (+ keys, channels, events)
    DELETE /cmd/app/<name>/data   → delete app's events (all channels)
"""

from __future__ import annotations

import json
from typing import Optional

from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.tools.command_client import CommandClient
from predictionio_tpu.utils.http import HttpService, JsonRequestHandler


class AdminServer(HttpService):
    def __init__(self, ip: str = "0.0.0.0", port: int = 7071,
                 storage: Optional[Storage] = None):
        client = CommandClient(storage)

        class Handler(JsonRequestHandler):
            def do_GET(self):
                self.read_body()
                if self.path == "/":
                    return self.send_json(200, {"status": "alive"})
                if self.path == "/cmd/app":
                    return self.send_json(200, [
                        {"name": a.name, "id": a.id, "accessKeys": a.access_keys}
                        for a in client.list_apps()
                    ])
                return self.send_json(404, {"message": "Not Found"})

            def do_POST(self):
                body = self.read_body()
                if self.path == "/cmd/app":
                    try:
                        d = json.loads(body or b"{}")
                        name = d["name"]
                    except (json.JSONDecodeError, KeyError, TypeError):
                        return self.send_json(
                            400, {"message": 'body must be {"name": ...}'})
                    created = client.create_app(name, d.get("description", ""))
                    if created is None:
                        return self.send_json(409, {"message": f"App {name!r} exists."})
                    app_id, key = created
                    return self.send_json(201, {"name": name, "id": app_id,
                                                "accessKey": key})
                return self.send_json(404, {"message": "Not Found"})

            def do_DELETE(self):
                self.read_body()
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 3 and parts[:2] == ["cmd", "app"]:
                    name = parts[2]
                    if len(parts) == 3:
                        if client.delete_app(name):
                            return self.send_json(200, {"message": f"Deleted {name}."})
                        return self.send_json(404, {"message": "Not Found"})
                    if len(parts) == 4 and parts[3] == "data":
                        if client.delete_app_data(name):
                            return self.send_json(200, {"message": "Data deleted."})
                        return self.send_json(404, {"message": "Not Found"})
                return self.send_json(404, {"message": "Not Found"})

        super().__init__(ip, port, Handler, server_name="adminserver")
