"""pio-lint CLI: run the analysis engine over the repo.

    python bin/pio-lint                 # text output
    python bin/pio-lint --json          # machine output (CI)
    python bin/pio-lint --rules race-shared-state,race-lock-order
    python bin/pio-lint --list-rules
    python bin/pio-lint --no-baseline   # show grandfathered findings too
    python bin/pio-lint --changed main  # only modules touched vs a ref

Exit 0 when every finding is baselined (conf/analysis-baseline.json)
or inline-suppressed; 1 on any new finding or a malformed baseline.
``--changed`` narrows *reporting* to touched modules; the analysis
itself (call graph, lock graph) stays whole-program.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from predictionio_tpu.analysis import engine


def _changed_modules(root: str, ref: str) -> Set[str]:
    """Repo-relative .py paths touched relative to ``ref``: committed,
    staged, and unstaged changes since merge-base(ref, HEAD) — what a
    pre-push hook cares about. ``git diff <ref>...`` gives exactly
    that in one call."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", f"{ref}..."],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"git diff failed: {e}")
    if diff.returncode != 0:
        raise RuntimeError(
            f"git diff {ref!r} failed: {diff.stderr.strip()}")
    return {line.strip().replace(os.sep, "/")
            for line in diff.stdout.splitlines()
            if line.strip().endswith(".py")}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="pio-lint",
        description="whole-repo static analysis: race detector, "
                    "event-loop blocking-call rule, jit shape "
                    "discipline, coverage rules, and the migrated CI "
                    "gates — one AST engine, no imports of the scanned "
                    "code")
    p.add_argument("--root", default=engine.default_root(),
                   help="repo root to scan (default: this checkout)")
    p.add_argument("--subdir", action="append", default=None,
                   help="scan root(s) relative to --root (default: "
                        "predictionio_tpu)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (default: "
                        "<root>/conf/analysis-baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="JSON output")
    p.add_argument("--changed", metavar="GIT_REF", default=None,
                   help="report only findings in modules touched "
                        "relative to GIT_REF (committed + staged + "
                        "unstaged); the call/lock graphs stay "
                        "whole-program, only reporting narrows")
    args = p.parse_args(argv)

    if args.list_rules:
        rules = engine.all_rules()
        if args.as_json:
            print(json.dumps({rid: r.doc for rid, r in sorted(rules.items())},
                             indent=2))
        else:
            for rid in sorted(rules):
                print(f"{rid:24s} {rules[rid].doc}")
        return 0

    subdirs = tuple(args.subdir) if args.subdir else engine.DEFAULT_SUBDIRS
    project = engine.Project(args.root, subdirs=subdirs)
    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    try:
        findings = engine.run_rules(project, rule_ids)
    except KeyError as e:
        print(f"pio-lint: {e.args[0]}", file=sys.stderr)
        return 2

    changed = None
    if args.changed is not None:
        try:
            changed = _changed_modules(args.root, args.changed)
        except RuntimeError as e:
            print(f"pio-lint: --changed: {e}", file=sys.stderr)
            return 2
        # the scan above was still whole-program — cross-module rules
        # already saw every path; we only narrow what gets reported
        findings = [f for f in findings if f.file in changed]

    baseline_path = args.baseline or os.path.join(
        args.root, engine.DEFAULT_BASELINE)
    baseline = {}
    baseline_error = None
    if not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (engine.BaselineError, ValueError) as e:
            baseline_error = str(e)
    new, grandfathered, stale = engine.partition(findings, baseline)
    if changed is not None:
        # a filtered view can't judge staleness — entries for untouched
        # modules are invisible here, not stale
        stale = []

    if args.as_json:
        print(json.dumps({
            "root": project.root,
            "modules": len(project.modules()),
            "findings": [dict(f.to_dict(), baselined=(f.key in baseline))
                         for f in findings],
            "new": len(new),
            "baselined": len(grandfathered),
            "stale_baseline": stale,
            "baseline_error": baseline_error,
            "changed_filter": (sorted(changed) if changed is not None
                               else None),
        }, indent=2))
    else:
        for f in new:
            print(f.render(), file=sys.stderr)
        if args.no_baseline:
            for f in grandfathered:
                print(f"{f.render()}  [baselined]", file=sys.stderr)
        if baseline_error:
            print(f"pio-lint: baseline error: {baseline_error}",
                  file=sys.stderr)
        for key in stale:
            print(f"pio-lint: note: baseline entry {key!r} no longer "
                  f"fires — remove it", file=sys.stderr)
        verdict = "FAIL" if (new or baseline_error) else "OK"
        print(f"pio-lint: {verdict} — {len(new)} new finding(s), "
              f"{len(grandfathered)} baselined, "
              f"{len(project.modules())} module(s) scanned")
    return 1 if (new or baseline_error) else 0


if __name__ == "__main__":
    sys.exit(main())
