"""Evaluation + MetricEvaluator: offline param-grid search.

Parity with «core/.../controller/{Evaluation,MetricEvaluator,
EngineParamsGenerator}.scala» (SURVEY.md §2.1 [U]): an Evaluation binds an
engine to metrics; an EngineParamsGenerator yields the params grid; the
MetricEvaluator scores every (engine params, fold) combination and ranks
engine params by the primary metric.
"""

from __future__ import annotations

import dataclasses
import json
import math
import logging
from typing import Any, Optional, Sequence

from predictionio_tpu.controller.context import WorkflowContext
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.metrics import Metric
from predictionio_tpu.controller.params import params_to_dict

log = logging.getLogger(__name__)


class Evaluation:
    """Subclass and set `engine` + `metric` (and optionally `metrics` for
    secondary metrics)."""

    engine: Engine
    metric: Metric
    metrics: Sequence[Metric] = ()

    def all_metrics(self) -> list[Metric]:
        return [self.metric, *self.metrics]


class EngineParamsGenerator:
    """Subclass and set `engine_params_list`."""

    engine_params_list: Sequence[EngineParams]


@dataclasses.dataclass
class MetricScores:
    engine_params: EngineParams
    scores: dict[str, float]  # metric name → aggregated value
    per_fold: list[dict[str, float]]


@dataclasses.dataclass
class EvaluationResult:
    best: MetricScores
    all_results: list[MetricScores]
    metric_name: str

    def to_json(self) -> str:
        def ep_dict(ep: EngineParams) -> dict:
            return {
                "dataSource": params_to_dict(ep.data_source_params) if ep.data_source_params else {},
                "preparator": params_to_dict(ep.preparator_params) if ep.preparator_params else {},
                "algorithms": [
                    {"name": name, "params": params_to_dict(p) if p else {}}
                    for name, p in ep.algorithm_params_list
                ],
                "serving": params_to_dict(ep.serving_params) if ep.serving_params else {},
            }

        return json.dumps(
            {
                "metric": self.metric_name,
                "bestScore": self.best.scores[self.metric_name],
                "bestEngineParams": ep_dict(self.best.engine_params),
                "results": [
                    {"engineParams": ep_dict(r.engine_params), "scores": r.scores}
                    for r in self.all_results
                ],
            },
            indent=2,
        )

    def summary(self) -> str:
        lines = [f"Metric: {self.metric_name}"]
        for r in self.all_results:
            marker = " <= BEST" if r is self.best else ""
            lines.append(f"  score={r.scores[self.metric_name]:.6f}{marker}")
        return "\n".join(lines)


class MetricEvaluator:
    """`MetricEvaluator.evaluateBase` [U]."""

    @staticmethod
    def evaluate(
        ctx: WorkflowContext,
        evaluation: Evaluation,
        engine_params_list: Sequence[EngineParams],
    ) -> EvaluationResult:
        if not engine_params_list:
            raise ValueError("No engine params to evaluate (empty generator list).")
        engine = evaluation.engine
        metrics = evaluation.all_metrics()
        primary = metrics[0]
        all_results: list[MetricScores] = []
        # defensive: drop any buffered state a custom stateful metric may
        # carry between evaluations (the built-in zoo is stateless)
        for metric in metrics:
            metric.reset()
        # the TPU-native grid path (SURVEY.md §2.6 strategy 4): folds read
        # once, batchable algorithms train every grid cell in one device
        # program (Engine.eval_grid → Algorithm.train_grid → ops/als_grid);
        # None = grid not shareable, run the reference-shaped sequential
        # loop («EvaluationWorkflow» outer grid loop [U])
        grid_results = engine.eval_grid(ctx, engine_params_list)
        for i, ep in enumerate(engine_params_list):
            if grid_results is not None:
                fold_results = grid_results[i]
            else:
                log.info("MetricEvaluator: engine params %d/%d", i + 1,
                         len(engine_params_list))
                fold_results = engine.eval(ctx, ep)
            per_fold: list[dict[str, float]] = []
            for _, qpa in fold_results:
                fold_scores = {m.name: m.evaluate_all(qpa) for m in metrics}
                per_fold.append(fold_scores)
            # a fold where a metric is undefined (NaN — e.g. AUC on a
            # one-class test split) must not poison the candidate's mean:
            # average over the folds where the metric IS defined
            def _mean_defined(name: str) -> float:
                vals = [f[name] for f in per_fold
                        if not math.isnan(f[name])]
                return sum(vals) / len(vals) if vals else float("nan")

            agg = {m.name: _mean_defined(m.name) for m in metrics}
            all_results.append(MetricScores(ep, agg, per_fold))
        best = all_results[0]
        for r in all_results[1:]:
            if primary.compare(r.scores[primary.name], best.scores[primary.name]) > 0:
                best = r
        return EvaluationResult(best=best, all_results=all_results,
                                metric_name=primary.name)
