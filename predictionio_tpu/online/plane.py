"""OnlinePlane: event → served model in seconds, no retrain.

The loop: an `ingest.tailer.StoreTailer` (batch mode) polls rating
events out of the durable store; each fresh batch names the dirty
users/items; each variant's fold handles (`foldin.FoldModel` — `ALSFold`
re-solving exactly the dirty factor rows against the fixed opposite
side, `session.SessionFold` rebuilding the dirty users' session windows
and embeddings) produce updated models; `swap.DeltaSwapper` publishes
them into the server's served-state table per variant — bandit arms
keep learning mid-experiment — and invalidates only the touched users'
cache entries. Freshness is observed per event on the north-star
histogram and sliced per model family on
`online_family_event_to_servable_seconds`.

Crash safety is the tailer's at-least-once contract: the watermark
advances only after fold+swap complete, and a fold re-solves each dirty
row from its FULL history, so replaying a batch lands on bit-identical
factors. The `online.pre_watermark` fault site sits exactly in that
window for the crash drill (quality.py --online-gate).

The periodic parity check bounds drift against a full retrain: it
re-reads the training data through the variant's own DataSource/
Preparator and re-solves every common user row one half-epoch against
the served item factors. Rows the plane folded re-solve bit-identically
(same inputs); untouched rows show the ALS convergence residual; the
gauge `online_parity_drift` carries the max element delta and the
runbook in docs/online.md says what to do when it grows.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.ingest.tailer import OVERLAP, StoreTailer
from predictionio_tpu.models.als_model import ALSModel
from predictionio_tpu.models.session_model import SessionRecModel
from predictionio_tpu.online import foldin
from predictionio_tpu.online.foldin import ALSFold, FoldModel
from predictionio_tpu.online.metrics import (
    ONLINE_EVENTS_FOLDED,
    ONLINE_EVENT_TO_SERVABLE,
    ONLINE_FAMILY_FRESHNESS,
    ONLINE_FOLD_ERRORS,
    ONLINE_FOLDIN_SECONDS,
    ONLINE_LAG,
    ONLINE_PARITY_CHECKS,
    ONLINE_PARITY_DRIFT,
)
from predictionio_tpu.online.session import SessionFold
from predictionio_tpu.online.swap import DeltaSwapper, StaleState
from predictionio_tpu.ops.als import ALSConfig
from predictionio_tpu.telemetry import slo, tenant, tracing
from predictionio_tpu.telemetry.lineage import LINEAGE, context_of
from predictionio_tpu.utils import faults

log = logging.getLogger(__name__)


def _truthy(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "on", "yes")


def _aware(dt: Optional[datetime]) -> Optional[datetime]:
    """Storage round trips may drop tzinfo; event times are UTC."""
    if dt is not None and dt.tzinfo is None:
        return dt.replace(tzinfo=timezone.utc)
    return dt


@dataclasses.dataclass
class OnlineConfig:
    """PIO_ONLINE_* posture (env-resolved like PIO_SERVING_*/_EXPERIMENT_*
    so every pre-fork pool worker folds the same way)."""

    interval_s: float = 0.25
    overlap_s: float = OVERLAP.total_seconds()
    max_batch: int = 4096
    fold_items: bool = True
    parity_every_s: float = 0.0  # 0 = manual/gate-driven only
    app_id: Optional[int] = None  # override DataSource appName resolution

    @classmethod
    def from_env(cls) -> Optional["OnlineConfig"]:
        if not _truthy(os.environ.get("PIO_ONLINE", "")):
            return None
        e = os.environ.get
        app_id = e("PIO_ONLINE_APP_ID", "")
        return cls(
            interval_s=float(e("PIO_ONLINE_INTERVAL_S", "0.25")),
            overlap_s=float(e("PIO_ONLINE_OVERLAP_S",
                              str(OVERLAP.total_seconds()))),
            max_batch=int(e("PIO_ONLINE_MAX_BATCH", "4096")),
            fold_items=_truthy(e("PIO_ONLINE_FOLD_ITEMS", "1")),
            parity_every_s=float(e("PIO_ONLINE_PARITY_EVERY_S", "0")),
            app_id=int(app_id) if app_id else None,
        )


@dataclasses.dataclass
class _VariantCtx:
    variant: str
    app_id: int
    event_names: List[str]
    buy_rating: float
    # (position in state.models, fold handle) per foldable model — one
    # handle per model FAMILY the variant serves (foldin.FoldModel)
    folds: List[Tuple[int, FoldModel]]

    @property
    def als(self) -> List[Tuple[int, ALSConfig]]:
        """The ALS slice of the fold handles as (idx, config) pairs —
        the parity check and gate drills re-solve against the config
        directly, and predate the FoldModel generalization."""
        return [(idx, h.cfg) for idx, h in self.folds
                if isinstance(h, ALSFold)]


class _FoldTailer(StoreTailer):
    """Batch-mode tailer: the whole batch folds and swaps BEFORE any
    watermark/seen state advances (at-least-once; fold-in idempotence
    makes replay free — see ingest/tailer.py)."""

    def __init__(self, plane: "OnlinePlane", app_id: int, **kw):
        super().__init__(plane.storage, app_id=app_id, **kw)
        self._plane = plane

    def _process(self, fresh: list) -> int:
        applied = self._plane._fold_batch(self.app_id, fresh)
        # the crash window: events folded and served, watermark not yet
        # advanced — a kill here must lose nothing (crash drill)
        faults.inject("online.pre_watermark")
        for e in fresh:
            self._mark(e)
        if self._since is not None:
            lag = (datetime.now(timezone.utc)
                   - _aware(self._since)).total_seconds()
            ONLINE_LAG.set(max(0.0, lag))
        return applied


class OnlinePlane:
    """Owns the fold tailers (one per event-store app) and the parity
    loop for one PredictionServer."""

    def __init__(self, server, config: Optional[OnlineConfig] = None):
        self.config = config or OnlineConfig()
        self._server = server
        self.storage = server.storage
        self._fold_lock = threading.Lock()
        self._parity_thread: Optional[threading.Thread] = None
        self._parity_stop = threading.Event()
        self._swapper = DeltaSwapper(server._states, server._state_lock)
        self.events_folded = 0
        # per-(app, event_names, buy_rating) keep-last history cache —
        # see _gather_histories for the contract
        self._hist_cache: Dict[tuple, Dict[str, dict]] = {}
        self._contexts: List[_VariantCtx] = []
        self._tailers: List[_FoldTailer] = []
        self.rebase()

    # -- context resolution --------------------------------------------------
    def _resolve_contexts(self) -> List[_VariantCtx]:
        out = []
        for variant, state in self._server._states.items():
            dsp = state.engine_params.data_source_params
            app_id = self.config.app_id
            if app_id is None:
                app_name = getattr(dsp, "appName", None)
                if not app_name:
                    log.warning("online: variant %r has no appName; skipped",
                                variant)
                    continue
                app = self.storage.meta_apps().get_by_name(app_name)
                if app is None:
                    log.warning("online: app %r not found; variant %r "
                                "skipped", app_name, variant)
                    continue
                app_id = app.id
            folds: List[Tuple[int, FoldModel]] = []
            for idx, (_, params) in enumerate(
                    state.engine_params.algorithm_params_list):
                model = state.models[idx]
                if isinstance(model, ALSModel):
                    folds.append((idx, ALSFold(ALSConfig(
                        rank=getattr(params, "rank", 10),
                        reg=getattr(params, "lambda_", 0.01),
                        implicit=getattr(params, "implicitPrefs", False),
                        alpha=getattr(params, "alpha", 1.0),
                        seed=getattr(params, "seed", None) or 0,
                        split_cap=getattr(params, "splitCap", 32768),
                    ))))
                elif isinstance(model, SessionRecModel):
                    folds.append((idx, SessionFold(
                        max_seq_len=getattr(params, "maxSeqLen",
                                            model.max_seq_len))))
            if not folds:
                log.info("online: variant %r serves no foldable model; "
                         "skipped", variant)
                continue
            out.append(_VariantCtx(
                variant=variant, app_id=app_id,
                event_names=list(getattr(dsp, "eventNames", ["rate", "buy"])),
                buy_rating=float(getattr(dsp, "buyRating", 4.0)),
                folds=folds))
        return out

    def rebase(self) -> None:
        """(Re)derive variant contexts and tailers from the CURRENT served
        states — called at construction and after a full /reload. The
        watermark restarts at the oldest served instance's train start
        minus the overlap, so events that landed during/after training
        fold in (idempotently, even if the new instance already saw
        them)."""
        with self._fold_lock:
            self._contexts = self._resolve_contexts()
            starts = [
                _aware(self._server._states[c.variant].instance.start_time)
                for c in self._contexts
                if self._server._states[c.variant].instance.start_time]
            since = min(starts) if starts else None
            overlap = timedelta(seconds=self.config.overlap_s)
            by_app: Dict[int, List[str]] = {}
            for c in self._contexts:
                by_app.setdefault(c.app_id, []).extend(c.event_names)
            running = bool(self._tailers) and any(
                t._thread is not None for t in self._tailers)
            for t in self._tailers:
                t.stop()
            self._tailers = [
                _FoldTailer(self, app_id,
                            interval_s=self.config.interval_s,
                            event_names=sorted(set(names)),
                            overlap=overlap, name=f"online-fold-{app_id}",
                            since=since, max_batch=self.config.max_batch)
                for app_id, names in sorted(by_app.items())]
            if running:
                for t in self._tailers:
                    t.start()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        for t in self._tailers:
            t.start()
        if self.config.parity_every_s > 0 and self._parity_thread is None:
            self._parity_stop.clear()
            self._parity_thread = threading.Thread(
                target=self._parity_run, name="online-parity", daemon=True)
            self._parity_thread.start()

    def stop(self) -> None:
        for t in self._tailers:
            t.stop()
        self._parity_stop.set()
        t = self._parity_thread
        if t is not None:
            t.join(timeout=5.0)
            self._parity_thread = None

    def poll_once(self) -> int:
        """One synchronous tail pass over every app (tests, drills)."""
        return sum(t.poll_once() for t in self._tailers)

    def snapshot(self) -> dict:
        marks = [t._since for t in self._tailers if t._since is not None]
        return {
            "variants": [c.variant for c in self._contexts],
            "eventsFolded": self.events_folded,
            "watermark": (min(_aware(m) for m in marks).isoformat()
                          if marks else None),
        }

    # -- fold pass ------------------------------------------------------------
    def _value(self, e, ctx: _VariantCtx) -> Optional[float]:
        """The DataSource quickstart rule: explicit rating for "rate"
        events, the configured implicit rating otherwise; malformed
        ratings drop the event (same as the columnar NaN filter)."""
        if e.event == "rate":
            try:
                v = float(e.properties.to_dict().get("rating"))
            except (TypeError, ValueError):
                return None
            return None if np.isnan(v) else v
        return ctx.buy_rating

    def _fetch_histories(self, ctx: _VariantCtx, ids, side: str):
        """FULL keep-last histories for a batch of same-side entities as
        {id: {opposing_id: (event_time, value)}} — ONE indexed `find()`
        (idx_events_entity / idx_events_target, IN-style id batch). One
        call matters: each store round trip releases and re-queues for
        the GIL, and under query load a fold pass doing hundreds of
        point lookups convoys behind the serving threads."""
        kw = dict(channel_id=None, entity_type="user",
                  target_entity_type="item",
                  event_names=ctx.event_names)
        kw["entity_id" if side == "user" else "target_entity_id"] = \
            sorted(ids)
        out: Dict[str, dict] = {i: {} for i in ids}
        events = self.storage.l_events().find(ctx.app_id, **kw)
        for e in sorted(events, key=lambda e: e.event_time):
            eid = e.entity_id if side == "user" else e.target_entity_id
            other = e.target_entity_id if side == "user" else e.entity_id
            if not other:
                continue
            v = self._value(e, ctx)
            if v is not None:
                out[str(eid)][str(other)] = (_aware(e.event_time), v)
        return out

    def _history(self, ctx: _VariantCtx, entity_id: str, side: str):
        """One entity's FULL rating history as [(opposing_id, value)],
        deduped keep-last in event-time order (the Preparator's rule).
        Pure store read — parity/gate/test entry point, never cached."""
        pairs = self._fetch_histories(ctx, [entity_id], side)[entity_id]
        return [(o, v) for o, (_, v) in pairs.items()]

    def _gather_histories(self, ctx: _VariantCtx, users, items, events):
        """Full keep-last histories for every dirty entity, O(batch)
        steady state: a dirty entity's history is fetched ONCE through
        the store's per-entity index and cached; from then on the tailed
        batch itself keeps the cache current. The naive alternative —
        re-scanning the store per poll — made the fold pass quadratic in
        total event count and was the difference between the freshness
        bench draining its backlog and drowning in it.

        Safe under the tailer's at-least-once replay (keep-last re-apply
        of the same event is a no-op) and across `rebase()` (the event
        store is append-only, so cached histories never go stale — a
        redelivered pre-watermark event just overwrites equal values).
        Bounded by the same data the Preparator would hold: one (time,
        value) pair per observed (entity, opposing) edge."""
        cache = self._hist_cache.setdefault(
            (ctx.app_id, tuple(ctx.event_names), ctx.buy_rating),
            {"user": {}, "item": {}})
        for side, ids in (("user", users), ("item", items)):
            tracked = cache[side]
            missing = [eid for eid in ids if eid not in tracked]
            if missing:
                tracked.update(self._fetch_histories(ctx, missing, side))
        u_tracked, i_tracked = cache["user"], cache["item"]
        for e in events:
            # find() pre-filters names for the tailer; raw batches here
            # may carry anything
            if ctx.event_names and e.event not in ctx.event_names:
                continue
            v = self._value(e, ctx)
            if v is None:
                continue
            u, it = str(e.entity_id), str(e.target_entity_id)
            t = _aware(e.event_time)
            for tracked, key, other in ((u_tracked, u, it),
                                        (i_tracked, it, u)):
                pairs = tracked.get(key)
                if pairs is None:  # not a dirty-ever entity on this side
                    continue
                old = pairs.get(other)
                if old is None or t >= old[0]:
                    pairs[other] = (t, v)
        # histories carry (opposing_id, value, event_time) triples: ALS
        # folds consume the (id, value) pairs, the session fold needs
        # (id, time) to rebuild windows — one gather serves every family
        return ({u: [(o, v, t) for o, (t, v) in u_tracked[u].items()]
                 for u in users if u_tracked[u]},
                {i: [(o, v, t) for o, (t, v) in i_tracked[i].items()]
                 for i in items if i_tracked[i]})

    def _fold_batch(self, app_id: int, events: list) -> int:
        if not events:
            return 0
        t0 = time.perf_counter()
        with self._fold_lock:
            model_events = [
                e for e in events
                if e.entity_id and e.target_entity_id
                and e.entity_type == "user"
                and (e.target_entity_type or "item") == "item"]
            dirty_users = sorted({str(e.entity_id) for e in model_events})
            dirty_items = (sorted({str(e.target_entity_id)
                                   for e in model_events})
                           if self.config.fold_items else [])
            folded_any = False
            folded_families: set = set()
            for ctx in self._contexts:
                if ctx.app_id != app_id or not dirty_users:
                    continue
                user_hist, item_hist = self._gather_histories(
                    ctx, dirty_users, dirty_items, model_events)
                if not user_hist and not item_hist:
                    continue
                state = self._server._states.get(ctx.variant)
                if state is None:
                    continue
                try:
                    models = list(state.models)
                    t_fold = time.perf_counter()
                    for idx, handle in ctx.folds:
                        models[idx], _ = handle.fold(
                            models[idx], user_hist, item_hist)
                        folded_families.add(handle.family)
                    fold_s = time.perf_counter() - t_fold
                    t_swap = time.perf_counter()
                    self._swapper.swap(ctx.variant, state, models,
                                       sorted(user_hist))
                    swap_s = time.perf_counter() - t_swap
                    folded_any = True
                    # the swap call also publishes the invalidations, so
                    # the invalidate stage lands at the same instant; its
                    # detail is the touched-user fan-out
                    now_s = time.time()
                    n_touched = str(len(user_hist))
                    for e in model_events:
                        lctx = context_of(e)
                        LINEAGE.record_stage(lctx, "fold",
                                             duration_s=fold_s, now=now_s)
                        LINEAGE.record_stage(lctx, "swap",
                                             duration_s=swap_s,
                                             detail=ctx.variant, now=now_s)
                        LINEAGE.record_stage(lctx, "invalidate",
                                             detail=n_touched, now=now_s)
                except StaleState:
                    # a full /reload landed mid-fold; re-resolve and make
                    # the tailer replay this batch against the new state
                    raise
                except Exception:
                    ONLINE_FOLD_ERRORS.inc()
                    for e in model_events:
                        LINEAGE.record_stage(context_of(e), "fold",
                                             error=True)
                    log.exception("online: fold failed for variant %r; "
                                  "batch will replay", ctx.variant)
                    raise
        if folded_any:
            now = datetime.now(timezone.utc)
            samples = []
            for e in model_events:
                age = max(0.0,
                          (now - _aware(e.event_time)).total_seconds())
                lctx = context_of(e)
                if lctx is not None:
                    # an open trace during observe() links the histogram
                    # bucket to this trace id as an exemplar
                    with tracing.trace(lctx.trace_id):
                        ONLINE_EVENT_TO_SERVABLE.observe(age)
                else:
                    ONLINE_EVENT_TO_SERVABLE.observe(age)
                # per-family slice: one observation per family that
                # actually folded this batch (als, sessionrec, ...)
                for fam in sorted(folded_families):
                    ONLINE_FAMILY_FRESHNESS.labels(family=fam).observe(age)
                samples.append((200, age))
                # per-tenant freshness slice: the envelope's app (minted
                # at the auth boundary) wins over the tailer's app_id so
                # cross-app replays attribute to the event's true owner
                tenant.observe_freshness(
                    (lctx.app if lctx is not None and lctx.app
                     else app_id), age)
                LINEAGE.complete(lctx, freshness_s=age)
            slo.observe_many("online", "event_to_servable", samples)
            ONLINE_EVENTS_FOLDED.inc(len(model_events))
            tenant.record_folded(app_id, len(model_events))
            self.events_folded += len(model_events)
        ONLINE_FOLDIN_SECONDS.observe(time.perf_counter() - t0)
        return len(model_events) if folded_any else 0

    # -- full-retrain parity ---------------------------------------------------
    def parity_check(self, max_rows: int = 2048) -> Dict[str, dict]:
        """Bound served-factor drift against a fresh half-epoch: re-read
        the training data through each variant's own DataSource +
        Preparator, re-solve every common user row against the SERVED
        item factors, and compare. Returns per-variant stats and sets
        `online_parity_drift`."""
        from predictionio_tpu.controller.context import WorkflowContext

        out: Dict[str, dict] = {}
        for ctx in self._contexts:
            state = self._server._states.get(ctx.variant)
            if state is None:
                continue
            ds, prep, _algos, _serving = state.components
            wctx = WorkflowContext(storage=self.storage)
            pd = prep.prepare(wctx, ds.read_training(wctx))
            for idx, cfg in ctx.als:
                model = state.models[idx]
                u_served = np.asarray(
                    [model.user_ids.get(s, -1)
                     for s in pd.user_ids.from_index(
                         np.arange(len(pd.user_ids)))], np.int32)
                i_served = np.asarray(
                    [model.item_ids.get(s, -1)
                     for s in pd.item_ids.from_index(
                         np.arange(len(pd.item_ids)))], np.int32)
                u = u_served[pd.user_idx]
                i = i_served[pd.item_idx]
                keep = (u >= 0) & (i >= 0)
                u, i, r = u[keep], i[keep], pd.ratings[keep]
                rows = np.unique(u)[:max_rows]
                sel = np.isin(u, rows)
                u, i, r = u[sel], i[sel], r[sel]
                entries = []
                for row in rows:
                    m = u == row
                    entries.append((i[m], r[m]))
                resolved = foldin.solve_rows(
                    np.asarray(model.item_factors), entries, cfg)
                served = np.asarray(model.user_factors)[rows]
                delta = np.abs(resolved - served)
                scale = float(np.max(np.abs(served), initial=1e-9))
                stats = {
                    "rows": int(len(rows)),
                    "max_abs": float(delta.max(initial=0.0)),
                    "rms": float(np.sqrt(np.mean(delta ** 2))
                                 if delta.size else 0.0),
                    "scale": scale,
                }
                stats["rel_max"] = stats["max_abs"] / scale
                out[ctx.variant] = stats
                ONLINE_PARITY_DRIFT.labels(variant=ctx.variant).set(
                    stats["max_abs"])
                ONLINE_PARITY_CHECKS.labels(variant=ctx.variant).inc()
        return out

    def _parity_run(self) -> None:
        while not self._parity_stop.wait(self.config.parity_every_s):
            try:
                self.parity_check()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("online: parity check failed; retrying")
