"""pio-lint static-analysis engine: rule packs on known fixtures, the
suppression/baseline workflow, the migrated gate rules, and a self-scan
holding the live tree clean.

Also the regression tests for the concurrency/blocking findings the
first whole-repo run surfaced (fault-counter exactness, history meta
publication, traffic-share reads, the /stats.json registration) — if a
fix regresses, both the behavioral test here and the self-scan fail.
"""

import json
import os
import sys
import threading
import time

import pytest

from predictionio_tpu.analysis import astutil, engine
from predictionio_tpu.analysis.cli import main as lint_main
from predictionio_tpu.analysis.engine import (
    BaselineError,
    Finding,
    Module,
    Project,
)
from predictionio_tpu.analysis.gates import run_legacy_static

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_on_fixtures(rule_ids):
    return engine.run_rules(Project(FIXTURES), rule_ids)


# -- engine -----------------------------------------------------------------


class TestEngine:
    def test_finding_key_is_symbol_anchored(self):
        f = Finding("r", "a/b.py", 42, "msg", symbol="fn")
        assert f.key == "r:a/b.py:fn"
        assert Finding("r", "a/b.py", 42, "msg").key == "r:a/b.py:42"

    def test_suppressions_trailing_and_standalone(self):
        src = ("x = 1  # pio-lint: disable=rule-a\n"
               "# pio-lint: disable=rule-b, rule-c\n"
               "y = 2\n"
               "z = 3\n")
        m = Module("f.py", "f.py", src)
        assert m.suppressed("rule-a", 1)
        assert m.suppressed("rule-b", 3) and m.suppressed("rule-c", 3)
        assert not m.suppressed("rule-a", 3)
        assert not m.suppressed("rule-b", 4)

    def test_unknown_rule_is_an_error(self):
        with pytest.raises(KeyError):
            engine.run_rules(Project(FIXTURES), ["no-such-rule"])

    def test_suppression_text_inside_fstring_is_not_a_suppression(self):
        # suppressions come from the token stream, so a string that
        # merely *contains* the magic text must not disable anything
        src = ('msg = f"use  # pio-lint: disable=rule-a  inline"\n'
               "y = 2\n")
        m = Module("f.py", "f.py", src)
        assert not m.suppressed("rule-a", 1)
        assert not m.suppressed("rule-a", 2)

    def test_suppression_on_line_continuation(self):
        src = ("x = 1 + \\\n"
               "    2  # pio-lint: disable=rule-a\n"
               "# pio-lint: disable=rule-b\n"
               "y = (3 +\n"
               "     4)\n")
        m = Module("f.py", "f.py", src)
        # trailing comment binds to the physical line it sits on
        assert m.suppressed("rule-a", 2)
        assert not m.suppressed("rule-a", 1)
        # standalone comment covers the next line even when that
        # statement continues past it
        assert m.suppressed("rule-b", 4)
        assert not m.suppressed("rule-b", 5)

    def test_syntax_error_module_skipped_not_fatal(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        (tmp_path / "good.py").write_text(
            "import time\n"
            "class API:\n"
            "    def router(self, r):\n"
            "        r.get('/x.json', self._handle)\n"
            "        return r\n"
            "    def _handle(self, req):\n"
            "        time.sleep(1)\n"
            "        return req\n")
        proj = Project(str(tmp_path))
        # the scan survives and still flags the parsable module
        findings = engine.run_rules(proj, ["loop-blocking-call"])
        assert any(f.file == "good.py" for f in findings)
        # the call graph excludes the broken module instead of dying
        from predictionio_tpu.analysis import callgraph
        cg = callgraph.get(proj)
        assert all(fs.rel != "bad.py" for fs in cg.funcs.values())
        assert any(fs.rel == "good.py" for fs in cg.funcs.values())

    def test_baseline_entry_requires_reason(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(
            {"findings": [{"key": "r:f.py:fn", "reason": ""}]}))
        with pytest.raises(BaselineError):
            engine.load_baseline(str(p))
        p.write_text(json.dumps({"findings": [{"reason": "no key"}]}))
        with pytest.raises(BaselineError):
            engine.load_baseline(str(p))
        p.write_text(json.dumps(
            {"findings": [{"key": "r:f.py:fn", "reason": "reviewed"}]}))
        assert engine.load_baseline(str(p)) == {"r:f.py:fn": "reviewed"}

    def test_partition_splits_new_grandfathered_stale(self):
        f1 = Finding("r", "a.py", 1, "m", symbol="x")
        f2 = Finding("r", "b.py", 2, "m", symbol="y")
        baseline = {f2.key: "reviewed", "r:gone.py:z": "stale"}
        new, old, stale = engine.partition([f1, f2], baseline)
        assert new == [f1] and old == [f2] and stale == ["r:gone.py:z"]


# -- rule packs on fixtures -------------------------------------------------


class TestRaceRules:
    def test_known_racy_flags_rmw_and_inconsistent_locks(self):
        findings = run_on_fixtures(["race-shared-state"])
        racy = [f for f in findings if f.file == "known_racy.py"]
        attrs = {f.symbol for f in racy}
        assert any("count" in a for a in attrs), racy
        assert any("items" in a for a in attrs), racy

    def test_known_clean_and_suppressed_stay_silent(self):
        findings = run_on_fixtures(["race-shared-state"])
        assert not [f for f in findings
                    if f.file in ("known_clean.py", "suppressed.py")]

    def test_lock_inversion_reported_once(self):
        findings = run_on_fixtures(["race-lock-order"])
        inv = [f for f in findings if f.file == "lock_inversion.py"]
        assert len(inv) == 1, inv
        assert "lock_a" in inv[0].message and "lock_b" in inv[0].message


class TestLoopBlockingRule:
    def test_nonblocking_route_closure_flagged(self):
        findings = engine.run_rules(Project(FIXTURES),
                                    ["loop-blocking-call"])
        hits = [f for f in findings if f.file == "blocking_on_loop.py"]
        whats = " ".join(f.message for f in hits)
        assert ".execute()" in whats and "time.sleep" in whats
        # the blocking=True route's sleep is legal: all findings anchor
        # to the non-blocking route, with the containing qualname
        assert {f.symbol for f in hits} == {
            "GET /fast.json:FixtureAPI._handle_fast",
            "GET /fast.json:FixtureAPI._settle",
        }
        # the helper reached through the handler prints its chain
        settle = [f for f in hits if f.symbol.endswith("._settle")]
        assert settle and "via FixtureAPI._handle_fast" in settle[0].message

    def test_new_vocabulary_flagged_only_off_the_pool(self):
        findings = engine.run_rules(Project(FIXTURES),
                                    ["loop-blocking-call"])
        hits = [f for f in findings if f.file == "blocking_vocab.py"]
        whats = " ".join(f.message for f in hits)
        for what in ("shutil.rmtree", "os.replace", ".fetchmany()",
                     "socket.create_connection", ".connect()"):
            assert what in whats, what
        # the blocking=True bulk route makes the same calls legally
        assert not [f for f in hits if "/bulk.json" in f.symbol]

    def test_cross_module_chain_flagged(self):
        # the route module itself has nothing blocking — the PR 12
        # same-module rule had nothing to anchor to...
        from predictionio_tpu.analysis.eventloop import _blocking_calls
        proj = Project(FIXTURES)
        assert not _blocking_calls(proj.module("xmod_routes.py").tree)
        assert not _blocking_calls(proj.module("xmod_helper.py").tree)
        # ...but the whole-program rule blames the db module on the
        # route, witness chain included
        findings = engine.run_rules(proj, ["loop-blocking-call"])
        hits = [f for f in findings if f.file == "xmod_db.py"]
        assert hits and all(
            f.symbol == "GET /report.json:fetch_rows" for f in hits)
        assert "via XModAPI._handle_report" in hits[0].message
        assert "load_report" in hits[0].message

    def test_same_named_nested_functions_get_distinct_keys(self):
        findings = engine.run_rules(Project(FIXTURES),
                                    ["loop-blocking-call"])
        hits = [f for f in findings if f.file == "nested_dup.py"]
        keys = {f.key for f in hits}
        assert len(keys) == len(hits) == 2, hits
        assert {f.symbol for f in hits} == {
            "<loop>:spawn_fast.<locals>.run",
            "<loop>:spawn_slow.<locals>.run",
        }

    def test_live_stats_route_is_blocking(self):
        # regression for the finding that started this: GET /stats.json
        # reaches the sqlite-backed meta accessors via _auth, so its
        # registration must put it on the worker pool
        proj = Project(REPO_ROOT, subdirs=("predictionio_tpu",))
        mod = proj.module("data/api.py")
        regs = [r for r in astutil.registration_details(mod.tree)
                if r.path == "/stats.json"]
        assert regs and all(r.blocking for r in regs)


class TestShapeRule:
    def test_len_into_jit_flagged_pad_helper_not(self):
        findings = run_on_fixtures(["jit-shape-discipline"])
        hits = [f for f in findings if f.file == "retrace_bait.py"]
        assert {f.symbol for f in hits} == {"bad_call->solve"}, hits

    def test_unbounded_history_len_into_jitted_scorer_flagged(self):
        # sequence-ladder discipline: len(history) straight into the
        # jitted sessionrec scorer retraces per history length; routing
        # it through a seq-tier pad helper is the legal spelling
        findings = run_on_fixtures(["jit-shape-discipline"])
        hits = [f for f in findings if f.file == "session_bait.py"]
        assert {f.symbol for f in hits} == {"bad_session_call->score"}, hits


class TestLabelRule:
    def test_unbounded_label_flagged_capped_and_constant_not(self):
        findings = run_on_fixtures(["no-unbounded-metric-labels"])
        hits = [f for f in findings if f.file == "label_taint.py"]
        # only bad_site's event= kwarg: str(app_id) is tainted too but
        # good_site caps it, bad_site's app_id IS tainted and uncapped
        assert {f.symbol for f in hits} == {"EVENTS.app_id",
                                            "EVENTS.event"}, hits
        msgs = " ".join(f.message for f in hits)
        assert "event_name" in msgs and "app_id" in msgs

    def test_live_tree_has_no_unbounded_labels(self):
        # the one historically-unbounded site (data/api.py EVENTS_TOTAL)
        # now flows through tenant_label/capped_label; keep it that way
        proj = Project(REPO_ROOT, subdirs=engine.DEFAULT_SUBDIRS)
        findings = engine.run_rules(proj, ["no-unbounded-metric-labels"])
        assert findings == [], [(f.file, f.line, f.message)
                                for f in findings]


class TestGateRules:
    def test_alias_registration_resolved_to_handler(self):
        # satellite 6: `h = self._handle_query; r.post(..., h)` must
        # resolve through the alias — the old resolver missed it
        findings = run_on_fixtures(["gate-serving-admission"])
        hits = [f for f in findings if f.file == "alias_handler.py"]
        msgs = " ".join(f.message for f in hits)
        assert "_handle_query" in msgs
        assert "without" in msgs and "predict" in msgs

    def test_legacy_static_matches_engine_and_passes_live(self):
        pkg = os.path.join(REPO_ROOT, "predictionio_tpu")
        for rule_id in ("gate-hotpath-json", "gate-serving-admission",
                        "gate-ingest-funnel"):
            assert run_legacy_static(rule_id, pkg) == []

    def test_legacy_lines_reconstruct_old_format(self):
        from predictionio_tpu.analysis.gates import legacy_lines
        lines = legacy_lines([
            Finding("r", "a.py", 3, "boom"),
            Finding("r", "a.py", 0, "file-scoped"),
            Finding("r", "", 0, "sentinel"),
        ])
        assert lines == ["a.py:3: boom", "a.py: file-scoped", "sentinel"]


# -- self-scan + CLI --------------------------------------------------------


class TestSelfScan:
    def test_live_tree_scans_clean_modulo_baseline_within_budget(self):
        t0 = time.perf_counter()
        proj = Project(REPO_ROOT, subdirs=engine.DEFAULT_SUBDIRS)
        findings = engine.run_rules(proj)
        elapsed = time.perf_counter() - t0
        baseline = engine.load_baseline(
            os.path.join(REPO_ROOT, engine.DEFAULT_BASELINE))
        new, _old, _stale = engine.partition(findings, baseline)
        assert not new, "\n".join(f.render() for f in new)
        # the whole-package scan (call graph + lock graph included)
        # must stay inside the pre-push budget
        assert elapsed <= 10.0, f"package scan took {elapsed:.1f}s"

    def test_cli_json_exit_zero(self, capsys):
        rc = lint_main(["--root", REPO_ROOT, "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["new"] == 0 and payload["baseline_error"] is None
        assert payload["modules"] > 100

    def test_cli_rules_filter_and_list(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        listed = capsys.readouterr().out
        for rid in ("race-shared-state", "loop-blocking-call",
                    "jit-shape-discipline", "gate-hotpath-json",
                    "gate-serving-admission", "gate-ingest-funnel",
                    "coverage-fault-site", "coverage-metric-docs",
                    "race-lock-order", "race-global-rmw"):
            assert rid in listed
        assert lint_main(["--rules", "bogus"]) == 2

    def test_cli_changed_filters_reporting(self, capsys):
        # against HEAD the filter is the worktree delta — a clean tree
        # reports zero either way, and the payload carries the filter
        rc = lint_main(["--root", REPO_ROOT, "--changed", "HEAD",
                        "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert isinstance(payload["changed_filter"], list)
        assert all(f["file"] in payload["changed_filter"]
                   for f in payload["findings"])
        # an unknown ref is a usage error, not a crash
        assert lint_main(["--root", REPO_ROOT,
                          "--changed", "no-such-ref-xyz"]) == 2
        capsys.readouterr()


# -- concurrency-fix regressions --------------------------------------------


class TestConcurrencyFixes:
    def test_fault_hit_counter_exact_under_threads(self, monkeypatch):
        from predictionio_tpu.utils import faults
        site = "analysis.regression.site"
        monkeypatch.setenv("PIO_FAULTS", f"{site}:999999=delay:0")
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            faults._parse()
            n_threads, per_thread = 8, 2000

            def hammer():
                for _ in range(per_thread):
                    faults.inject(site)

            threads = [threading.Thread(target=hammer)
                       for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert faults._hits[site] == n_threads * per_thread
        finally:
            sys.setswitchinterval(old_interval)
            monkeypatch.setenv("PIO_FAULTS", "")
            faults._parse()

    def test_history_meta_consistent_under_concurrent_reads(self):
        from predictionio_tpu.telemetry.history import MetricsHistory
        from predictionio_tpu.telemetry.registry import MetricsRegistry
        reg = MetricsRegistry()
        counter = reg.counter("test_hammer_total", "fixture").labels()
        hist = MetricsHistory(registry=reg, interval_s=0.05, window_s=10.0,
                              prefixes=("test_",))
        errors = []
        stop = threading.Event()

        def read():
            while not stop.is_set():
                try:
                    snap = hist.snapshot_json()
                    for fam in snap["families"].values():
                        assert fam["type"]
                    hist.series("test_hammer_total")
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(e)
                    return

        readers = [threading.Thread(target=read) for _ in range(3)]
        for t in readers:
            t.start()
        for i in range(300):
            counter.inc()
            hist.sample_now(now=1000.0 + i)
        stop.set()
        for t in readers:
            t.join()
        assert not errors, errors
        snap = hist.snapshot_json()
        assert "test_hammer_total" in snap["families"]

    def test_traffic_share_consistent_under_load(self):
        from predictionio_tpu.experiment.router import (
            ExperimentConfig,
            VariantRouter,
        )
        from predictionio_tpu.serving import ServingConfig, ServingPlane
        planes = {
            v: ServingPlane(lambda qs: [{"ok": 1} for _ in qs],
                            config=ServingConfig(batching=False),
                            name=f"analysis-{v}")
            for v in ("a", "b")
        }
        router = VariantRouter(
            planes, ExperimentConfig(variants=("a", "b"),
                                     share_window=64),
            server_name="analysistest")
        errors = []
        try:
            def query(i):
                for j in range(50):
                    try:
                        router.handle_query({"user": f"u{i}-{j}", "num": 1})
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return

            def observe():
                for _ in range(100):
                    shares = router.traffic_share()
                    total = sum(shares.values())
                    if shares and not (0.0 <= total <= 1.0 + 1e-9):
                        errors.append(AssertionError(shares))
                        return

            threads = ([threading.Thread(target=query, args=(i,))
                        for i in range(4)]
                       + [threading.Thread(target=observe)])
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            router.close()
            for p in planes.values():
                p.close()
        assert not errors, errors
