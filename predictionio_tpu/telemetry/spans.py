"""Per-request span timelines — the flight recorder's data model.

PR 2 gave every request a trace id and every subsystem aggregate metrics;
what neither can answer is "*which stage* of THIS slow request ate the
time". A `Timeline` is that answer: one bounded list of named spans
(start offset + duration, measured on `time.monotonic()`) hanging off a
contextvar for the request's whole handler run. The HTTP middleware opens
one per request; `spans.span("serving.admission")` blocks record into it
from anywhere downstream; the flight recorder (telemetry/recorder.py)
tail-samples the finished product.

Two recording paths, because two threads touch a request:

- `span(name)` — a context manager for work on the *request's own thread*
  (admission, validation, storage calls). When jax is loaded it also
  opens a `jax.profiler.TraceAnnotation`, so the same stage names appear
  on XLA timelines and in the flight recorder.
- `record(name, duration_s, start_s=...)` — for stages measured on
  *another* thread (the micro-batcher's dispatcher, the group-commit
  writer) and stamped onto the pending entry; the handler thread copies
  the stamps into its own timeline after being woken. Contextvars don't
  cross threads, and handing the timeline itself to the dispatcher would
  make one slow request's bookkeeping a shared-state problem.

Clock discipline: all offsets are `time.monotonic()` relative to the
timeline's `t0`, the same clock the serving/ingest planes already stamp
deadlines and queue waits with — so cross-thread stamps land on the same
axis as same-thread spans without conversion.

Everything here sits on the per-request hot path under the established
≤5% instrumentation budget: __slots__ classes, one contextvar get per
span, a plain list append, and a hard `MAX_SPANS` cap so a pathological
loop cannot grow a timeline without bound.
"""

from __future__ import annotations

import contextvars
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from predictionio_tpu.telemetry import tracing

_sys_modules = sys.modules

# Hard per-timeline span cap: a runaway loop (e.g. a storage op per event
# row) must not turn one request's timeline into a memory leak. Overflow
# is counted on the timeline so truncation is visible, never silent.
MAX_SPANS = 128


# One recorded stage is a plain tuple (name, start_s, duration_s, error,
# nested) — tuple allocation is the cheapest record Python can make, and
# span recording sits inside the ≤5% per-request overhead budget
# (tests/test_telemetry.py). `nested` marks spans recorded inside another
# span (e.g. a storage op inside an inline commit): they refine
# attribution but are excluded from `Timeline.span_sum_s()` so stage
# sums don't double-count.


class Timeline:
    """The per-request flight record: identity + bounded span list.

    Built by the HTTP middleware (or a workflow run) at request start;
    `status`/`duration_s` are stamped by `finish()`; the recorder decides
    afterwards whether the finished timeline is worth keeping."""

    __slots__ = ("trace_id", "server", "route", "method", "start_time",
                 "t0", "spans", "status", "duration_s", "error", "pinned",
                 "dropped_spans", "depth")

    def __init__(self, server: str, route: str, method: str, trace_id: str):
        self.server = server
        self.route = route
        self.method = method
        self.trace_id = trace_id
        # epoch start is derived lazily in to_dict (one fewer clock call
        # on the per-request path); t0 anchors the span-offset axis
        self.start_time = 0.0
        self.t0 = time.monotonic()
        self.spans: List[tuple] = []
        self.status: Optional[int] = None
        self.duration_s = 0.0
        self.error = False
        # force-capture flag (X-PIO-Debug header, workflow runs): the
        # recorder keeps pinned timelines regardless of sampling
        self.pinned = False
        self.dropped_spans = 0
        # live nesting depth of `span` context managers on this thread;
        # spans recorded at depth > 0 are marked nested
        self.depth = 0

    def record(self, name: str, start_s: float, duration_s: float,
               error: bool = False, nested: bool = False) -> None:
        if len(self.spans) >= MAX_SPANS:
            self.dropped_spans += 1
            return
        self.spans.append((name, start_s, duration_s, error, nested))

    def span_sum_s(self) -> float:
        """Sum of top-level stage durations — the acceptance check that
        stage attribution accounts for the measured wall latency compares
        this against `duration_s`. Nested spans are excluded: they refine
        a parent stage, so counting them would double-bill the time."""
        return sum(s[2] for s in self.spans if not s[4])

    def to_dict(self) -> dict:
        if not self.start_time:
            # freeze time: map the monotonic anchor onto the epoch axis
            self.start_time = time.time() - (time.monotonic() - self.t0)
        spans_out = []
        for name, start_s, duration_s, error, nested in self.spans:
            s = {
                "name": name,
                "start_ms": round(start_s * 1e3, 3),
                "duration_ms": round(duration_s * 1e3, 3),
            }
            if error:
                s["error"] = True
            if nested:
                s["nested"] = True
            spans_out.append(s)
        d = {
            "trace_id": self.trace_id,
            "server": self.server,
            "route": self.route,
            "method": self.method,
            "start_time": self.start_time,
            "status": self.status,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "spans": spans_out,
        }
        if self.error:
            d["error"] = True
        if self.dropped_spans:
            d["dropped_spans"] = self.dropped_spans
        return d


_active: contextvars.ContextVar[Optional[Timeline]] = \
    contextvars.ContextVar("pio_timeline", default=None)

# Thread-ident → active Timeline. Contextvars are invisible from other
# threads, but the stack sampler (telemetry/profiler.py) must attribute a
# thread's frames to the request it is serving — so begin/finish mirror
# the active timeline into this plain dict. Dict store/pop on int keys is
# GIL-atomic; no lock on the per-request hot path. The sampler only ever
# *reads* (a racy read sees either the old or new timeline, both fine for
# a statistical profile).
_BY_THREAD: Dict[int, Timeline] = {}


def current() -> Optional[Timeline]:
    return _active.get()


def thread_timeline(ident: int) -> Optional[Timeline]:
    """The timeline active on another thread, by thread ident — the
    profiler's route/trace join point. Best-effort by design."""
    return _BY_THREAD.get(ident)


def begin(server: str, route: str, method: str,
          trace_id: str) -> tuple[Timeline, contextvars.Token]:
    """Open a timeline for the current context; pair with `finish()`."""
    tl = Timeline(server, route, method, trace_id)
    token = _active.set(tl)
    _BY_THREAD[threading.get_ident()] = tl
    return tl, token


def finish(tl: Timeline, token: contextvars.Token, status: Optional[int],
           duration_s: float, error: bool = False) -> Timeline:
    """Stamp the outcome and deactivate. The caller decides what happens
    to the finished timeline (normally: offer it to the flight recorder)."""
    tl.status = status
    tl.duration_s = duration_s
    tl.error = tl.error or error
    _active.reset(token)
    # Restore the outer timeline for nested begins (workflow runs that
    # issue sub-requests on the same thread); drop the entry otherwise so
    # idle pool threads don't pin finished timelines.
    outer = _active.get()
    ident = threading.get_ident()
    if outer is None:
        _BY_THREAD.pop(ident, None)
    else:
        _BY_THREAD[ident] = outer
    return tl


def _reinit_after_fork() -> None:
    # Thread idents are reused and only the forking thread survives into
    # the child — inherited entries would mis-attribute fresh threads.
    _BY_THREAD.clear()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)


def record(name: str, duration_s: float,
           start_s: Optional[float] = None,
           error: bool = False,
           nested: Optional[bool] = None) -> None:
    """Record a pre-measured span into the active timeline (no-op without
    one — storage ops triggered by untimed work, committer threads).

    `start_s` is an offset on the timeline's monotonic axis; when omitted
    the span is assumed to have just ended. `nested` defaults to "am I
    inside a live span context"; cross-thread stamps that refine a stage
    recorded the same way (the batcher's dispatch host/device split) pass
    it explicitly, since the stage's span context is long gone by the
    time the waiting thread copies the stamps."""
    tl = _active.get()
    if tl is None:
        return
    if start_s is None:
        start_s = time.monotonic() - tl.t0 - duration_s
    tl.record(name, start_s, duration_s, error,
              nested=tl.depth > 0 if nested is None else nested)


def record_between(name: str, start_monotonic: float,
                   end_monotonic: float,
                   nested: Optional[bool] = None) -> None:
    """Record a span from two absolute `time.monotonic()` stamps — the
    shape cross-thread stages arrive in (enqueued_at / taken_at / done
    stamps on a pending queue entry)."""
    tl = _active.get()
    if tl is None:
        return
    tl.record(name, start_monotonic - tl.t0,
              max(0.0, end_monotonic - start_monotonic),
              nested=tl.depth > 0 if nested is None else nested)


class span:
    """A named stage: timeline record + XLA trace annotation.

    Drop-in for tracing.span everywhere a stage should show up in the
    flight recorder; on threads without an active timeline only the
    annotation remains (train workers, committer threads). Unlike
    tracing.span it does NOT open a child trace context: stage spans are
    identified by name in the timeline, not by span id, and the context
    push/pop would triple the cost of a stage on the serving hot path
    (the ≤5% overhead bar in tests/test_telemetry.py)."""

    __slots__ = ("name", "_tl", "_t0", "_nested", "_ann")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> "span":
        # inline the jax-loaded check: _jax_annotation is a call + dict
        # lookup per stage, and most processes (ingest, tests) never
        # load jax
        if "jax" in _sys_modules:
            ann = self._ann = tracing._jax_annotation(self.name)
            if ann is not None:
                try:
                    ann.__enter__()
                except Exception:
                    self._ann = None
        else:
            self._ann = None
        tl = self._tl = _active.get()
        if tl is not None:
            self._nested = tl.depth > 0
            tl.depth += 1
            self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        tl = self._tl
        if tl is not None:
            t1 = time.monotonic()
            tl.depth -= 1
            tl.record(self.name, self._t0 - tl.t0, t1 - self._t0,
                      error=exc_type is not None, nested=self._nested)
        ann = self._ann
        if ann is not None:
            try:
                ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        return False
