"""Serving gate — CI check that no predict route bypasses admission.

Run via `python quality.py --serving-gate`. Mirrors the telemetry gate's
two layers:

1. Static scan (AST, no imports, no jax): inside `predictionio_tpu/`,
   any handler that routes `/queries.json` — a legacy `do_*` HTTP method
   or a function registered on a Router (`router.post("/queries.json",
   self._handle_query)`) — must call the serving plane's `handle_query`
   (which is admit → dispatch → release), and must not call an engine
   `predict`/`predict_batch` itself — a handler that dispatches directly
   has no queue bound, no deadline handling, and no shed path, which is
   exactly the saturation-collapse mode this subsystem exists to
   prevent.

2. Runtime check: saturate a tiny ServingPlane (max_queue=1) and verify
   the second concurrent request raises ShedLoad carrying a positive
   Retry-After; verify an expired deadline raises DeadlineExceeded
   WITHOUT the dispatch function ever running; verify the serving_*
   telemetry families render on the registry.

Exit code 0 when clean; 1 with one line per violation otherwise.
"""

from __future__ import annotations

import ast
import os
import sys

from predictionio_tpu.utils import route_scan

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_EXEMPT = {
    os.path.join("serving", "gate.py"),
}

_QUERY_ROUTE = "/queries.json"
# engine dispatch spellings a predict handler must not call directly
_DIRECT_DISPATCH = {"predict", "predict_batch"}
# the admission-controlled entry point (ServingPlane.handle_query)
_PLANE_ENTRY = "handle_query"


def _contains_query_route(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == _QUERY_ROUTE:
            return True
    return False


def _scan_handler(fn: ast.FunctionDef, rel: str) -> list[str]:
    problems = []
    calls = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            calls.add(node.func.attr)
    if _PLANE_ENTRY not in calls:
        problems.append(
            f"{rel}:{fn.lineno}: {fn.name} routes {_QUERY_ROUTE} without "
            f"calling the serving plane's {_PLANE_ENTRY}() — predict "
            f"requests must pass admission control")
    direct = calls & _DIRECT_DISPATCH
    if direct:
        problems.append(
            f"{rel}:{fn.lineno}: {fn.name} calls {sorted(direct)} directly "
            f"in the {_QUERY_ROUTE} handler — dispatch belongs behind "
            f"ServingPlane.{_PLANE_ENTRY} (queue bound, deadlines, shed)")
    return problems


def _scan_file(path: str, rel: str) -> tuple[list[str], bool]:
    """Returns (problems, saw_query_route)."""
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read(), filename=rel)
        except SyntaxError as e:
            return [f"{rel}: unparseable ({e})"], False
    problems = []
    saw_route = False
    # legacy transport: do_* methods with the route constant inline
    for node in ast.walk(tree):
        if (isinstance(node, ast.FunctionDef) and node.name.startswith("do_")
                and _contains_query_route(node)):
            saw_route = True
            problems.extend(_scan_handler(node, rel))
    # event-loop transport: resolve router.post("/queries.json", fn)
    # back to fn's FunctionDef and hold it to the same contract
    for handler in route_scan.handlers_for(tree, _QUERY_ROUTE,
                                           method="POST"):
        saw_route = True
        if isinstance(handler, ast.FunctionDef):
            problems.extend(_scan_handler(handler, rel))
        else:
            problems.append(
                f"{rel}: {_QUERY_ROUTE} is registered to a lambda — the "
                f"predict handler must be a named function the gate can "
                f"hold to the admission contract")
    return problems, saw_route


def _static_scan() -> list[str]:
    problems = []
    found_route = False
    for dirpath, _dirnames, filenames in os.walk(_PKG_DIR):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, _PKG_DIR)
            if rel in _EXEMPT:
                continue
            file_problems, saw_route = _scan_file(path, rel)
            problems.extend(file_problems)
            found_route = found_route or saw_route
    if not found_route:
        # the gate must notice if the predict route itself disappears —
        # an empty scan proves nothing
        problems.append(
            f"static: no in-package handler routes {_QUERY_ROUTE}; "
            f"the serving gate has nothing to hold")
    return problems


def _runtime_check() -> list[str]:
    import threading
    import time

    from predictionio_tpu.serving import (
        AdmissionConfig,
        DeadlineExceeded,
        ServingConfig,
        ServingPlane,
        ShedLoad,
    )
    from predictionio_tpu.serving.admission import DEADLINE_HEADER
    from predictionio_tpu.telemetry.registry import REGISTRY

    problems = []
    release = threading.Event()
    dispatched = []

    def blocking_dispatch(queries):
        dispatched.append(list(queries))
        release.wait(10)
        return queries

    cfg = ServingConfig(
        admission=AdmissionConfig(max_queue=1, retry_after_s=0.25))
    plane = ServingPlane(blocking_dispatch, config=cfg, name="servinggate")
    try:
        t = threading.Thread(
            target=lambda: plane.handle_query({"probe": 1}), daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while not dispatched and time.monotonic() < deadline:
            time.sleep(0.005)
        if not dispatched:
            problems.append("runtime: occupying request never dispatched")
        try:
            plane.handle_query({"probe": 2})
            problems.append("runtime: saturated plane (max_queue=1) "
                            "admitted a second request instead of shedding")
        except ShedLoad as e:
            if not e.retry_after_s > 0:
                problems.append("runtime: ShedLoad carries no positive "
                                "Retry-After")
        n_before = len(dispatched)
        try:
            plane.handle_query({"probe": 3}, {DEADLINE_HEADER: "0.0001"})
            problems.append("runtime: expired deadline was served instead "
                            "of rejected")
        except (DeadlineExceeded, ShedLoad):
            pass
        if len(dispatched) != n_before:
            problems.append("runtime: expired-deadline request reached the "
                            "dispatch function")
        release.set()
        t.join(timeout=10)
    finally:
        release.set()
        plane.close()
    text = REGISTRY.render()
    for family in ("serving_shed_total", "serving_deadline_misses_total",
                   "serving_admitted_in_flight", "serving_batch_size",
                   "serving_queue_depth", "serving_queue_wait_seconds",
                   "serving_batches_total", "serving_degraded_total"):
        if f"# TYPE {family} " not in text:
            problems.append(f"runtime: /metrics is missing {family}")
    return problems


def run_gate() -> int:
    problems = _static_scan()
    try:
        problems += _runtime_check()
    except Exception as e:  # noqa: BLE001 — a crash IS a gate failure
        problems.append(f"runtime check crashed: {e!r}")
    for p in problems:
        print(p, file=sys.stderr)
    print(f"serving gate: {'FAIL' if problems else 'OK'} "
          f"({len(problems)} problem(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(run_gate())
