"""Bounded in-process flight recorder with Dapper-style tail sampling.

Aggregate histograms say *that* p95 regressed; the flight recorder keeps
the evidence for *why*: completed span timelines (telemetry/spans.py) for
the requests worth a post-mortem. The sampling decision runs at request
*end* — tail sampling — so the outcome can steer it:

  - **pinned** ring: always kept — errors (5xx or handler exception),
    admission sheds (429/503), anything slower than its route's
    threshold, and requests that asked for capture (`X-PIO-Debug: 1`).
  - **sampled** ring: a small random fraction of the healthy rest, so
    there is always a baseline timeline to diff a slow one against.

Both rings are fixed-length deques of plain dicts (timelines are frozen
to JSON-shaped dicts on entry, so a retained record can't keep handler
state alive), giving a hard memory bound: ring slots × MAX_SPANS spans.
Oldest entries fall out first; pinned and sampled evict independently so
a burst of healthy traffic can never push out an error.

Retrieval is over HTTP on every HttpService (wired by the middleware):

    GET /debug/requests.json                 newest-first ring dump
    GET /debug/requests.json?route=/queries.json&kind=pinned&limit=20
    GET /debug/requests/<trace_id>.json      one timeline by trace id

Sizing knobs (environment, read at import):

    PIO_FLIGHT_PINNED    pinned ring slots          (default 256)
    PIO_FLIGHT_SAMPLED   sampled ring slots         (default 256)
    PIO_FLIGHT_SAMPLE    healthy-request sample rate (default 0.01)
    PIO_FLIGHT_SLOW_MS   default slow threshold, ms  (default 250)
"""

from __future__ import annotations

import os
import random
import threading
from collections import deque
from typing import Dict, List, Optional

from predictionio_tpu.telemetry.registry import REGISTRY
from predictionio_tpu.telemetry.spans import Timeline

FLIGHT_RECORDED = REGISTRY.counter(
    "flight_recorded_total", "Timelines kept by the flight recorder",
    labelnames=("kind",))
FLIGHT_DISCARDED = REGISTRY.counter(
    "flight_discarded_total",
    "Healthy timelines that fell outside the random sample")
FLIGHT_EVICTED = REGISTRY.counter(
    "flight_evicted_total", "Timelines evicted to make room",
    labelnames=("kind",))
FLIGHT_BUFFER_SIZE = REGISTRY.gauge(
    "flight_buffer_size", "Timelines currently held",
    labelnames=("kind",))

_SHED_STATUSES = frozenset({429, 503})


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FlightRecorder:
    """Two independent bounded rings plus a trace-id index over both."""

    def __init__(self, pinned_slots: Optional[int] = None,
                 sampled_slots: Optional[int] = None,
                 sample_rate: Optional[float] = None,
                 slow_threshold_s: Optional[float] = None):
        self.pinned_slots = pinned_slots if pinned_slots is not None \
            else _env_int("PIO_FLIGHT_PINNED", 256)
        self.sampled_slots = sampled_slots if sampled_slots is not None \
            else _env_int("PIO_FLIGHT_SAMPLED", 256)
        self.sample_rate = sample_rate if sample_rate is not None \
            else _env_float("PIO_FLIGHT_SAMPLE", 0.01)
        self.slow_threshold_s = slow_threshold_s if slow_threshold_s is not None \
            else _env_float("PIO_FLIGHT_SLOW_MS", 250.0) / 1e3
        # per-route-template overrides of the slow bar; e.g. a checkpoint
        # restore route is legitimately slower than a serving query
        self._slow_by_route: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._pinned: deque = deque()
        self._sampled: deque = deque()
        # trace_id -> frozen timeline dict; kept in lockstep with the rings
        self._index: Dict[str, dict] = {}
        # ids that were held once but fell out of a ring — lets the 404
        # envelope distinguish "evicted" from "never seen". Bounded FIFO.
        self._evicted_ids: Dict[str, bool] = {}
        self._evicted_order: deque = deque()
        self._evicted_slots = 4096
        self._rng = random.Random()
        self._random = self._rng.random
        # cached children: .inc() via the metric re-resolves the child
        # under a lock every call — too hot for the healthy-request path
        self._discarded = FLIGHT_DISCARDED.labels()
        self._size_pinned = FLIGHT_BUFFER_SIZE.labels(kind="pinned")
        self._size_sampled = FLIGHT_BUFFER_SIZE.labels(kind="sampled")
        self._kept_pinned = FLIGHT_RECORDED.labels(kind="pinned")
        self._kept_sampled = FLIGHT_RECORDED.labels(kind="sampled")
        self._evicted_pinned = FLIGHT_EVICTED.labels(kind="pinned")
        self._evicted_sampled = FLIGHT_EVICTED.labels(kind="sampled")

    # -- policy ----------------------------------------------------------

    def set_slow_threshold(self, route: str, threshold_s: float) -> None:
        with self._lock:
            self._slow_by_route[route] = threshold_s

    def _slow_bar(self, route: str) -> float:
        return self._slow_by_route.get(route, self.slow_threshold_s)

    def classify(self, tl: Timeline) -> Optional[str]:
        """Why a timeline deserves pinning, or None if it is healthy."""
        if tl.error or (tl.status is not None and tl.status >= 500
                        and tl.status not in _SHED_STATUSES):
            return "error"
        if tl.status in _SHED_STATUSES:
            return "shed"
        if tl.duration_s >= self._slow_bar(tl.route):
            return "slow"
        if tl.pinned:
            return "debug"
        return None

    # -- ingest ----------------------------------------------------------

    def offer(self, tl: Timeline) -> Optional[str]:
        """Called once per finished request; returns the retention kind
        ("pinned"/"sampled") or None when the timeline was let go."""
        # inlined healthy fast path (≡ classify(tl) is None): nearly every
        # request exits here, inside the ≤5% per-request overhead budget
        status = tl.status
        if (not tl.error and not tl.pinned
                and (status is None or (status < 500 and status != 429))
                and tl.duration_s < self._slow_by_route.get(
                    tl.route, self.slow_threshold_s)):
            if self._random() >= self.sample_rate:
                self._discarded.inc()
                return None
            reason = None
        else:
            reason = self.classify(tl)
        entry = tl.to_dict()
        if reason is not None:
            entry["kept"] = reason
        with self._lock:
            if reason is not None:
                self._push(self._pinned, self.pinned_slots, entry,
                           self._evicted_pinned)
                self._size_pinned.set(len(self._pinned))
                kept, counter = "pinned", self._kept_pinned
            else:
                entry["kept"] = "sampled"
                self._push(self._sampled, self.sampled_slots, entry,
                           self._evicted_sampled)
                self._size_sampled.set(len(self._sampled))
                kept, counter = "sampled", self._kept_sampled
            self._index[entry["trace_id"]] = entry
        counter.inc()
        return kept

    def _push(self, ring: deque, slots: int, entry: dict,
              evicted_counter) -> None:
        while len(ring) >= slots:
            old = ring.popleft()
            # a retried trace id may have overwritten the index slot; only
            # drop the index entry if it still points at the evictee
            if self._index.get(old["trace_id"]) is old:
                del self._index[old["trace_id"]]
                self._remember_evicted(old["trace_id"])
            evicted_counter.inc()
        ring.append(entry)

    def _remember_evicted(self, trace_id: str) -> None:
        if trace_id not in self._evicted_ids:
            self._evicted_ids[trace_id] = True
            self._evicted_order.append(trace_id)
            while len(self._evicted_order) > self._evicted_slots:
                del self._evicted_ids[self._evicted_order.popleft()]

    # -- retrieval -------------------------------------------------------

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            return self._index.get(trace_id)

    def was_evicted(self, trace_id: str) -> bool:
        """Held once, since pushed out — not the same 404 as never-seen."""
        with self._lock:
            return trace_id in self._evicted_ids

    def snapshot(self, limit: int = 50, route: Optional[str] = None,
                 kind: Optional[str] = None) -> List[dict]:
        """Newest-first merged view of both rings (filtered, bounded)."""
        with self._lock:
            entries = []
            if kind in (None, "pinned"):
                entries.extend(self._pinned)
            if kind in (None, "sampled"):
                entries.extend(self._sampled)
        entries.sort(key=lambda e: e["start_time"], reverse=True)
        if route is not None:
            entries = [e for e in entries if e["route"] == route]
        return entries[:max(0, limit)]

    def sizes(self) -> Dict[str, int]:
        with self._lock:
            return {"pinned": len(self._pinned),
                    "sampled": len(self._sampled),
                    "index": len(self._index)}

    def clear(self) -> None:
        with self._lock:
            self._pinned.clear()
            self._sampled.clear()
            self._index.clear()
            self._evicted_ids.clear()
            self._evicted_order.clear()
            self._size_pinned.set(0)
            self._size_sampled.set(0)


# Process-wide recorder, mirroring telemetry.registry.REGISTRY: every
# HttpService in the process feeds and serves the same rings.
RECORDER = FlightRecorder()
