"""Event-loop HTTP transport (utils/httploop.py) — protocol conformance.

The selector loop replaced thread-per-connection serving for the hot
routes; these tests pin the HTTP/1.1 semantics that keep-alive parking
makes easy to get wrong: pipelining order, malformed-request containment
(one bad client must not kill the shared loop), slowloris timeouts, and
the pause/resume lifecycle the supervisor's rolling deploys drive.
"""

import http.client
import json
import socket
import time

import pytest

from predictionio_tpu.utils.http import HttpService
from predictionio_tpu.utils.routing import Request, Response, Router


def _router():
    r = Router()
    r.get("/", lambda req: Response.json(200, {"ok": True}))
    r.post("/echo", lambda req: Response.json(
        200, {"n": len(req.body or b""), "q": req.params.get("q", "")}))

    def _slow(req):
        time.sleep(0.05)
        return Response.json(200, {"slow": True})

    r.post("/slow", _slow, blocking=True)
    return r


@pytest.fixture
def svc():
    service = HttpService("127.0.0.1", 0, router=_router(),
                          server_name="looptest")
    service.start()
    yield service
    service.shutdown()


def _recv_responses(sock, n, timeout=10.0):
    """Read exactly n HTTP responses (Content-Length framed) off a raw
    socket; returns a list of (status, body_bytes)."""
    sock.settimeout(timeout)
    buf = b""
    out = []
    while len(out) < n:
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise AssertionError(
                    f"connection closed after {len(out)}/{n} responses; "
                    f"buffer {buf[:200]!r}")
            buf += chunk
        head, _, rest = buf.partition(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        length = 0
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            if k.lower() == b"content-length":
                length = int(v)
        while len(rest) < length:
            chunk = sock.recv(65536)
            if not chunk:
                raise AssertionError("closed mid-body")
            rest += chunk
        out.append((status, rest[:length]))
        buf = rest[length:]
    return out, buf


def test_keep_alive_reuses_one_connection(svc):
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    for i in range(5):
        conn.request("POST", f"/echo?q=v{i}", b"x" * i,
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        body = json.loads(r.read())
        assert r.status == 200 and body == {"n": i, "q": f"v{i}"}
    conn.close()


def test_pipelined_requests_answered_in_order(svc):
    """Two requests in ONE tcp segment → two responses, request order."""
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"POST /echo?q=a HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 2\r\n\r\nAA"
              b"POST /echo?q=b HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 3\r\n\r\nBBB")
    (r1, r2), _ = _recv_responses(s, 2)
    assert r1[0] == 200 and json.loads(r1[1]) == {"n": 2, "q": "a"}
    assert r2[0] == 200 and json.loads(r2[1]) == {"n": 3, "q": "b"}
    s.close()


def test_pipelined_blocking_routes_stay_ordered(svc):
    """Pipelining across worker-pool routes must still answer in request
    order (strict per-connection FIFO), even when the first is slower."""
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"POST /slow HTTP/1.1\r\nHost: x\r\nContent-Length: 0\r\n\r\n"
              b"POST /echo?q=after HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 0\r\n\r\n")
    responses, _ = _recv_responses(s, 2)
    assert json.loads(responses[0][1]) == {"slow": True}
    assert json.loads(responses[1][1])["q"] == "after"
    s.close()


def test_malformed_request_line_400_loop_survives(svc):
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"this is not http\r\n\r\n")
    responses, _ = _recv_responses(s, 1)
    assert responses[0][0] == 400
    s.close()
    # the shared loop still serves other clients
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    conn.request("GET", "/")
    assert conn.getresponse().status == 200
    conn.close()


def test_unknown_verb_501(svc):
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"BREW /coffee HTTP/1.1\r\nHost: x\r\n\r\n")
    responses, _ = _recv_responses(s, 1)
    assert responses[0][0] == 501
    s.close()


def test_slowloris_partial_header_times_out(monkeypatch):
    monkeypatch.setenv("PIO_HTTP_READ_TIMEOUT_S", "0.4")
    service = HttpService("127.0.0.1", 0, router=_router(),
                          server_name="slowloris")
    service.start()
    try:
        s = socket.create_connection(("127.0.0.1", service.port), timeout=10)
        t0 = time.monotonic()
        s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\nX-Drip")  # never finishes
        responses, _ = _recv_responses(s, 1)
        elapsed = time.monotonic() - t0
        assert responses[0][0] == 408
        assert 0.2 <= elapsed < 5.0, elapsed
        s.settimeout(5)
        assert s.recv(1024) == b""  # server closed the unframeable conn
        s.close()
        # idle PARKED connections are not subject to the read timeout:
        # a keep-alive client that simply goes quiet between requests
        # stays parked
        conn = http.client.HTTPConnection("127.0.0.1", service.port,
                                          timeout=5)
        conn.request("GET", "/")
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        time.sleep(0.8)  # > read timeout, parked the whole time
        conn.request("GET", "/")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        service.shutdown()


def test_connection_close_honored(svc):
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
    responses, _ = _recv_responses(s, 1)
    assert responses[0][0] == 200
    s.settimeout(5)
    assert s.recv(1024) == b""
    s.close()


def test_http10_defaults_to_close(svc):
    s = socket.create_connection(("127.0.0.1", svc.port), timeout=5)
    s.sendall(b"GET / HTTP/1.0\r\nHost: x\r\n\r\n")
    responses, _ = _recv_responses(s, 1)
    assert responses[0][0] == 200
    s.settimeout(5)
    assert s.recv(1024) == b""
    s.close()


def test_pause_resume_accept_cycle(svc):
    assert svc.accepting
    svc.pause_accept()
    assert not svc.accepting
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", svc.port), timeout=0.5)
    svc.resume_accept()
    assert svc.accepting
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    conn.request("GET", "/")
    assert conn.getresponse().status == 200
    conn.close()


def test_parked_connection_served_across_pause(svc):
    """pause_accept only closes the LISTENER: already-parked keep-alive
    clients keep being served through the drain (zero-drop reload)."""
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    conn.request("GET", "/")
    r = conn.getresponse()
    r.read()
    assert r.status == 200
    svc.pause_accept()
    try:
        conn.request("GET", "/")
        assert conn.getresponse().status == 200
    finally:
        svc.resume_accept()
        conn.close()


def test_busy_requests_counts_pipelined_backlog(svc):
    assert svc.busy_requests() == 0
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    conn.request("GET", "/")
    conn.getresponse().read()
    assert svc.busy_requests() == 0  # parked between requests ≠ busy
    conn.close()


def test_threaded_fallback_env(monkeypatch):
    """PIO_HTTP_LOOP=0 routes the same Router through the threaded
    adapter — the escape hatch must serve identically."""
    monkeypatch.setenv("PIO_HTTP_LOOP", "0")
    service = HttpService("127.0.0.1", 0, router=_router(),
                          server_name="fallback")
    assert service.httpd is not None  # threaded transport engaged
    service.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", service.port,
                                          timeout=5)
        conn.request("POST", "/echo?q=z", b"abc",
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read()) == {"n": 3, "q": "z"}
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()
    finally:
        service.shutdown()


def test_metrics_and_trace_header(svc):
    conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
    conn.request("GET", "/")
    r = conn.getresponse()
    r.read()
    assert r.getheader("X-PIO-Trace-Id")
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    for family in ("http_requests_total", "http_parked_connections",
                   "http_requests_per_connection"):
        assert f"# TYPE {family} " in text, family
    assert 'server="looptest"' in text


def test_parked_gauge_never_underflows(svc):
    """Regression: conns were born in _PARKED, so accept's park was a
    no-op while the first unpark still decremented — the gauge went
    negative one per served-then-closed connection."""
    for _ in range(4):
        conn = http.client.HTTPConnection("127.0.0.1", svc.port, timeout=5)
        conn.request("GET", "/")
        conn.getresponse().read()
        conn.close()
    deadline = time.monotonic() + 2
    while svc._loop.parked_connections != 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert svc._loop.parked_connections == 0
