"""Native C++ data loader vs the numpy reference implementation —
bit-identical bucketization (SURVEY.md §2.5: native host-side loader as
the rebuild's runtime-native component)."""

import numpy as np
import pytest

from predictionio_tpu import native
from predictionio_tpu.ops import als


def _python_buckets(rows, cols, vals, n_rows, row_multiple=8, max_cap=None,
                    cap_growth=1.5):
    """Force the numpy path regardless of native availability."""
    import unittest.mock as mock

    with mock.patch.object(native, "bucket_ragged_native",
                           return_value=None):
        return als.bucket_ragged(rows, cols, vals, n_rows,
                                 row_multiple, max_cap,
                                 cap_growth=cap_growth)


needs_native = pytest.mark.skipif(not native.native_available(),
                                  reason="no C++ toolchain")


def synth(n, n_rows, n_cols, seed, zipf=False):
    rng = np.random.default_rng(seed)
    if zipf:
        raw = rng.zipf(1.5, n).astype(np.int64)
        rows = (raw % n_rows).astype(np.int32)
    else:
        rows = rng.integers(0, n_rows, n).astype(np.int32)
    cols = rng.integers(0, n_cols, n).astype(np.int32)
    vals = rng.uniform(1, 5, n).astype(np.float32)
    return rows, cols, vals


@needs_native
class TestNativeBucketize:
    @pytest.mark.parametrize("seed,zipf", [(0, False), (1, True), (2, True)])
    @pytest.mark.parametrize("row_multiple", [8, 16])
    def test_bit_identical_to_python(self, seed, zipf, row_multiple):
        rows, cols, vals = synth(5000, 300, 200, seed, zipf)
        py = _python_buckets(rows, cols, vals, 300, row_multiple)
        nat = native.bucket_ragged_native(rows, cols, vals, 300, row_multiple)
        assert nat is not None
        assert len(py) == len(nat)
        for pb, nb in zip(py, nat):
            np.testing.assert_array_equal(pb.rows, nb.rows)
            np.testing.assert_array_equal(pb.cols, nb.cols)
            np.testing.assert_array_equal(pb.vals, nb.vals)
            np.testing.assert_array_equal(pb.mask, nb.mask)

    def test_max_cap_truncation_matches(self):
        rows, cols, vals = synth(4000, 50, 100, 3, zipf=True)
        py = _python_buckets(rows, cols, vals, 50, max_cap=16)
        nat = native.bucket_ragged_native(rows, cols, vals, 50, 8, 16)
        assert len(py) == len(nat)
        for pb, nb in zip(py, nat):
            np.testing.assert_array_equal(pb.cols, nb.cols)
            np.testing.assert_array_equal(pb.vals, nb.vals)

    def test_non_pow2_max_cap(self):
        rows, cols, vals = synth(3000, 40, 60, 4, zipf=True)
        py = _python_buckets(rows, cols, vals, 40, max_cap=100)
        nat = native.bucket_ragged_native(rows, cols, vals, 40, 8, 100)
        assert len(py) == len(nat)
        assert [b.cap for b in py] == [b.cap for b in nat]
        for pb, nb in zip(py, nat):
            np.testing.assert_array_equal(pb.mask, nb.mask)

    def test_out_of_range_rows_fall_back(self):
        # row id >= n_rows: native defers to numpy so behavior is the
        # same with and without a toolchain
        rows = np.array([0, 5], dtype=np.int32)  # 5 >= n_rows=3
        cols = np.zeros(2, np.int32)
        vals = np.ones(2, np.float32)
        assert native.bucket_ragged_native(rows, cols, vals, 3) is None

    def test_empty_input(self):
        nat = native.bucket_ragged_native(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), 10)
        assert nat == []

    def test_single_row_all_entries(self):
        rows = np.zeros(37, np.int32)
        cols = np.arange(37, dtype=np.int32)
        vals = np.ones(37, np.float32)
        py = _python_buckets(rows, cols, vals, 1)
        nat = native.bucket_ragged_native(rows, cols, vals, 1)
        assert len(nat) == 1 and nat[0].cap == 40  # 1.5 ladder: 8,16,24,40
        np.testing.assert_array_equal(py[0].cols, nat[0].cols)
        nat2 = native.bucket_ragged_native(rows, cols, vals, 1,
                                           cap_growth=2.0)
        assert nat2[0].cap == 64  # pow2 ladder

    def test_als_train_uses_native_and_converges(self):
        # end-to-end: als_train with the native loader reaches the same
        # factors as with the numpy loader
        from tests.test_als import synth_ratings

        ui, ii, r, _ = synth_ratings(n_users=40, n_items=30, seed=5)
        cfg = als.ALSConfig(rank=4, iterations=3, reg=0.05, seed=1)
        out_native = als.als_train(ui, ii, r, 40, 30, cfg)
        import unittest.mock as mock

        with mock.patch.object(native, "bucket_ragged_native",
                               return_value=None):
            out_py = als.als_train(ui, ii, r, 40, 30, cfg)
        np.testing.assert_allclose(out_native.user_factors,
                                   out_py.user_factors, rtol=1e-5, atol=1e-6)


class TestFallback:
    def test_env_disable(self, monkeypatch):
        monkeypatch.setenv("PIO_NATIVE", "0")
        assert native.get_lib() is None
        assert native.bucket_ragged_native(
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.ones(1, np.float32), 1) is None


@needs_native
class TestCapGrowthParity:
    """The C++ ladder must match numpy bit-for-bit at every growth."""

    @pytest.mark.parametrize("growth", [2.0, 1.5, 1.25])
    def test_ladder_parity(self, growth):
        rows, cols, vals = synth(5000, 300, 200, seed=11, zipf=True)
        py = _python_buckets(rows, cols, vals, 300, cap_growth=growth)
        nat = native.bucket_ragged_native(rows, cols, vals, 300,
                                          cap_growth=growth)
        assert nat is not None
        assert len(py) == len(nat)
        for pb, nb in zip(py, nat):
            np.testing.assert_array_equal(pb.rows, nb.rows)
            np.testing.assert_array_equal(pb.cols, nb.cols)
            np.testing.assert_array_equal(pb.vals, nb.vals)
            np.testing.assert_array_equal(pb.mask, nb.mask)
