"""Fixture: two same-named nested loop drivers. Their findings must
carry distinct qualname-anchored keys — with name-anchored symbols the
keys collided, so one baseline entry silently covered both."""

import time


def spawn_fast(selector):
    def run():
        while True:
            selector.select(0.01)
            time.sleep(0.001)
    return run


def spawn_slow(selector):
    def run():
        while True:
            selector.select(0.5)
            time.sleep(0.1)
    return run
