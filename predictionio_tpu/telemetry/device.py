"""Device-plane observability: per-dispatch device time, the jit-cache
inventory with retrace blame, and device-memory history.

Everything host-side is already deep (19 Hz profiler, lineage, fleet
metrics) but the accelerator was dark: `metered_jit` only counted
compiles, `/debug/profile/device.json` was a point-in-time buffer dump,
and nothing said how many device-seconds a route or bucket tier
consumed. Three instruments fix that, all fed by the single
`record_dispatch()` hook that `utils/profiling.metered_jit` calls on
every dispatch:

- `DeviceClock`: per-dispatch device time via a block-until-ready delta
  measured on a drain thread — the caller never sync-stalls; jax-less or
  CPU-backend processes fall back to dispatch wall time labelled
  ``device="cpu"``. Lands in `device_seconds_total{route,fn,tier,device}`
  (routes/tiers come from the `attribution()` context the dispatch sites
  open) plus a rolling 60 s `device_utilization_ratio` gauge. Internally
  time is integer microseconds so the supervisor's fleet merge is
  sum-exact (`total_us == sum(workers.values())`, no float drift).
- The jit-cache inventory (`GET /debug/jit.json`): per-fn compiled
  signatures (abstract shapes/dtypes, compile seconds, dispatch counts,
  last-used) with **retrace blame** — on recompile the new signature is
  diffed against the nearest cached one and the changed argument /
  dimension is named. The runtime twin of pio-lint's static
  `jit-shape-discipline` rule.
- A device-memory sampler feeding `telemetry/history.py` with
  `device_mem_*` high-water gauges plus the headroom burn-rate alert in
  `telemetry/alerts.py`.

Lazy-import discipline: this module never imports jax at module level
and only touches it when ``"jax" in sys.modules`` — event servers and
gate drills stay jax-free.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from predictionio_tpu.telemetry import tenant
from predictionio_tpu.telemetry.registry import REGISTRY, capped_label

log = logging.getLogger(__name__)

UNTRACKED_ROUTE = "(untracked)"

# Inventory bounds: per-fn signature map is LRU-capped so a shape-unstable
# function cannot grow the payload forever (the eviction count is itself
# a retrace-storm signal); fn labels are capped upstream by capped_label.
MAX_SIGNATURES_PER_FN = 64
MAX_RETRACE_RECORDS = 16
UTILIZATION_WINDOW_S = 60.0

DEVICE_SECONDS = REGISTRY.counter(
    "device_seconds_total",
    "Device-execution seconds per route/fn/bucket-tier, measured as the "
    "block-until-ready delta on the device clock's drain thread "
    "(device=\"cpu\" marks the dispatch-wall-time fallback)",
    labelnames=("route", "fn", "tier", "device"))
DEVICE_DISPATCHES = REGISTRY.counter(
    "device_dispatches_total",
    "Jitted dispatches observed by the device clock, same labels as "
    "device_seconds_total",
    labelnames=("route", "fn", "tier", "device"))
DEVICE_UTILIZATION = REGISTRY.gauge(
    "device_utilization_ratio",
    "Fraction of the last 60 s wall window the device spent executing "
    "dispatched programs (from the device clock)",
    labelnames=("device",))
DEVICE_CLOCK_DROPPED = REGISTRY.counter(
    "device_clock_dropped_total",
    "Dispatches whose ready-delta measurement was skipped because the "
    "device clock's drain queue was full (their wall time was recorded "
    "on the device=\"cpu\" fallback instead)")
DEVICE_CLOCK_QUEUE = REGISTRY.gauge(
    "device_clock_queue_depth",
    "Dispatches currently waiting on the device clock's drain thread")
JIT_RETRACES = REGISTRY.counter(
    "jit_retraces_total",
    "Recompiles of an already-warm jitted function (compile count beyond "
    "its first signature) — each one carries retrace blame in "
    "/debug/jit.json naming the argument/dimension that changed",
    labelnames=("fn",))

DEVICE_MEM_LIVE = REGISTRY.gauge(
    "device_mem_live_bytes",
    "Live jax buffer bytes per device (device-memory sampler)",
    labelnames=("device",))
DEVICE_MEM_HIGH_WATER = REGISTRY.gauge(
    "device_mem_high_water_bytes",
    "High-water mark of live jax buffer bytes per device since process "
    "start (device-memory sampler)",
    labelnames=("device",))
DEVICE_MEM_LIMIT = REGISTRY.gauge(
    "device_mem_limit_bytes",
    "Device memory capacity as reported by memory_stats (absent on "
    "backends that do not report a limit)",
    labelnames=("device",))
DEVICE_MEM_HEADROOM = REGISTRY.gauge(
    "device_mem_headroom_ratio",
    "(limit - live) / limit per device — 0 means HBM exhausted; the "
    "device-mem-headroom-burn alert fires on a fast-shrinking ratio",
    labelnames=("device",))


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) not in ("0", "false", "off", "no")


# -- dispatch-site attribution -------------------------------------------------

_TLS = threading.local()


class Attribution:
    """Open at a dispatch site; every metered_jit dispatch inside the
    block inherits the route/tier labels, and the site can read back the
    host-vs-device split (`t_first_dispatch`, `jit_wall_s`) to record it
    as nested spans. A plain __enter__/__exit__ class, not a generator
    contextmanager: this sits on the batch-of-1 serving hot path, where
    the generator machinery alone is a measurable share of the ≤5%
    per-query overhead bar."""

    __slots__ = ("route", "tier", "t_enter", "t_first_dispatch",
                 "jit_wall_s", "dispatches", "_prev")

    def __init__(self, route: str, tier: str = ""):
        self.route = route
        self.tier = tier
        self.t_enter = time.perf_counter()
        self.t_first_dispatch: Optional[float] = None
        self.jit_wall_s = 0.0
        self.dispatches = 0

    def __enter__(self) -> "Attribution":
        self._prev = getattr(_TLS, "att", None)
        _TLS.att = self
        return self

    def __exit__(self, *exc) -> None:
        _TLS.att = self._prev


def attribution(route: str, tier: str = "") -> Attribution:
    return Attribution(route, tier=str(tier))


def current_attribution() -> Optional[Attribution]:
    return getattr(_TLS, "att", None)


# -- abstract signatures -------------------------------------------------------


def _spec(x: Any) -> str:
    """Abstract spec of one dispatch argument. Arrays abstract to
    dtype[shape] (value-independent, like a jit trace); Python scalars
    keep their value — they are usually static args, where the value IS
    the retrace trigger worth naming."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            dims = ",".join(str(int(d)) for d in shape)
        except (TypeError, ValueError):
            dims = ",".join(str(d) for d in shape)
        return f"{dtype}[{dims}]"
    if isinstance(x, bool):
        return f"bool({x})"
    if isinstance(x, int):
        return f"int({x})"
    if isinstance(x, float):
        return f"float({x:g})"
    if isinstance(x, str):
        return f"str({x[:32]})"
    if x is None:
        return "None"
    if isinstance(x, (list, tuple)):
        return f"{type(x).__name__}(n={len(x)})"
    return type(x).__name__


def signature_of(args: Sequence[Any],
                 kwargs: Optional[Dict[str, Any]]) -> Tuple[str, ...]:
    parts = [f"arg{i}:{_spec(a)}" for i, a in enumerate(args)]
    if kwargs:
        parts.extend(f"{k}={_spec(kwargs[k])}" for k in sorted(kwargs))
    return tuple(parts)


def _split_spec(part: str) -> Tuple[str, str]:
    """'arg0:f32[8]' / 'out_rows=int(32)' → (name, spec)."""
    colon, eq = part.find(":"), part.find("=")
    if colon != -1 and (eq == -1 or colon < eq):
        return part[:colon], part[colon + 1:]
    if eq != -1:
        return part[:eq], part[eq + 1:]
    return part, part


def _dims_of(spec: str) -> Optional[Tuple[str, List[str]]]:
    """dtype[d0,d1] → (dtype, [d0, d1]); None for non-array specs."""
    if not spec.endswith("]") or "[" not in spec:
        return None
    dtype, _, dims = spec[:-1].partition("[")
    return dtype, dims.split(",") if dims else []

def diff_signatures(old: Tuple[str, ...],
                    new: Tuple[str, ...]) -> List[str]:
    """Human-readable per-argument differences; dimension-level when both
    sides are arrays of the same dtype/rank ('arg0 dim0: 8→32')."""
    changed: List[str] = []
    for i in range(max(len(old), len(new))):
        o = old[i] if i < len(old) else None
        n = new[i] if i < len(new) else None
        if o == n:
            continue
        if o is None or n is None:
            changed.append(f"{(n or o)} {'added' if o is None else 'removed'}")
            continue
        oname, ospec = _split_spec(o)
        nname, nspec = _split_spec(n)
        label = nname if oname == nname else f"{oname}/{nname}"
        od, nd = _dims_of(ospec), _dims_of(nspec)
        if (od and nd and od[0] == nd[0] and oname == nname
                and len(od[1]) == len(nd[1])):
            for k, (a, b) in enumerate(zip(od[1], nd[1])):
                if a != b:
                    changed.append(f"{label} dim{k}: {a}→{b}")
            continue
        changed.append(f"{label}: {ospec}→{nspec}")
    return changed


# -- jit-cache inventory -------------------------------------------------------


class _FnInventory:
    __slots__ = ("compiles", "dispatches", "compile_seconds", "retraces",
                 "evicted", "signatures", "blames")

    def __init__(self):
        self.compiles = 0
        self.dispatches = 0
        self.compile_seconds = 0.0
        self.retraces = 0
        self.evicted = 0
        # sig tuple -> {"compiles","dispatches","compile_seconds",
        #               "first_seen","last_used"}; insertion order is the
        # LRU order (entries are re-inserted on use).
        self.signatures: "collections.OrderedDict[Tuple[str, ...], Dict]" = \
            collections.OrderedDict()
        self.blames: "collections.deque[Dict]" = collections.deque(
            maxlen=MAX_RETRACE_RECORDS)


_inventory_lock = threading.Lock()
_INVENTORY: Dict[str, _FnInventory] = {}

# (route, fn, tier, device) -> [microseconds, dispatches]; integer so the
# fleet merge sums exactly.
_attr_lock = threading.Lock()
_ATTR_TOTALS: Dict[Tuple[str, str, str, str], List[int]] = {}


def _nearest_signature(entry: _FnInventory,
                       sig: Tuple[str, ...]) -> Optional[Tuple[str, ...]]:
    best, best_n = None, None
    for cached in entry.signatures:
        n = len(diff_signatures(cached, sig))
        if best_n is None or n < best_n:
            best, best_n = cached, n
    return best


def _record_inventory(fn: str, sig: Tuple[str, ...], compiled: bool,
                      compile_s: float, now: float) -> None:
    with _inventory_lock:
        entry = _INVENTORY.get(fn)
        if entry is None:
            entry = _INVENTORY[fn] = _FnInventory()
        entry.dispatches += 1
        blame = None
        if compiled:
            entry.compiles += 1
            entry.compile_seconds += compile_s
            if sig not in entry.signatures and entry.signatures:
                # Warm function recompiled: a retrace. Name the culprit.
                entry.retraces += 1
                nearest = _nearest_signature(entry, sig)
                blame = {
                    "ts": time.time(),
                    "signature": list(sig),
                    "against": list(nearest) if nearest else None,
                    "changed": (diff_signatures(nearest, sig)
                                if nearest else []),
                    "compile_seconds": round(compile_s, 6),
                }
                entry.blames.append(blame)
        rec = entry.signatures.pop(sig, None)
        if rec is None:
            rec = {"compiles": 0, "dispatches": 0, "compile_seconds": 0.0,
                   "first_seen": now, "last_used": now}
            while len(entry.signatures) >= MAX_SIGNATURES_PER_FN:
                entry.signatures.popitem(last=False)
                entry.evicted += 1
        rec["dispatches"] += 1
        rec["last_used"] = now
        if compiled:
            rec["compiles"] += 1
            rec["compile_seconds"] += compile_s
        entry.signatures[sig] = rec    # (re-)insert at MRU end
    if blame is not None:
        JIT_RETRACES.labels(fn=fn).inc()
        log.info("device: %s retraced (%s)", fn,
                 "; ".join(blame["changed"]) or "no cached signature diff")


def _account(route: str, fn: str, tier: str, device: str, us: int,
             app: Optional[str] = None) -> None:
    us = max(0, int(us))
    key = (route, fn, tier, device)
    with _attr_lock:
        slot = _ATTR_TOTALS.get(key)
        if slot is None:
            slot = _ATTR_TOTALS[key] = [0, 0]
        slot[0] += us
        slot[1] += 1
    labels = dict(route=route, fn=fn, tier=tier, device=device)
    DEVICE_SECONDS.labels(**labels).inc(us / 1e6)
    DEVICE_DISPATCHES.labels(**labels).inc()
    # tenant dimension: the same integer microseconds land in the tenant
    # meter, so sum over tenant labels (incl. "-") == device total exactly
    tenant.record_device_us(us, app=app)


# -- the device clock ----------------------------------------------------------


_backend_name: Optional[str] = None


def _backend() -> str:
    """Cached jax backend name; "cpu" when jax is absent (the wall-time
    fallback label)."""
    global _backend_name
    if _backend_name is None:
        if "jax" not in sys.modules:
            return "cpu"    # not cached: jax may load later
        try:
            import jax
            _backend_name = str(jax.default_backend())
        except Exception:  # noqa: BLE001
            _backend_name = "cpu"
    return _backend_name


class DeviceClock:
    """Measures per-dispatch device time without stalling the caller:
    dispatch sites enqueue (out, t0, labels); the drain thread blocks
    until the output buffers are ready and books the delta."""

    def __init__(self, maxsize: int = 2048):
        self._queue: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._running = False
        # (t_ready_monotonic, us) per device for the utilization window
        self._window: Dict[str, "collections.deque"] = {}

    def is_running(self) -> bool:
        return self._running

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._drain, name="pio-device-clock", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if not self._running:
                return
            self._running = False
            thread = self._thread
            self._thread = None
        self._queue.put(None)
        if thread is not None:
            thread.join(timeout=2.0)
        DEVICE_CLOCK_QUEUE.set(0)

    def submit(self, out: Any, t0: float, t1: float, fn: str, route: str,
               tier: str, compiled: bool,
               app: Optional[str] = None) -> bool:
        """Enqueue a dispatch for ready-delta measurement; False when the
        queue is full (caller falls back to wall time).

        `app` is the tenant captured on the DISPATCH thread — the drain
        thread has no contextvar binding, so it must travel in the item."""
        if not self._running:
            self.start()
        try:
            self._queue.put_nowait(
                (out, t0, t1, fn, route, tier, compiled, app))
        except queue.Full:
            DEVICE_CLOCK_DROPPED.inc()
            return False
        DEVICE_CLOCK_QUEUE.set(self._queue.qsize())
        return True

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait (bounded) until every submitted dispatch is measured —
        gate drills and tests; serving never calls this."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            time.sleep(0.005)
        return self._queue.empty()

    def _drain(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                if not self._running:
                    return
                continue
            if item is None:
                return
            try:
                self._measure(*item)
            except Exception:  # noqa: BLE001 — the clock must never die
                log.debug("device: drain measurement failed", exc_info=True)
            finally:
                DEVICE_CLOCK_QUEUE.set(self._queue.qsize())

    def _measure(self, out: Any, t0: float, t1: float, fn: str, route: str,
                 tier: str, compiled: bool,
                 app: Optional[str] = None) -> None:
        device = _backend()
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001
            device = "cpu"
        t_ready = time.perf_counter()
        # A compiled dispatch spent (t1 - t0) mostly tracing+compiling on
        # the host; its device execution is the tail after the call
        # returned. A warm dispatch returns as soon as the work is
        # enqueued, so the whole t0→ready delta is device time.
        start = t1 if compiled else t0
        us = int(max(0.0, t_ready - start) * 1e6)
        _account(route, fn, tier, device, us, app=app)
        self._tick_utilization(device, t_ready, us)

    def _tick_utilization(self, device: str, now: float, us: int) -> None:
        win = self._window.get(device)
        if win is None:
            win = self._window[device] = collections.deque()
        win.append((now, us))
        horizon = now - UTILIZATION_WINDOW_S
        while win and win[0][0] < horizon:
            win.popleft()
        busy_us = sum(u for _, u in win)
        DEVICE_UTILIZATION.labels(device=device).set(
            round(busy_us / (UTILIZATION_WINDOW_S * 1e6), 6))


CLOCK = DeviceClock(
    maxsize=int(os.environ.get("PIO_DEVICE_CLOCK_QUEUE") or 2048))

_clock_enabled = _env_flag("PIO_DEVICE_CLOCK")


def clock_enabled() -> bool:
    return _clock_enabled


def set_clock_enabled(on: bool) -> None:
    """Runtime toggle for the overhead A/B drill (mirrors profiler.stop)."""
    global _clock_enabled
    _clock_enabled = bool(on)
    if not on:
        CLOCK.stop()


# -- the metered_jit hook ------------------------------------------------------


def record_dispatch(fn: str, args: Sequence[Any] = (),
                    kwargs: Optional[Dict[str, Any]] = None,
                    out: Any = None, t0: float = 0.0,
                    t1: Optional[float] = None, compiled: bool = False,
                    compile_s: float = 0.0) -> None:
    """The single entry point `utils/profiling.metered_jit` calls per
    dispatch: updates the jit-cache inventory, books route/tier
    attribution, and hands the output to the device clock."""
    t1 = time.perf_counter() if t1 is None else t1
    now = time.time()
    _record_inventory(fn, signature_of(args, kwargs), compiled, compile_s,
                      now)
    att = current_attribution()
    if att is not None:
        route, tier = att.route, att.tier
        if att.t_first_dispatch is None:
            att.t_first_dispatch = t0
        att.jit_wall_s += max(0.0, t1 - t0)
        att.dispatches += 1
    else:
        route, tier = UNTRACKED_ROUTE, ""
    if not _clock_enabled:
        return
    # capture the tenant HERE, on the dispatch thread, where the serving
    # plane's contextvar binding is live; the clock's drain thread isn't
    app = tenant.current_app()
    if out is not None and "jax" in sys.modules and _backend() != "cpu":
        if CLOCK.submit(out, t0, t1, fn, route, tier, compiled, app=app):
            return
    # Wall-time fallback: jax-less processes, the CPU backend (execution
    # completes inside the call), or a saturated drain queue.
    _account(route, fn, tier, "cpu", int(max(0.0, t1 - t0) * 1e6), app=app)


# -- /debug/jit.json -----------------------------------------------------------


def jit_payload() -> Tuple[int, Dict]:
    """GET /debug/jit.json — the process-local jit-cache inventory."""
    fns: Dict[str, Dict] = {}
    totals = {"compiles": 0, "dispatches": 0, "retraces": 0, "evicted": 0}
    with _inventory_lock:
        for name, entry in _INVENTORY.items():
            sigs = [
                {"signature": list(sig),
                 "compiles": rec["compiles"],
                 "dispatches": rec["dispatches"],
                 "compile_seconds": round(rec["compile_seconds"], 6),
                 "first_seen": rec["first_seen"],
                 "last_used": rec["last_used"]}
                for sig, rec in entry.signatures.items()]
            sigs.sort(key=lambda s: -s["dispatches"])
            fns[name] = {
                "compiles_total": entry.compiles,
                "dispatches_total": entry.dispatches,
                "compile_seconds_total": round(entry.compile_seconds, 6),
                "retraces_total": entry.retraces,
                "evicted_signatures": entry.evicted,
                "signatures": sigs,
                "retrace_blame": list(entry.blames),
            }
            totals["compiles"] += entry.compiles
            totals["dispatches"] += entry.dispatches
            totals["retraces"] += entry.retraces
            totals["evicted"] += entry.evicted
    with _attr_lock:
        attribution_rows = [
            {"route": k[0], "fn": k[1], "tier": k[2], "device": k[3],
             "us": v[0], "dispatches": v[1]}
            for k, v in sorted(_ATTR_TOTALS.items(),
                               key=lambda kv: -kv[1][0])]
    return 200, {
        "fns": fns,
        "totals": totals,
        "device_attribution": attribution_rows,
        "clock": {"enabled": _clock_enabled,
                  "running": CLOCK.is_running(),
                  "queue_depth": CLOCK._queue.qsize(),
                  "backend": _backend()},
    }


# -- /debug/profile/device.json (moved from profiler.py, envelope kept) --------


def memory_payload() -> Tuple[int, Dict]:
    """GET /debug/profile/device.json — jax live-buffer and device-memory
    view. Lazy-import discipline: processes that never loaded jax (event
    server, tests) answer a 503 envelope instead of paying the import."""
    if "jax" not in sys.modules:
        return 503, {"status": 503,
                     "error": "jax not loaded in this process"}
    import jax

    out: Dict = {"backend": None, "devices": [], "live_buffers": {},
                 "top_buffers": [], "memory_stats": {}}
    try:
        out["backend"] = jax.default_backend()
        out["devices"] = [str(d) for d in jax.devices()]
    except Exception:  # noqa: BLE001
        pass
    try:
        per_device: Dict[str, Dict] = {}
        buffers = []
        for arr in jax.live_arrays():
            try:
                dev = str(next(iter(arr.devices())))
                nbytes = int(arr.nbytes)
            except Exception:  # noqa: BLE001
                continue
            slot = per_device.setdefault(dev, {"count": 0, "bytes": 0})
            slot["count"] += 1
            slot["bytes"] += nbytes
            buffers.append((nbytes, str(arr.shape), str(arr.dtype), dev))
        out["live_buffers"] = per_device
        buffers.sort(key=lambda b: -b[0])
        out["top_buffers"] = [
            {"bytes": b, "shape": shape, "dtype": dtype, "device": dev}
            for b, shape, dtype, dev in buffers[:20]]
    except Exception:  # noqa: BLE001
        out["live_buffers_error"] = "live_arrays unavailable"
    try:
        prof = jax.profiler.device_memory_profile()
        out["device_memory_profile_bytes"] = len(prof)
    except Exception:  # noqa: BLE001
        out["device_memory_profile_bytes"] = None
    try:
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", None)
            if callable(stats):
                s = stats()
                if s:
                    out["memory_stats"][str(d)] = {
                        k: v for k, v in s.items()
                        if isinstance(v, (int, float))}
    except Exception:  # noqa: BLE001
        pass
    return 200, out


# -- device-memory sampler -----------------------------------------------------


class MemorySampler:
    """Periodically folds live-buffer bytes per device into the
    `device_mem_*` gauges (which `telemetry/history.py` then samples into
    queryable series). No-ops cheaply while jax is unloaded."""

    def __init__(self, interval_s: float = 10.0):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.high_water: Dict[str, int] = {}

    @classmethod
    def from_env(cls) -> "MemorySampler":
        return cls(interval_s=float(
            os.environ.get("PIO_DEVICE_MEM_INTERVAL_S") or 10.0))

    def is_running(self) -> bool:
        return self._running

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pio-device-mem", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 — sampling must never die
                log.debug("device: memory sample failed", exc_info=True)

    def sample_now(self) -> Dict[str, int]:
        """One sample sweep; returns live bytes per device (empty while
        jax is unloaded)."""
        if "jax" not in sys.modules:
            return {}
        import jax

        live: Dict[str, int] = {}
        try:
            for arr in jax.live_arrays():
                try:
                    dev = str(next(iter(arr.devices())))
                    live[dev] = live.get(dev, 0) + int(arr.nbytes)
                except Exception:  # noqa: BLE001
                    continue
        except Exception:  # noqa: BLE001
            return {}
        limits: Dict[str, int] = {}
        try:
            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", None)
                if callable(stats):
                    s = stats() or {}
                    limit = s.get("bytes_limit")
                    if isinstance(limit, (int, float)) and limit > 0:
                        limits[str(d)] = int(limit)
        except Exception:  # noqa: BLE001
            pass
        for dev, nbytes in live.items():
            DEVICE_MEM_LIVE.labels(device=dev).set(nbytes)
            hw = max(self.high_water.get(dev, 0), nbytes)
            self.high_water[dev] = hw
            DEVICE_MEM_HIGH_WATER.labels(device=dev).set(hw)
        for dev, limit in limits.items():
            DEVICE_MEM_LIMIT.labels(device=dev).set(limit)
            used = live.get(dev, 0)
            DEVICE_MEM_HEADROOM.labels(device=dev).set(
                round(max(0.0, (limit - used) / limit), 6))
        return live


SAMPLER: Optional[MemorySampler] = None
_sampler_lock = threading.Lock()


def ensure_started() -> None:
    """Start the drain thread + memory sampler (idempotent); every
    instrumented server calls this at startup, same contract as the
    profiler and history."""
    if _clock_enabled:
        CLOCK.start()
    global SAMPLER
    if not _env_flag("PIO_DEVICE_MEM"):
        return
    with _sampler_lock:
        if SAMPLER is None:
            SAMPLER = MemorySampler.from_env()
        SAMPLER.start()


def stop() -> None:
    CLOCK.stop()
    with _sampler_lock:
        if SAMPLER is not None:
            SAMPLER.stop()


# -- fleet merge (rides PR 9's snapshot channel) -------------------------------


def export_state() -> Dict:
    """The per-worker device block embedded in aggregate
    snapshot_registry() payloads — what the supervisor merges. Times are
    integer microseconds so merged totals are sum-exact."""
    with _attr_lock:
        attribution_rows = [
            [k[0], k[1], k[2], k[3], v[0], v[1]]
            for k, v in _ATTR_TOTALS.items()]
    with _inventory_lock:
        fns = {name: {"compiles": e.compiles, "dispatches": e.dispatches,
                      "retraces": e.retraces}
               for name, e in _INVENTORY.items()}
    return {
        "attribution": attribution_rows,
        "fns": fns,
        "total_us": sum(r[4] for r in attribution_rows),
        "clock_running": CLOCK.is_running(),
    }


def merge_device(parts: Iterable[Tuple[str, Optional[Dict]]]) -> Dict:
    """Merge (worker_label, export_state()) pairs into one fleet device
    view. Microsecond totals are summed exactly — integers, no averaging
    — and the per-worker totals ship *inside the same payload* as the
    fleet total, so exactness is checkable from one fetch:
    ``total_us == sum(workers.values())`` always holds."""
    workers: Dict[str, int] = {}
    attribution: Dict[Tuple[str, str, str, str], List[int]] = {}
    routes: Dict[str, int] = {}
    fns: Dict[str, Dict[str, int]] = {}
    clocks_running = 0
    total_us = 0
    for wlabel, state in parts:
        if state is None:
            workers.setdefault(str(wlabel), 0)
            continue
        part_us = 0
        for row in state.get("attribution", []):
            route, fn, tier, device = (str(row[0]), str(row[1]),
                                       str(row[2]), str(row[3]))
            us, n = int(row[4]), int(row[5])
            slot = attribution.setdefault((route, fn, tier, device), [0, 0])
            slot[0] += us
            slot[1] += n
            routes[route] = routes.get(route, 0) + us
            part_us += us
        workers[str(wlabel)] = workers.get(str(wlabel), 0) + part_us
        total_us += part_us
        for name, counts in state.get("fns", {}).items():
            dst = fns.setdefault(name, {"compiles": 0, "dispatches": 0,
                                        "retraces": 0})
            for key in dst:
                dst[key] += int(counts.get(key, 0))
        if state.get("clock_running"):
            clocks_running += 1
    return {
        "fleet": True,
        "workers": workers,
        "clocks_running": clocks_running,
        "total_us": total_us,
        "total_seconds": round(total_us / 1e6, 6),
        "routes": {r: us for r, us in
                   sorted(routes.items(), key=lambda kv: -kv[1])},
        "attribution": [
            {"route": k[0], "fn": k[1], "tier": k[2], "device": k[3],
             "us": v[0], "dispatches": v[1]}
            for k, v in sorted(attribution.items(),
                               key=lambda kv: -kv[1][0])],
        "fns": fns,
    }


# -- lifecycle -----------------------------------------------------------------


def reset_state() -> None:
    """Zero the inventory and attribution totals (tests, gate drills,
    and the post-fork child — the supervisor merge must never sum a
    parent's history twice)."""
    with _inventory_lock:
        _INVENTORY.clear()
    with _attr_lock:
        _ATTR_TOTALS.clear()


def _reinit_after_fork() -> None:
    global _inventory_lock, _attr_lock, _sampler_lock, _backend_name
    _inventory_lock = threading.Lock()
    _attr_lock = threading.Lock()
    _sampler_lock = threading.Lock()
    _backend_name = None
    _INVENTORY.clear()
    _ATTR_TOTALS.clear()
    clock_was_running = CLOCK._running
    CLOCK._lock = threading.Lock()
    CLOCK._queue = queue.Queue(maxsize=CLOCK._queue.maxsize)
    CLOCK._thread = None
    CLOCK._running = False
    CLOCK._window = {}
    if clock_was_running and _clock_enabled:
        CLOCK.start()
    sampler = SAMPLER
    if sampler is not None:
        was_running = sampler._running
        sampler._stop = threading.Event()
        sampler._thread = None
        sampler._running = False
        sampler.high_water = {}
        if was_running and _env_flag("PIO_DEVICE_MEM"):
            sampler.start()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)
