"""Online-learning plane: event → servable in seconds via ALS fold-in.

The batch world (ROADMAP item 2's "freshness still means retrain") ends
here: a `StoreTailer` in batch mode feeds fresh rating events to a
`FoldIn` solve (one `ops/als.py` half-epoch restricted to the dirty
rows, cold-start rows appended for never-seen ids) and a `DeltaSwapper`
publishes the folded models into the serving plane's immutable
served-state table per variant, invalidating only the touched users'
cache entries. See docs/online.md for architecture, knobs, and the
parity-drift runbook; `quality.py --online-gate` drills freshness,
crash recovery, and full-retrain parity in CI.
"""

from predictionio_tpu.online.foldin import (  # noqa: F401
    FoldStats,
    SeenOverlay,
    fold_model,
    solve_rows,
)
from predictionio_tpu.online.plane import (  # noqa: F401
    OnlineConfig,
    OnlinePlane,
)
from predictionio_tpu.online.swap import DeltaSwapper, StaleState  # noqa: F401

__all__ = [
    "DeltaSwapper", "FoldStats", "OnlineConfig", "OnlinePlane",
    "SeenOverlay", "StaleState", "fold_model", "solve_rows",
]
