"""FakeWorkflow — run arbitrary code under the workflow harness.

Parity with «core/…/workflow/FakeWorkflow.scala :: FakeWorkflow» (SURVEY.md
§2.1 [U]): the reference lets tests and one-off jobs run a function with a
real SparkContext inside the workflow machinery (status rows, error
handling) without defining a DASE engine. The TPU equivalent hands the
function a `WorkflowContext` (mesh, storage, seed, profiling hooks) and
records an `EngineInstance` row for the run, so ad-hoc jobs stay visible
to `pio status`-style tooling and are idempotently re-runnable like any
train."""

from __future__ import annotations

import logging
import traceback
from datetime import datetime, timezone
from typing import Any, Callable, Optional

from predictionio_tpu.controller.context import WorkflowContext
from predictionio_tpu.storage.base import EngineInstance

log = logging.getLogger(__name__)


def run_fake_workflow(
    fn: Callable[[WorkflowContext], Any],
    ctx: Optional[WorkflowContext] = None,
    batch: str = "",
    record: bool = True,
) -> Any:
    """Run `fn(ctx)` as a workflow: RUNNING → COMPLETED/FAILED row in the
    engine-instances store (when `record`), exceptions re-raised after the
    FAILED mark. Returns fn's result."""
    ctx = ctx or WorkflowContext(batch=batch)
    instances = ctx.storage.meta_engine_instances() if record else None

    def now():
        return datetime.now(timezone.utc)

    instance = EngineInstance(
        id="", status="RUNNING", start_time=now(), end_time=now(),
        engine_id="fake", engine_version="1", engine_variant="fake",
        engine_factory=f"{fn.__module__}.{getattr(fn, '__qualname__', fn)}",
        batch=batch, env={},
    )
    if instances is not None:
        instance.id = instances.insert(instance)
        log.info("FakeWorkflow: instance %s RUNNING (%s)", instance.id,
                 instance.engine_factory)
    try:
        result = fn(ctx)
    except Exception:
        if instances is not None:
            instance.status = "FAILED"
            instance.end_time = now()
            instances.update(instance)
        log.error("FakeWorkflow: FAILED\n%s", traceback.format_exc())
        raise
    if instances is not None:
        instance.status = "COMPLETED"
        instance.end_time = now()
        instances.update(instance)
        log.info("FakeWorkflow: instance %s COMPLETED", instance.id)
    return result
