"""RewardTailer: $reward events out of the store, into the posteriors.

Rewards do NOT take a side channel to the bandit. Clients POST `$reward`
through /events.json like any other event, the group-commit write plane
makes it durable, and this tailer polls the event store and folds what
it finds into the ThompsonBandit's Beta posteriors. That buys three
properties a direct in-memory update cannot:

- **durability** — a reward survives a worker crash; the posterior is
  reconstructed from the store, not from process memory;
- **convergent workers** — every pool worker tails the same store, so
  all of them settle on the same split regardless of which process
  accepted the HTTP POST;
- **restart recovery** — a fresh tailer replays the full $reward
  history first (first poll has no watermark), so a redeployed server
  resumes the experiment where it left off instead of back at the
  uniform prior.

The watermark+overlap+dedup tail loop itself lives in
`ingest/tailer.py` (`StoreTailer`) since PR 11 — the online-learning
plane tails the same store with the same machinery. This subclass only
supplies the $reward filter and the posterior update.
"""

from __future__ import annotations

import logging
from typing import Optional

from predictionio_tpu.experiment.bandit import ThompsonBandit
from predictionio_tpu.experiment.metrics import (
    EXPERIMENT_POSTERIOR_MEAN,
    EXPERIMENT_REWARDS,
)
from predictionio_tpu.ingest.tailer import OVERLAP, StoreTailer  # noqa: F401
from predictionio_tpu.telemetry.lineage import LINEAGE, context_of

log = logging.getLogger(__name__)


class RewardTailer(StoreTailer):
    """Poll the durable event store for $reward events and apply them."""

    def __init__(self, storage, bandit: ThompsonBandit,
                 app_id: int = 1, channel_id: Optional[int] = None,
                 interval_s: float = 0.5):
        super().__init__(storage, app_id=app_id, channel_id=channel_id,
                         interval_s=interval_s, event_names=["$reward"],
                         name="reward-tailer")
        self.bandit = bandit

    def _apply(self, e) -> bool:
        props = e.properties.to_dict()
        variant = props.get("variant")
        try:
            reward = float(props.get("reward"))
        except (TypeError, ValueError):
            # validate_event rejects these at ingest; a hand-inserted
            # row must not wedge the tail loop
            log.warning("skipping malformed $reward %s", e.event_id)
            return False
        if not self.bandit.reward(variant, reward):
            return False
        EXPERIMENT_REWARDS.labels(variant=variant).inc()
        EXPERIMENT_POSTERIOR_MEAN.labels(variant=variant).set(
            self.bandit.posterior_mean(variant))
        # a $reward's terminal stage is the posterior update, not a fold
        lctx = context_of(e)
        LINEAGE.record_stage(lctx, "reward", detail=variant)
        LINEAGE.complete(lctx)
        return True
