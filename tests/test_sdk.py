"""Python SDK (L7) against in-process event + prediction servers —
mirrors how the reference's separate-repo Python SDK drives the REST
contract (SURVEY.md §1 L7, §4.2 quickstart_test flow)."""

import pytest

from predictionio_tpu.data.api import EventServer, EventServerConfig
from predictionio_tpu.sdk import (
    EngineClient,
    EventClient,
    NotFoundError,
    PredictionIOError,
)
from predictionio_tpu.storage.base import AccessKey, App, Channel


@pytest.fixture()
def event_client(memory_storage):
    app_id = memory_storage.meta_apps().insert(App(id=0, name="SdkApp"))
    key = AccessKey.generate(app_id)
    memory_storage.meta_access_keys().insert(key)
    memory_storage.meta_channels().insert(
        Channel(id=0, name="ch1", app_id=app_id))
    srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0, stats=True),
                      memory_storage)
    srv.start()
    yield EventClient(access_key=key.key,
                      url=f"http://127.0.0.1:{srv.port}")
    srv.shutdown()


class TestEventClient:
    def test_create_get_delete_roundtrip(self, event_client):
        eid = event_client.create_event(
            event="rate", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1",
            properties={"rating": 5})
        got = event_client.get_event(eid)
        assert got["event"] == "rate" and got["entityId"] == "u1"
        event_client.delete_event(eid)
        with pytest.raises(NotFoundError):
            event_client.get_event(eid)

    def test_find_events_filters(self, event_client):
        for i in range(3):
            event_client.record_user_action_on_item("view", "u1", f"i{i}")
        event_client.record_user_action_on_item("buy", "u1", "i0")
        views = event_client.find_events(event="view")
        assert len(views) == 3
        assert all(e["event"] == "view" for e in views)
        one = event_client.find_events(limit=1)
        assert len(one) == 1

    def test_batch(self, event_client):
        results = event_client.create_batch_events([
            {"event": "rate", "entityType": "user", "entityId": "u1",
             "targetEntityType": "item", "targetEntityId": "i1",
             "properties": {"rating": 3}},
            {"event": "rate", "entityType": "user", "entityId": "u2",
             "targetEntityType": "item", "targetEntityId": "i2",
             "properties": {"rating": 4}},
        ])
        assert len(results) == 2
        assert all(r["status"] == 201 for r in results)

    def test_entity_property_conveniences(self, event_client):
        event_client.set_user("u9", properties={"plan": "pro"})
        event_client.unset_user("u9", properties={"plan": None})
        event_client.delete_user("u9")
        event_client.set_item("i9", properties={"categories": ["a"]})
        event_client.delete_item("i9")
        events = event_client.find_events(entity_id="u9")
        assert {e["event"] for e in events} == {"$set", "$unset", "$delete"}

    def test_bad_key_raises(self, event_client):
        bad = EventClient(access_key="nope", url=event_client.url)
        with pytest.raises(PredictionIOError) as ei:
            bad.create_event(event="x", entity_type="user", entity_id="u")
        assert ei.value.status == 401

    def test_status_and_stats(self, event_client):
        assert event_client.get_status()["status"] == "alive"
        event_client.set_user("u1")
        stats = event_client.get_stats()
        assert stats  # per-app counts present


class TestEventIdDedup:
    """Client-set eventIds make event POSTs replay-safe (ADVICE r1: a
    RemoteDisconnected retry could otherwise duplicate an event the
    server committed before dying)."""

    def test_caller_event_id_roundtrip_and_real_duplicate_raises(
            self, event_client):
        eid = event_client.create_event(
            event="view", entity_type="user", entity_id="u1",
            event_id="fixed-id-1")
        assert eid == "fixed-id-1"
        # caller-supplied id: a duplicate is a real error, not mapped away
        with pytest.raises(PredictionIOError) as ei:
            event_client.create_event(
                event="view", entity_type="user", entity_id="u1",
                event_id="fixed-id-1")
        assert ei.value.status == 400

    def test_generated_id_duplicate_maps_to_success(
            self, event_client, monkeypatch):
        """A duplicate rejection for an id generated in this call proves a
        previous send attempt committed — the client reports success."""
        import uuid as _uuid

        class FakeUUID:
            hex = "replayed-uuid-0001"

        event_client.create_event(
            event="view", entity_type="user", entity_id="u1",
            event_id=FakeUUID.hex)  # "the first attempt that committed"
        monkeypatch.setattr("predictionio_tpu.sdk.uuid.uuid4",
                            lambda: FakeUUID)
        eid = event_client.create_event(
            event="view", entity_type="user", entity_id="u1")
        assert eid == FakeUUID.hex
        # only one event stored despite two successful-looking creates
        assert len([e for e in event_client.find_events(limit=-1)
                    if e["eventId"] == FakeUUID.hex]) == 1

    def test_batch_generated_id_duplicate_rewritten_to_201(
            self, event_client, monkeypatch):
        import uuid as _uuid

        class FakeUUID:
            hex = "replayed-batch-uuid"

        base = {"event": "view", "entityType": "user", "entityId": "u1"}
        first = event_client.create_batch_events(
            [dict(base, eventId=FakeUUID.hex)])
        assert first[0]["status"] == 201
        monkeypatch.setattr("predictionio_tpu.sdk.uuid.uuid4",
                            lambda: FakeUUID)
        replay = event_client.create_batch_events([dict(base)])
        assert replay[0] == {"status": 201, "eventId": FakeUUID.hex}
        # caller-set duplicate in a batch still surfaces as 400
        dup = event_client.create_batch_events(
            [dict(base, eventId=FakeUUID.hex)])
        assert dup[0]["status"] == 400


class TestEngineClient:
    def test_send_query_against_deployed_engine(self, memory_storage):
        # train a tiny recommendation model through the real workflow,
        # deploy in-process, query via the SDK (quickstart_test.py shape)
        from predictionio_tpu.workflow.create_server import (
            PredictionServer,
            ServerConfig,
        )
        from tests.test_prediction_server import train_once
        from tests.test_recommendation_template import ingest_ratings

        ingest_ratings(memory_storage)
        train_once(memory_storage)
        server = PredictionServer(
            ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                         engine_variant="rec-test"),
            memory_storage)
        server.start()
        try:
            client = EngineClient(url=f"http://127.0.0.1:{server.port}")
            result = client.send_query({"user": "u1", "num": 3})
            assert "itemScores" in result
        finally:
            server.shutdown()


class TestKeepAliveTransport:
    def test_stale_connection_reconnects(self, memory_storage):
        """Server restarts between calls: the reused keep-alive fails with
        RemoteDisconnected and the client retries once on a fresh
        connection (send-complete failures are NOT retried — POST dedup)."""
        app_id = memory_storage.meta_apps().insert(App(id=0, name="KaApp"))
        key = AccessKey.generate(app_id)
        memory_storage.meta_access_keys().insert(key)
        srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0),
                          memory_storage)
        srv.start()
        port = srv.port
        client = EventClient(access_key=key.key,
                             url=f"http://127.0.0.1:{port}")
        client.record_user_action_on_item("view", "u1", "i1")  # opens conn
        srv.shutdown()
        srv2 = EventServer(EventServerConfig(ip="127.0.0.1", port=port),
                           memory_storage)
        srv2.start()
        try:
            # reused connection is stale; must transparently reconnect
            eid = client.record_user_action_on_item("view", "u1", "i2")
            assert eid
            assert len(client.find_events(limit=-1)) == 2
        finally:
            srv2.shutdown()


class TestBusyRetry:
    """429/503 backoff-replay posture (round 6): idempotent routes retry
    with capped jittered backoff honoring Retry-After; single-event
    POSTs replay ONLY when the caller brought an explicit event_id (a
    generated id proves OUR replay is safe, but a late replay of an
    append can land behind the caller's next event)."""

    @pytest.fixture()
    def scripted(self):
        """Stub server answering from a script of (status, headers),
        then 200; records every request path."""
        import time as _time

        from predictionio_tpu.utils.http import (
            HttpService, JsonRequestHandler,
        )

        script = {"responses": [], "hits": [], "trace_ids": []}

        class Handler(JsonRequestHandler):
            def do_POST(self):
                self.read_body()
                script["hits"].append((self.path.split("?")[0],
                                       _time.monotonic()))
                script["trace_ids"].append(
                    self.headers.get("X-PIO-Trace-Id"))
                if script["responses"]:
                    status, headers = script["responses"].pop(0)
                else:
                    status, headers = 200, None
                body = ({"message": "busy"} if status >= 400
                        else {"eventId": "e-1", "itemScores": []})
                self.send_json(status, body, headers=headers)

        svc = HttpService("127.0.0.1", 0, Handler, server_name="t-busy")
        svc.start()
        yield svc, script
        svc.shutdown()

    def _fast(self, **kw):
        out = dict(busy_retries=2, busy_backoff_base_s=0.01,
                   busy_backoff_cap_s=0.3)
        out.update(kw)
        return out

    def test_send_query_replays_through_429_and_503(self, scripted):
        svc, script = scripted
        script["responses"] = [(429, {"Retry-After": "0.01"}), (503, None)]
        eng = EngineClient(url=f"http://127.0.0.1:{svc.port}",
                           **self._fast())
        out = eng.send_query({"user": "u1", "num": 1})
        assert out == {"eventId": "e-1", "itemScores": []}
        assert len(script["hits"]) == 3  # 429, 503, then the 200

    def test_retry_after_stretches_the_backoff(self, scripted):
        svc, script = scripted
        script["responses"] = [(429, {"Retry-After": "0.2"})]
        eng = EngineClient(url=f"http://127.0.0.1:{svc.port}",
                           **self._fast())
        eng.send_query({"user": "u1"})
        (_, t0), (_, t1) = script["hits"]
        assert t1 - t0 >= 0.2  # waited at least the server's ask

    def test_retries_exhausted_surfaces_the_status(self, scripted):
        svc, script = scripted
        script["responses"] = [(429, None)] * 3
        eng = EngineClient(url=f"http://127.0.0.1:{svc.port}",
                           **self._fast(busy_retries=1))
        with pytest.raises(PredictionIOError) as ei:
            eng.send_query({"user": "u1"})
        assert ei.value.status == 429
        assert len(script["hits"]) == 2  # first answer + one replay

    def test_create_event_generated_id_never_busy_replays(self, scripted):
        svc, script = scripted
        script["responses"] = [(429, None)]
        ec = EventClient(access_key="k",
                         url=f"http://127.0.0.1:{svc.port}",
                         **self._fast())
        with pytest.raises(PredictionIOError) as ei:
            ec.create_event(event="rate", entity_type="user",
                            entity_id="u1")
        assert ei.value.status == 429
        assert len(script["hits"]) == 1  # fail-fast, no replay

    def test_create_event_with_event_id_busy_replays(self, scripted):
        svc, script = scripted
        script["responses"] = [(503, {"Retry-After": "0.01"})]
        ec = EventClient(access_key="k",
                         url=f"http://127.0.0.1:{svc.port}",
                         **self._fast())
        eid = ec.create_event(event="rate", entity_type="user",
                              entity_id="u1", event_id="caller-key-1")
        assert eid == "e-1"  # the stub's answer after the replay
        assert len(script["hits"]) == 2

    def test_busy_replay_reuses_the_original_trace_id(self, scripted):
        """An idempotent replay is the SAME logical request: every
        attempt must carry the X-PIO-Trace-Id minted for the first one,
        or the server-side lineage of the event that finally commits
        can't be stitched back to the request that created it."""
        svc, script = scripted
        script["responses"] = [(503, {"Retry-After": "0.01"}),
                               (503, None)]  # 503, 503, then the 201-ish 200
        ec = EventClient(access_key="k",
                         url=f"http://127.0.0.1:{svc.port}",
                         **self._fast())
        eid = ec.create_event(event="rate", entity_type="user",
                              entity_id="u1", event_id="trace-reuse-1")
        assert eid == "e-1"
        assert len(script["hits"]) == 3
        tids = script["trace_ids"]
        assert tids[0], "first attempt carried no trace id"
        assert len(set(tids)) == 1, (
            f"busy replays minted fresh trace ids: {tids}")

    def test_busy_retries_zero_restores_fail_fast(self, scripted):
        svc, script = scripted
        script["responses"] = [(503, None)]
        eng = EngineClient(url=f"http://127.0.0.1:{svc.port}",
                           busy_retries=0)
        with pytest.raises(PredictionIOError) as ei:
            eng.send_query({"user": "u1"})
        assert ei.value.status == 503
        assert len(script["hits"]) == 1
