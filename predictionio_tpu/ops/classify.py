"""Classification ops: multinomial Naive Bayes + multinomial logistic
regression, TPU-first.

Replaces the reference Classification template's calls into Spark MLlib
(«NaiveBayes.train», «LogisticRegressionWithLBFGS/SGD» — SURVEY.md §2.4
[U]). MLlib aggregates per-class feature sums with `treeAggregate` over RDD
partitions (parameter-mixing DP, SURVEY.md §2.6 strategy 3); here both
trainers are single jitted XLA programs whose example axis is sharded over
the mesh `data` axis, so the class-count / gradient reductions become the
hardware allreduces GSPMD inserts (psum over ICI) instead of a driver-side
tree.

Design notes:
- NB sufficient statistics are ONE one-hot matmul: `onehot[N,C]ᵀ @ X[N,D]`
  → [C, D] per-class feature sums on the MXU. No per-class Python loop.
- LogReg is full-batch softmax regression driven by `lax.scan` over Adam
  steps — one dispatch for the whole train, no host round trips.
- Both pad N to the data-axis size; a weight column masks padding out of
  every reduction.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import math
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass
class NaiveBayesModel:
    """Multinomial NB: log priors [C] + log feature likelihoods [C, D]."""

    log_prior: np.ndarray
    log_theta: np.ndarray

    def logits(self, x: np.ndarray) -> np.ndarray:
        return self.log_prior + x @ self.log_theta.T


@dataclasses.dataclass
class LogRegModel:
    weights: np.ndarray  # [D, C]
    bias: np.ndarray  # [C]
    loss_history: list = dataclasses.field(default_factory=list)

    def logits(self, x: np.ndarray) -> np.ndarray:
        return x @ self.weights + self.bias


def _pad_batch(x: np.ndarray, y: np.ndarray, multiple: int):
    """Pad the example axis to `multiple`; returns (x, y, weight)."""
    n = x.shape[0]
    n_pad = -(-n // multiple) * multiple
    w = np.zeros(n_pad, dtype=np.float32)
    w[:n] = 1.0
    if n_pad != n:
        x = np.concatenate([x, np.zeros((n_pad - n,) + x.shape[1:], x.dtype)])
        y = np.concatenate([y, np.zeros(n_pad - n, y.dtype)])
    return x, y, w


def _shard_examples(mesh, *arrays):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from predictionio_tpu.parallel.mesh import DATA_AXIS

    shard = NamedSharding(mesh, P(DATA_AXIS))
    return [jax.device_put(a, shard) for a in arrays]


@functools.lru_cache(maxsize=32)
def _nb_fit(n_classes: int, smoothing: float):
    import jax
    import jax.numpy as jnp

    def fit(x, y, w):
        onehot = jax.nn.one_hot(y, n_classes, dtype=x.dtype) * w[:, None]
        class_counts = onehot.sum(0)  # [C]
        feat_sums = onehot.T @ x  # [C, D] — MXU matmul
        n = w.sum()
        d = x.shape[1]
        log_prior = jnp.log(class_counts + smoothing) - jnp.log(
            n + n_classes * smoothing
        )
        log_theta = jnp.log(feat_sums + smoothing) - jnp.log(
            feat_sums.sum(-1, keepdims=True) + d * smoothing
        )
        return log_prior, log_theta

    from predictionio_tpu.utils.profiling import metered_jit

    return metered_jit(fit, label="classify.nb_fit")


def naive_bayes_train(
    features: np.ndarray,
    labels: np.ndarray,
    n_classes: int,
    smoothing: float = 1.0,
    mesh=None,
) -> NaiveBayesModel:
    """MLlib-compatible multinomial NB («NaiveBayes.train(lambda)» [U]):
    pi_c = log((n_c + λ)/(n + Cλ)); θ_cj = log((Σ x_j|c + λ)/(Σ x|c + Dλ)).
    Features must be non-negative counts/frequencies."""
    from predictionio_tpu.parallel.mesh import DATA_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh()
    x = np.ascontiguousarray(features, dtype=np.float32)
    y = np.ascontiguousarray(labels, dtype=np.int32)
    if np.any(x < 0):
        raise ValueError("multinomial NB requires non-negative features")
    # lcm: the padded N must divide by the data-axis size for P("data")
    # placement AND stay sublane-aligned
    x, y, w = _pad_batch(x, y, math.lcm(8, mesh.shape.get(DATA_AXIS, 1)))
    x, y, w = _shard_examples(mesh, x, y, w)
    log_prior, log_theta = _nb_fit(n_classes, float(smoothing))(x, y, w)
    return NaiveBayesModel(np.asarray(log_prior), np.asarray(log_theta))


@functools.lru_cache(maxsize=16)
def _nb_fit_grid(n_classes: int):
    import jax
    import jax.numpy as jnp

    def fit(x, y, w, smoothings):
        # sufficient statistics ONCE (they don't depend on smoothing);
        # the per-cell finish is a [G]-vmapped elementwise log transform
        onehot = jax.nn.one_hot(y, n_classes, dtype=x.dtype) * w[:, None]
        class_counts = onehot.sum(0)  # [C]
        feat_sums = onehot.T @ x  # [C, D]
        n = w.sum()
        d = x.shape[1]

        def finish(s):
            log_prior = jnp.log(class_counts + s) - jnp.log(
                n + n_classes * s)
            log_theta = jnp.log(feat_sums + s) - jnp.log(
                feat_sums.sum(-1, keepdims=True) + d * s)
            return log_prior, log_theta

        return jax.vmap(finish)(smoothings)

    from predictionio_tpu.utils.profiling import metered_jit

    return metered_jit(fit, label="classify.nb_fit_grid")


def naive_bayes_train_grid(
    features: np.ndarray,
    labels: np.ndarray,
    n_classes: int,
    smoothings,
    mesh=None,
) -> "list[NaiveBayesModel]":
    """N smoothing (λ) grid cells as ONE device program (SURVEY.md §2.6
    strategy 4's TPU-native form, extended beyond the ALS flagship): the
    one-hot count matmul — the only part that touches the data — runs
    once, and the λ-dependent log transforms vmap over a traced [G]
    axis. Per-cell results match `naive_bayes_train` exactly."""
    import jax.numpy as jnp

    from predictionio_tpu.parallel.mesh import DATA_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh()
    x = np.ascontiguousarray(features, dtype=np.float32)
    y = np.ascontiguousarray(labels, dtype=np.int32)
    if np.any(x < 0):
        raise ValueError("multinomial NB requires non-negative features")
    x, y, w = _pad_batch(x, y, math.lcm(8, mesh.shape.get(DATA_AXIS, 1)))
    x, y, w = _shard_examples(mesh, x, y, w)
    s = jnp.asarray([float(v) for v in smoothings], dtype=jnp.float32)
    log_prior, log_theta = _nb_fit_grid(n_classes)(x, y, w, s)
    lp, lt = np.asarray(log_prior), np.asarray(log_theta)
    return [NaiveBayesModel(lp[g], lt[g]) for g in range(len(s))]


@functools.lru_cache(maxsize=32)
def _logreg_fit(n_classes: int, n_steps: int, lr: float, reg: float):
    """`n_steps` Adam iterations as one jitted scan over an explicit
    (params, opt_state) carry — the carry fully captures trainer state,
    so the run segments into checkpoint-sized chunks (workflow/segmented)
    with results identical to one whole-run dispatch."""
    import jax
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    def loss_fn(params, x, y, w):
        logits = x @ params["w"] + params["b"]
        ll = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        data = (ll * w).sum() / jnp.maximum(w.sum(), 1.0)
        return data + 0.5 * reg * jnp.sum(params["w"] ** 2)

    def fit(params0, state0, x, y, w):
        def step(carry, _):
            params, state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y, w)
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            return (params, state), loss

        (params, state), losses = jax.lax.scan(
            step, (params0, state0), xs=None, length=n_steps
        )
        return params, state, losses

    from predictionio_tpu.utils.profiling import metered_jit

    return metered_jit(fit, label="classify.logreg_fit")


@functools.lru_cache(maxsize=16)
def _logreg_fit_grid(n_classes: int, n_steps: int):
    import jax
    import jax.numpy as jnp
    import optax

    # optax.adam(lr) == scale_by_adam() then scale(-lr); keeping lr out
    # of the transform lets it be a traced per-cell scalar under vmap.
    # (-lr)·d == -(lr·d) exactly in IEEE, so cells match the sequential
    # `_logreg_fit` bit for bit modulo vmap layout.
    base = optax.scale_by_adam()

    def fit_one(lr, reg, n_iter, params0, x, y, w):
        def loss_fn(params):
            logits = x @ params["w"] + params["b"]
            ll = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            data = (ll * w).sum() / jnp.maximum(w.sum(), 1.0)
            return data + 0.5 * reg * jnp.sum(params["w"] ** 2)

        def step(carry, t):
            params, state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, new_state = base.update(grads, state, params)
            new_params = jax.tree.map(lambda p, u: p - lr * u,
                                      params, updates)
            # per-cell iteration horizon (traced): past its own count a
            # cell carries params AND optimizer state unchanged, landing
            # exactly on its sequential result while longer cells keep
            # stepping
            act = t < n_iter
            params = jax.tree.map(
                lambda new, old: jnp.where(act, new, old),
                new_params, params)
            state = jax.tree.map(
                lambda new, old: jnp.where(act, new, old),
                new_state, state)
            return (params, state), loss

        (params, _), losses = jax.lax.scan(
            step, (params0, base.init(params0)), xs=jnp.arange(n_steps))
        return params, losses

    def run(lrs, regs, n_iters, params0, x, y, w):
        return jax.vmap(fit_one, in_axes=(0, 0, 0, None, None, None, None))(
            lrs, regs, n_iters, params0, x, y, w)

    from predictionio_tpu.utils.profiling import metered_jit

    return metered_jit(run, label="classify.logreg_fit_grid")


def logreg_train_grid(
    features: np.ndarray,
    labels: np.ndarray,
    n_classes: int,
    iterations,
    learning_rates,
    regs,
    mesh=None,
) -> "list[LogRegModel]":
    """N (stepSize, regParam, iterations) grid cells as ONE device
    program: the full-batch Adam scan vmaps over a traced [G]
    hyperparameter axis — one compile, one dispatch, the sharded example
    matmuls batched [G, N, D] on the MXU instead of re-dispatched per
    cell. `iterations` is an int shared by every cell OR a per-cell
    sequence (round 5): the scan runs max(iterations) steps and each
    cell freezes params + optimizer state at its own horizon, matching
    its sequential train."""
    import jax.numpy as jnp

    from predictionio_tpu.parallel.mesh import DATA_AXIS, make_mesh

    if mesh is None:
        mesh = make_mesh()
    x = np.ascontiguousarray(features, dtype=np.float32)
    y = np.ascontiguousarray(labels, dtype=np.int32)
    d = x.shape[1]
    x, y, w = _pad_batch(x, y, math.lcm(8, mesh.shape.get(DATA_AXIS, 1)))
    x, y, w = _shard_examples(mesh, x, y, w)
    params0 = {
        "w": jnp.zeros((d, n_classes), dtype=jnp.float32),
        "b": jnp.zeros((n_classes,), dtype=jnp.float32),
    }
    lrs = jnp.asarray([float(v) for v in learning_rates], jnp.float32)
    rgs = jnp.asarray([float(v) for v in regs], jnp.float32)
    if np.ndim(iterations) == 0:
        iters_list = [int(iterations)] * int(len(lrs))
    else:
        iters_list = [int(v) for v in iterations]
    if len(iters_list) != len(lrs):
        raise ValueError(
            f"logreg_train_grid: {len(iters_list)} iteration counts for "
            f"{len(lrs)} cells")
    n_steps = max(iters_list) if iters_list else 0
    n_iters = jnp.asarray(iters_list, jnp.int32)
    params, losses = _logreg_fit_grid(n_classes, n_steps)(
        lrs, rgs, n_iters, params0, x, y, w)
    wts = np.asarray(params["w"])
    bs = np.asarray(params["b"])
    ls = np.asarray(losses)
    return [
        LogRegModel(weights=wts[g], bias=bs[g],
                    # post-horizon rows re-measure frozen params — slice
                    # to the cell's own history
                    loss_history=[float(v) for v in ls[g][:iters_list[g]]])
        for g in range(len(lrs))
    ]


def logreg_train(
    features: np.ndarray,
    labels: np.ndarray,
    n_classes: int,
    iterations: int = 200,
    learning_rate: float = 0.1,
    reg: float = 0.0,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
) -> LogRegModel:
    """Softmax regression, full-batch Adam in one jitted `lax.scan` —
    gradients over the sharded example axis reduce via GSPMD psum (the
    `treeAggregate` replacement, SURVEY.md §2.7 'Aggregation').

    `checkpoint_dir`: when set, (params, Adam state) are checkpointed
    every `checkpoint_every` iterations (default: one save at the end)
    under a fingerprint of the training data + config, and a re-run
    resumes from the latest usable step — the same SURVEY.md §5
    contract als_train carries, via workflow/segmented. Without it the
    whole run stays ONE dispatch (unchanged behavior)."""
    import jax
    import jax.numpy as jnp
    import optax

    from predictionio_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from predictionio_tpu.workflow.segmented import (
        fingerprint_of, segmented_train,
    )

    if mesh is None:
        mesh = make_mesh()
    x_np = np.ascontiguousarray(features, dtype=np.float32)
    y_np = np.ascontiguousarray(labels, dtype=np.int32)
    d = x_np.shape[1]
    x, y, w = _pad_batch(x_np, y_np, math.lcm(8, mesh.shape.get(DATA_AXIS, 1)))
    x, y, w = _shard_examples(mesh, x, y, w)
    lr, rg = float(learning_rate), float(reg)
    opt = optax.adam(lr)

    def init_state():
        params0 = {
            "w": jnp.zeros((d, n_classes), dtype=jnp.float32),
            "b": jnp.zeros((n_classes,), dtype=jnp.float32),
        }
        return (params0, opt.init(params0))

    def run_chunk(state, n_steps, done):
        params, ostate = state
        params, ostate, losses = _logreg_fit(n_classes, n_steps, lr, rg)(
            params, ostate, x, y, w)
        # np.asarray on the losses is the execution fence (scalar
        # readback — see segmented_train's contract)
        return (params, ostate), [float(v) for v in np.asarray(losses)]

    def state_to_host(state):
        return {"leaves": [np.asarray(leaf) for leaf in jax.tree.leaves(state)]}

    def state_from_host(tree):
        template = init_state()
        want = jax.tree.leaves(template)
        got = tree["leaves"]
        if len(got) != len(want):
            raise ValueError(f"leaf count {len(got)} != {len(want)}")
        leaves = []
        for g, t in zip(got, want):
            if tuple(np.shape(g)) != tuple(t.shape):
                raise ValueError(f"shape {np.shape(g)} != {t.shape}")
            leaves.append(jnp.asarray(g, dtype=t.dtype))
        return jax.tree.unflatten(jax.tree.structure(template), leaves)

    # fingerprint excludes `iterations` (resuming into a longer run is
    # legal, matching als_train) but covers data, shapes, and config
    fp = fingerprint_of(x_np, y_np, (n_classes, d, lr, rg, "logreg.v1"))
    state, history, _ = segmented_train(
        total_steps=int(iterations),
        init_state=init_state,
        run_chunk=run_chunk,
        state_to_host=state_to_host,
        state_from_host=state_from_host,
        fingerprint=fp,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        fault_site="logreg.step_boundary",
        name="logreg_train",
    )
    params = state[0]
    return LogRegModel(
        weights=np.asarray(params["w"]),
        bias=np.asarray(params["b"]),
        loss_history=[float(v) for v in history],
    )
