"""Checkpoint/resume: manager round-trips, ALS per-epoch checkpointing,
and resume-after-interruption equivalence (SURVEY.md §5 'Checkpoint /
resume' — the rebuild's stronger contract vs the reference's
whole-model-after-train persistence)."""

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.workflow.checkpoint import CheckpointManager
from tests.test_als import synth_ratings


class TestCheckpointManager:
    def test_round_trip_nested_tree(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        tree = {
            "factors": {"user": np.arange(6, dtype=np.float32).reshape(2, 3),
                        "item": np.ones((3, 3))},
            "history": [np.float32(1.5), np.float32(0.7)],
            "step_count": np.int64(2),
        }
        cm.save(2, tree, metadata={"note": "hello"})
        restored, meta = cm.restore()
        assert meta["note"] == "hello"
        np.testing.assert_array_equal(restored["factors"]["user"],
                                      tree["factors"]["user"])
        np.testing.assert_array_equal(restored["factors"]["item"],
                                      tree["factors"]["item"])
        np.testing.assert_allclose([float(x) for x in restored["history"]],
                                   [1.5, 0.7], rtol=1e-6)
        assert int(restored["step_count"]) == 2

    def test_latest_and_gc(self, tmp_path):
        cm = CheckpointManager(str(tmp_path), keep=2)
        for step in (1, 2, 3, 4):
            cm.save(step, {"x": np.full((2,), step, dtype=np.float32)})
        assert cm.latest_step() == 4
        assert cm.all_steps() == [3, 4]  # keep=2 garbage-collects the rest
        restored, _ = cm.restore(3)
        assert restored["x"][0] == 3.0

    def test_restore_empty_raises(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            cm.restore()

    def test_tuple_and_scalar_leaves(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.save(1, {"t": (np.zeros(2), np.ones(2))})
        restored, _ = cm.restore(1)
        assert isinstance(restored["t"], tuple)
        np.testing.assert_array_equal(restored["t"][1], np.ones(2))


class TestALSCheckpointResume:
    def test_checkpointed_matches_single_dispatch(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=3)
        cfg = ALSConfig(rank=4, iterations=4, reg=0.05, seed=7)
        base = als_train(ui, ii, r, 30, 20, cfg)
        ckpt = als_train(ui, ii, r, 30, 20, cfg,
                         checkpoint_dir=str(tmp_path), checkpoint_every=2)
        np.testing.assert_allclose(base.user_factors, ckpt.user_factors,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(base.item_factors, ckpt.item_factors,
                                   rtol=1e-4, atol=1e-5)
        cm = CheckpointManager(str(tmp_path))
        assert cm.latest_step() == 4

    def test_resume_continues_from_latest(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=4)
        full_cfg = ALSConfig(rank=4, iterations=6, reg=0.05, seed=9)
        # "interrupted" run: only 3 of 6 iterations, checkpointed
        partial = als_train(ui, ii, r, 30, 20,
                            ALSConfig(rank=4, iterations=3, reg=0.05, seed=9),
                            checkpoint_dir=str(tmp_path), checkpoint_every=1)
        assert CheckpointManager(str(tmp_path)).latest_step() == 3
        # re-run asking for the full 6: must resume at step 3, not restart
        resumed = als_train(ui, ii, r, 30, 20, full_cfg,
                            checkpoint_dir=str(tmp_path), checkpoint_every=1)
        uninterrupted = als_train(ui, ii, r, 30, 20, full_cfg)
        np.testing.assert_allclose(resumed.user_factors,
                                   uninterrupted.user_factors,
                                   rtol=1e-4, atol=1e-5)
        assert CheckpointManager(str(tmp_path)).latest_step() == 6
        # the resumed run only paid for the remaining epochs
        assert len(resumed.epoch_times) == 3

    def test_resume_rmse_history_concatenates(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=5)
        als_train(ui, ii, r, 30, 20,
                  ALSConfig(rank=4, iterations=2, reg=0.05, seed=1),
                  checkpoint_dir=str(tmp_path), compute_rmse=True)
        resumed = als_train(ui, ii, r, 30, 20,
                            ALSConfig(rank=4, iterations=5, reg=0.05, seed=1),
                            checkpoint_dir=str(tmp_path), compute_rmse=True)
        assert len(resumed.rmse_history) == 5
        # converging: later rmse no worse than the first
        assert resumed.rmse_history[-1] <= resumed.rmse_history[0] + 1e-6

    def test_changed_data_retrains_from_scratch(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=8)
        cfg = ALSConfig(rank=4, iterations=2, reg=0.05, seed=3)
        stale = als_train(ui, ii, r, 30, 20, cfg, checkpoint_dir=str(tmp_path))
        # nightly retrain with new ratings into the same dir: the completed
        # checkpoint must NOT be returned as the new model
        r2 = r.copy()
        r2[0] += 2.0
        fresh = als_train(ui, ii, r2, 30, 20, cfg, checkpoint_dir=str(tmp_path))
        direct = als_train(ui, ii, r2, 30, 20, cfg)
        np.testing.assert_allclose(fresh.user_factors, direct.user_factors,
                                   rtol=1e-4, atol=1e-5)
        assert not np.allclose(fresh.user_factors, stale.user_factors)
        assert len(fresh.epoch_times) == 2

    def test_fully_resumed_run_returns_model_without_training(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=9)
        cfg = ALSConfig(rank=4, iterations=2, reg=0.05, seed=4)
        first = als_train(ui, ii, r, 30, 20, cfg, checkpoint_dir=str(tmp_path))
        again = als_train(ui, ii, r, 30, 20, cfg, checkpoint_dir=str(tmp_path))
        np.testing.assert_allclose(first.user_factors, again.user_factors)
        assert again.epoch_times == []  # no iterations executed

    def test_checkpoint_every_zero_does_not_hang(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=10)
        out = als_train(ui, ii, r, 30, 20,
                        ALSConfig(rank=4, iterations=2, reg=0.05, seed=5),
                        checkpoint_dir=str(tmp_path), checkpoint_every=0)
        assert np.isfinite(out.user_factors).all()

    def test_stale_higher_steps_purged_on_data_change(self, tmp_path):
        # a previous 6-iter run's leftovers must not shadow a new shorter
        # run's saves (the retention GC keeps the HIGHEST steps)
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=11)
        als_train(ui, ii, r, 30, 20,
                  ALSConfig(rank=4, iterations=6, reg=0.05, seed=6),
                  checkpoint_dir=str(tmp_path))
        r2 = r.copy()
        r2[0] += 1.0
        als_train(ui, ii, r2, 30, 20,
                  ALSConfig(rank=4, iterations=3, reg=0.05, seed=6),
                  checkpoint_dir=str(tmp_path))
        cm = CheckpointManager(str(tmp_path))
        assert cm.all_steps() == [1, 2, 3]  # old 4..6 gone, new saves kept
        # an interrupted re-run of the new config can actually resume
        resumed = als_train(ui, ii, r2, 30, 20,
                            ALSConfig(rank=4, iterations=3, reg=0.05, seed=6),
                            checkpoint_dir=str(tmp_path))
        assert resumed.epoch_times == []

    def test_fewer_iterations_than_checkpoint_retrains_to_target(self, tmp_path):
        # completed 6-iter checkpoint; asking for 3 must NOT return the
        # over-trained 6-iter factors
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=12)
        als_train(ui, ii, r, 30, 20,
                  ALSConfig(rank=4, iterations=6, reg=0.05, seed=7),
                  checkpoint_dir=str(tmp_path), checkpoint_every=2)
        shorter = als_train(ui, ii, r, 30, 20,
                            ALSConfig(rank=4, iterations=3, reg=0.05, seed=7),
                            checkpoint_dir=str(tmp_path), checkpoint_every=2)
        direct = als_train(ui, ii, r, 30, 20,
                           ALSConfig(rank=4, iterations=3, reg=0.05, seed=7))
        np.testing.assert_allclose(shorter.user_factors, direct.user_factors,
                                   rtol=1e-4, atol=1e-5)

    def test_resumed_metric_steps_continue_numbering(self, tmp_path):
        # start_epoch lets callers label resumed epochs correctly
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=13)
        als_train(ui, ii, r, 30, 20,
                  ALSConfig(rank=4, iterations=2, reg=0.05, seed=8),
                  checkpoint_dir=str(tmp_path))
        resumed = als_train(ui, ii, r, 30, 20,
                            ALSConfig(rank=4, iterations=5, reg=0.05, seed=8),
                            checkpoint_dir=str(tmp_path))
        assert resumed.start_epoch == 2
        assert len(resumed.epoch_times) == 3

    def test_mismatched_shapes_ignored(self, tmp_path):
        ui, ii, r, _ = synth_ratings(n_users=30, n_items=20, seed=6)
        als_train(ui, ii, r, 30, 20, ALSConfig(rank=4, iterations=1, seed=2),
                  checkpoint_dir=str(tmp_path))
        # different rank: stale checkpoint must not be loaded
        out = als_train(ui, ii, r, 30, 20, ALSConfig(rank=6, iterations=2, seed=2),
                        checkpoint_dir=str(tmp_path))
        assert out.user_factors.shape == (30, 6)


class TestWorkflowCheckpointWiring:
    def test_context_algorithm_dir(self, tmp_path):
        from predictionio_tpu.controller.context import WorkflowContext

        ctx = WorkflowContext(checkpoint_dir=str(tmp_path))
        d = ctx.algorithm_checkpoint_dir("als")
        assert d is not None and d.endswith("als")
        assert WorkflowContext().algorithm_checkpoint_dir("als") is None


class TestSegmentedTrainers:
    """VERDICT r4 missing #1: the ALS checkpoint contract generalized to
    the W2V SGNS loop and LogReg's Adam scan (workflow/segmented.py).
    The bar is IDENTITY: chunked, killed-and-resumed, and extended runs
    must reproduce the single-dispatch result bit for bit — the carry
    (params+opt state / embeddings+PRNG key) fully captures trainer
    state."""

    def _xy(self, seed=0, n=240, d=12, c=3):
        rng = np.random.default_rng(seed)
        return (rng.normal(size=(n, d)).astype(np.float32),
                rng.integers(0, c, n))

    def _docs(self):
        return [["the", "cat", "sat", "on", "mat"],
                ["dog", "ate", "cat", "food"],
                ["the", "dog", "sat"]] * 15

    def _w2v_cfg(self):
        from predictionio_tpu.ops.text import Word2VecConfig

        return Word2VecConfig(dim=8, steps=30, batch_size=32, negatives=3,
                              seed=3)

    def test_logreg_chunked_matches_single_dispatch(self, tmp_path):
        from predictionio_tpu.ops.classify import logreg_train

        x, y = self._xy()
        base = logreg_train(x, y, 3, iterations=40)
        chunked = logreg_train(x, y, 3, iterations=40,
                               checkpoint_dir=str(tmp_path),
                               checkpoint_every=7)
        np.testing.assert_array_equal(chunked.weights, base.weights)
        np.testing.assert_array_equal(chunked.bias, base.bias)
        assert chunked.loss_history == base.loss_history

    def test_logreg_resume_and_extend(self, tmp_path):
        from predictionio_tpu.ops.classify import logreg_train

        x, y = self._xy(1)
        base = logreg_train(x, y, 3, iterations=40)
        # partial run (20 iters) then an extended re-run to 40: resumes
        # at 20 and lands exactly on the uninterrupted 40-iter result
        logreg_train(x, y, 3, iterations=20,
                     checkpoint_dir=str(tmp_path), checkpoint_every=10)
        got = logreg_train(x, y, 3, iterations=40,
                           checkpoint_dir=str(tmp_path), checkpoint_every=10)
        np.testing.assert_array_equal(got.weights, base.weights)
        assert got.loss_history == base.loss_history  # prefix restored

    def test_logreg_changed_data_retrains(self, tmp_path, caplog):
        import logging

        from predictionio_tpu.ops.classify import logreg_train

        x, y = self._xy(2)
        logreg_train(x, y, 3, iterations=10,
                     checkpoint_dir=str(tmp_path), checkpoint_every=5)
        x2 = x + 1.0  # new data, same shapes
        base = logreg_train(x2, y, 3, iterations=10)
        with caplog.at_level(logging.WARNING):
            got = logreg_train(x2, y, 3, iterations=10,
                               checkpoint_dir=str(tmp_path),
                               checkpoint_every=5)
        np.testing.assert_array_equal(got.weights, base.weights)
        assert any("different data/config" in r.message
                   for r in caplog.records)

    def test_logreg_default_saves_once_at_end(self, tmp_path):
        from predictionio_tpu.ops.classify import logreg_train

        logreg_train(*self._xy(3), 3, iterations=12,
                     checkpoint_dir=str(tmp_path))
        assert CheckpointManager(str(tmp_path)).all_steps() == [12]

    def test_w2v_chunked_matches_single_dispatch(self, tmp_path):
        from predictionio_tpu.ops.text import word2vec_train

        docs, cfg = self._docs(), self._w2v_cfg()
        base = word2vec_train(docs, cfg)
        chunked = word2vec_train(docs, cfg, checkpoint_dir=str(tmp_path),
                                 checkpoint_every=7)
        np.testing.assert_array_equal(chunked.vectors, base.vectors)
        assert chunked.vocab == base.vocab

    def test_w2v_resume_continues_sampling_sequence(self, tmp_path):
        """The checkpointed carry includes the step PRNG key, so a
        resumed run samples the exact batches the uninterrupted run
        would have — asserted by bitwise identity of the final
        embeddings."""
        import dataclasses as dc

        from predictionio_tpu.ops.text import word2vec_train

        docs, cfg = self._docs(), self._w2v_cfg()
        base = word2vec_train(docs, cfg)
        partial = dc.replace(cfg, steps=14)
        word2vec_train(docs, partial, checkpoint_dir=str(tmp_path),
                       checkpoint_every=7)
        got = word2vec_train(docs, cfg, checkpoint_dir=str(tmp_path),
                             checkpoint_every=7)
        np.testing.assert_array_equal(got.vectors, base.vectors)

    def test_w2v_changed_config_retrains(self, tmp_path):
        import dataclasses as dc

        from predictionio_tpu.ops.text import word2vec_train

        docs, cfg = self._docs(), self._w2v_cfg()
        word2vec_train(docs, cfg, checkpoint_dir=str(tmp_path),
                       checkpoint_every=10)
        cfg2 = dc.replace(cfg, learning_rate=0.01)
        base = word2vec_train(docs, cfg2)
        got = word2vec_train(docs, cfg2, checkpoint_dir=str(tmp_path),
                             checkpoint_every=10)
        np.testing.assert_array_equal(got.vectors, base.vectors)


class TestSegmentedFuzz:
    """Property fuzz of the generic segmented-dispatch machinery
    (workflow/segmented.py, round 5): for RANDOM (total_steps,
    checkpoint_every, interruption point) the resumed run must land on
    the uninterrupted result exactly, with the metric history covering
    every absolute step exactly once. The toy trainer is a blake2 hash
    chain — any skipped, repeated, or re-ordered step changes the final
    digest, so identity is a strict execution-order proof."""

    @staticmethod
    def _toy(fingerprint="toyfp"):
        import hashlib

        def init_state():
            return b"genesis"

        def run_chunk(state, n_steps, done):
            metrics = []
            for k in range(n_steps):
                state = hashlib.blake2b(
                    state + str(done + k).encode(), digest_size=16).digest()
                metrics.append(float(state[0]))
            return state, metrics

        return dict(
            init_state=init_state,
            run_chunk=run_chunk,
            state_to_host=lambda s: {"state": np.frombuffer(s, np.uint8)},
            state_from_host=lambda t: t["state"].tobytes(),
            fingerprint=fingerprint,
        )

    def test_random_interruptions_resume_to_identity(self, tmp_path):
        from predictionio_tpu.workflow.segmented import segmented_train

        rng = np.random.default_rng(42)
        for trial in range(25):
            total = int(rng.integers(1, 13))
            every = int(rng.integers(1, total + 3))
            partial = int(rng.integers(0, total + 1))
            ckpt = str(tmp_path / f"t{trial}")
            toy = self._toy()
            want, want_hist, _ = segmented_train(
                total_steps=total, checkpoint_dir=None, **toy)
            if partial:
                segmented_train(total_steps=partial, checkpoint_dir=ckpt,
                                checkpoint_every=every, **toy)
            got, hist, start = segmented_train(
                total_steps=total, checkpoint_dir=ckpt,
                checkpoint_every=every, **toy)
            label = (f"trial {trial}: total={total} every={every} "
                     f"partial={partial} start={start}")
            assert got == want, label
            assert len(hist) == total, label
            assert hist == want_hist, label
            # a third, fully-resumed run returns without recomputing
            again, hist2, start2 = segmented_train(
                total_steps=total, checkpoint_dir=ckpt,
                checkpoint_every=every, **toy)
            assert again == want and start2 == total, label
            assert hist2 == want_hist, label

    def test_fingerprint_change_restarts(self, tmp_path):
        from predictionio_tpu.workflow.segmented import segmented_train

        toy_a = self._toy("fpA")
        segmented_train(total_steps=6, checkpoint_dir=str(tmp_path),
                        checkpoint_every=2, **toy_a)
        toy_b = self._toy("fpB")
        want, _, _ = segmented_train(total_steps=6, checkpoint_dir=None,
                                     **toy_b)
        got, hist, start = segmented_train(
            total_steps=6, checkpoint_dir=str(tmp_path),
            checkpoint_every=2, **toy_b)
        assert got == want and start == 0 and len(hist) == 6
