"""Fixture: a route whose blocking calls live two modules away —
routes → helper → db. This module itself contains nothing blocking, so
the PR 12 same-module closure rule provably missed it; the
whole-program rule must flag the db module with the witness chain."""

import xmod_helper


class XModAPI:
    def router(self, r):
        r.get("/report.json", self._handle_report)
        return r

    def _handle_report(self, req):
        return xmod_helper.load_report("users")
