"""Commit-notification bus: ingest write plane → serving result cache.

The serving plane's optional per-user result cache answers /queries.json
from memory; this bus is what keeps it read-your-writes. Every durable
commit path in the write plane (inline lone commit, grouped commit,
per-item fallback, and the batch route's direct insert_batch) publishes
the entity ids of the committed events; subscribers (the result cache)
drop whatever they hold for those entities.

Messages optionally carry an **engine variant id**. A plain data commit
(`variant=None`) may change any variant's answer, so every subscriber
acts on it; a variant-scoped commit (today: a `$reward` event, whose
properties name the variant it credits) only concerns that variant's
cache, and the per-variant serving planes of the experiment router
(experiment/router.py) filter on it. Subscribers that predate variants —
one-argument callables — keep working: the bus detects at subscribe time
whether the callable can take the variant and calls it accordingly.

Deliberately minimal:

- process-local. The cache and the write plane live in the same process
  per SO_REUSEPORT worker; a worker's cache can go stale only for writes
  landing on a *different* worker, which is why the cache also carries a
  short TTL (PIO_HTTP_RESULT_CACHE_TTL_S) as the cross-process bound.
- zero hot-path cost when unused: publishers check `has_subscribers`
  (one attribute read) before building the entity-id list, so ingest
  pays nothing unless a result cache is actually enabled.
- subscriber errors are contained: a broken subscriber cannot fail a
  commit that is already durable.
"""

from __future__ import annotations

import inspect
import logging
import threading
from typing import Callable, Iterable, List, Optional, Tuple

log = logging.getLogger(__name__)


def _accepts_variant(fn: Callable) -> bool:
    """True when `fn(entity_ids, variant)` is callable: a second
    positional slot (or *args) exists. Builtin callables that refuse
    introspection (list.append) are treated as single-argument."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    positional = 0
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            return True
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            positional += 1
    return positional >= 2


class InvalidationBus:
    __slots__ = ("_subs", "_lock")

    def __init__(self):
        self._subs: List[Tuple[Callable, bool]] = []
        self._lock = threading.Lock()

    @property
    def has_subscribers(self) -> bool:
        return bool(self._subs)

    def subscribe(self, fn: Callable) -> None:
        with self._lock:
            if all(s != fn for s, _ in self._subs):
                # replace the list instead of mutating it so publish()
                # iterates a stable snapshot without taking the lock
                self._subs = self._subs + [(fn, _accepts_variant(fn))]

    def unsubscribe(self, fn: Callable) -> None:
        # equality, not identity: bound methods (cache.invalidate_entities,
        # list.append) are fresh objects on every attribute access, and
        # subscribe's dedup (`s != fn`) already compares by equality
        with self._lock:
            self._subs = [(s, w) for s, w in self._subs if s != fn]

    def publish(self, entity_ids: Iterable[str],
                variant: Optional[str] = None) -> None:
        """Fan committed entity ids out to every subscriber. Called by
        the write plane AFTER the commit is durable — a subscriber that
        invalidates on this signal can never cache ahead of storage.
        `variant=None` means the commit may affect every variant;
        a named variant scopes the message to that variant's caches."""
        for fn, wants_variant in self._subs:
            try:
                if wants_variant:
                    fn(entity_ids, variant)
                else:
                    fn(entity_ids)
            except Exception:
                log.exception("invalidation subscriber failed")


# One bus per process: the write plane publishes here unconditionally,
# whichever server object owns it; caches subscribe at construction.
BUS = InvalidationBus()
