"""predictionio_tpu — a TPU-native ML-server framework.

Reproduces the capability surface of PredictionIO (reference:
chien146/PredictionIO, a fork of Apache PredictionIO — see SURVEY.md): the
DASE engine abstraction, an event server, engine.json-parameterized engine
templates, the `pio` CLI lifecycle, metadata/model/event storage, and an
HTTP prediction server — re-designed TPU-first on JAX/XLA/pjit/Pallas
instead of Scala/Spark.

Layering (bottom → top), mirroring SURVEY.md §1:

    predictionio_tpu.data       event model (Event, DataMap, PropertyMap, BiMap)
    predictionio_tpu.storage    storage registry + SQLite/memory/localfs backends
    predictionio_tpu.ops        jitted XLA/Pallas compute kernels (ALS, logreg, ...)
    predictionio_tpu.parallel   mesh / sharding / collectives / multi-host init
    predictionio_tpu.models     model pytrees + checkpoint helpers
    predictionio_tpu.controller DASE public API (Engine, DataSource, Algorithm, ...)
    predictionio_tpu.workflow   train/eval/serve runtimes (CoreWorkflow, CreateServer)
    predictionio_tpu.templates  built-in engine templates (recommendation, ...)
    predictionio_tpu.tools      `pio-tpu` CLI console, import/export, dashboard

Heavy deps (jax) are imported lazily by the modules that need them, so the
storage/event layers remain usable in processes that never touch a device.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("PIO_LOCKSAN"):
    # opt-in lock-order sanitizer: patch threading.Lock/RLock before
    # any plane module creates its locks (utils/locksan.py)
    from predictionio_tpu.utils import locksan as _locksan
    _locksan.maybe_install()

from predictionio_tpu.data.events import Event  # noqa: F401
from predictionio_tpu.data.datamap import DataMap, PropertyMap  # noqa: F401
from predictionio_tpu.data.bimap import BiMap  # noqa: F401
