"""`pio deploy --workers N` — SO_REUSEPORT pre-fork serving scale-out.

Compatibility shim. The pool lifecycle (fork/reap, readiness, respawn)
used to live here, split awkwardly from the serve/reload half in
`create_server.py`; both halves now belong to the supervisor control
plane in `predictionio_tpu/runtime/supervisor.py`, which added what this
module never had: SLO-driven autoscaling within `[min,max]` worker
bounds, worker-by-worker drain-then-reload rolling deploys (zero non-2xx
under load), heartbeat-based hang/error detection, and jittered-backoff
restarts behind per-slot circuit breakers.

The public contract is unchanged and re-exported here:

- `run_worker_pool(config, n_workers)` — supervise the pool, return the
  `pio deploy` exit code, mutate `config.port` when called with port 0;
- the `worker_pool_*` metric family (spawned/respawns/startup failures/
  live gauge) keeps its names — the new `supervisor_*` family is
  additive (see docs/operations.md § Supervisor).

Design rationale that still applies verbatim (SO_REUSEPORT balancing,
per-process GIL/model/jit isolation, why ingest is NOT pooled) lives in
the supervisor module's docstring.
"""

from __future__ import annotations

from predictionio_tpu.runtime.supervisor import (  # noqa: F401
    POOL_RESPAWNS,
    POOL_SPAWNED,
    POOL_STARTUP_FAILURES,
    POOL_WORKERS,
    Supervisor,
    SupervisorConfig,
    _READY_FMT,
    run_worker_pool,
)

__all__ = [
    "POOL_RESPAWNS", "POOL_SPAWNED", "POOL_STARTUP_FAILURES",
    "POOL_WORKERS", "Supervisor", "SupervisorConfig", "run_worker_pool",
]
