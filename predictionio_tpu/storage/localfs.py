"""LocalFS model-blob backend.

Parity with «storage/localfs/.../LocalFSModels.scala» (SURVEY.md §2.2
'LocalFS/HDFS/S3 model stores' [U]): model blobs as files on the local
filesystem — the right home for multi-hundred-MB factor matrices that
shouldn't live as SQLite rows. Only the `models()` repository is backed;
a LocalFS source wired as METADATA or EVENTDATA fails fast with a clear
message (the reference's localfs backend likewise only implements Models).

Writes are atomic (temp file + os.replace) so a crashed train never
leaves a half-written blob where `pio deploy` will read.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Optional

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model

log = logging.getLogger(__name__)


def _current_umask() -> int:
    mask = os.umask(0)
    os.umask(mask)
    return mask


class LocalFSModels(base.Models):
    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        # sweep temp files orphaned by a hard-killed writer (mkstemp done,
        # os.replace never reached). Age-gated: another live process may be
        # mid-write in this same directory (train writes, deploy reads)
        import time

        cutoff = time.time() - 3600
        for name in os.listdir(self.directory):
            if name.endswith(".tmp"):
                p = os.path.join(self.directory, name)
                try:
                    if os.path.getmtime(p) < cutoff:
                        os.unlink(p)
                except OSError:
                    pass

    def _path(self, model_id: str) -> str:
        # model ids are storage-generated hex strings; refuse anything that
        # could escape the directory
        if not model_id or any(c in model_id for c in "/\\\0") or ".." in model_id:
            raise ValueError(f"Invalid model id {model_id!r}")
        return os.path.join(self.directory, f"{model_id}.model")

    def insert(self, model: Model) -> None:
        path = self._path(model.id)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            # mkstemp creates 0600; widen to umask-honoring 0666&~umask so
            # a deploy process under another user/group can read the blob
            os.fchmod(fd, 0o666 & ~_current_umask())
            with os.fdopen(fd, "wb") as f:
                f.write(model.models)
                f.flush()
                os.fsync(f.fileno())  # rename must land on durable data
            os.replace(tmp, path)
            # fsync the directory too, else the rename itself can be lost
            # on power failure
            dirfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, model_id: str) -> Optional[Model]:
        path = self._path(model_id)
        try:
            with open(path, "rb") as f:
                return Model(id=model_id, models=f.read())
        except FileNotFoundError:
            return None

    def delete(self, model_id: str) -> bool:
        try:
            os.unlink(self._path(model_id))
            return True
        except FileNotFoundError:
            return False


class LocalFSBackend(base.StorageBackend):
    """Models-only storage source (type "localfs")."""

    def __init__(self, directory: str):
        # resolve + create once, so a relative PATH binds to the CWD at
        # construction (not at each models() call) and repos share one store
        self._models = LocalFSModels(directory)
        self.directory = self._models.directory

    def _unsupported(self, repo: str):
        raise NotImplementedError(
            f"The localfs backend only provides model blobs; wire {repo} to "
            "a sqlite/memory source (PIO_STORAGE_REPOSITORIES_*_SOURCE).")

    def apps(self):
        self._unsupported("apps")

    def access_keys(self):
        self._unsupported("access_keys")

    def channels(self):
        self._unsupported("channels")

    def engine_instances(self):
        self._unsupported("engine_instances")

    def evaluation_instances(self):
        self._unsupported("evaluation_instances")

    def models(self) -> LocalFSModels:
        return self._models

    def events(self):
        self._unsupported("events")
