"""Second model family on the online plane: session folds.

The FoldModel extraction (`online/foldin.py`) exists so the tailer →
fold → delta-swap → invalidate loop serves more than ALS. This module
is the receipt for the sessionrec side:

- SessionFold math — a fold rebuilds the dirty users' windows from
  their FULL keep-last history under the canonical `recent_window`
  rule, recomputes the pooled session embedding bitwise, never mutates
  the input model, drops (and counts) cold items, and is idempotent
  under replay — the property that makes the tailer's at-least-once
  batch mode safe without any session-specific machinery.
- End to end — a trained sessionrec engine behind a live OnlinePlane
  resolves a SessionFold handle (and an empty ALS compat view); fresh
  view events reach the served windows in one poll and the user's
  `/queries.json` answer reflects them; the per-family freshness
  histogram gains sessionrec observations; a crash between fold and
  watermark replays to a bit-identical window, embedding, and scores.
"""

import contextlib
import os
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.models.session_model import (
    SessionRecModel,
    recent_window,
)
from predictionio_tpu.online import ALSFold, FoldModel, OnlineConfig, \
    SessionFold
from predictionio_tpu.online.metrics import (
    ONLINE_FAMILY_FRESHNESS,
    SESSION_COLD_ITEMS,
    SESSION_WINDOWS_FOLDED,
)
from predictionio_tpu.storage.base import App
from predictionio_tpu.utils.faults import FaultInjected
from predictionio_tpu.workflow.create_server import (
    PredictionServer,
    ServerConfig,
)

T0 = datetime(2026, 3, 1, tzinfo=timezone.utc)


def _view(user, item, t):
    return Event(event="view", entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties=DataMap({}), event_time=t)


def ingest_views(storage, n_users=6, n_items=8, per_user=4):
    """Rotating runs of views per user, strictly time-ordered."""
    app_id = storage.meta_apps().insert(App(id=0, name="SessApp"))
    le = storage.l_events()
    for u in range(n_users):
        for k in range(per_user):
            le.insert(_view(f"u{u}", f"i{(u + k) % n_items}",
                            T0 + timedelta(minutes=k)), app_id)
    return app_id


def train_session_variant(storage, epochs=4):
    from predictionio_tpu.controller import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    from predictionio_tpu.workflow.workflow_utils import (
        EngineVariant,
        extract_engine_params,
        get_engine,
    )

    variant = EngineVariant.from_dict({
        "id": "sess-test",
        "engineFactory": ("predictionio_tpu.templates.sessionrec."
                          "SessionRecEngine"),
        "datasource": {"params": {"appName": "SessApp"}},
        "algorithms": [{"name": "attention", "params": {
            "embedDim": 8, "numBlocks": 1, "numHeads": 2, "maxSeqLen": 16,
            "epochs": epochs, "stepSize": 0.05, "seed": 1}}],
    })
    engine = get_engine(variant.engine_factory)
    ep = extract_engine_params(engine, variant)
    CoreWorkflow.run_train(engine, ep, variant,
                           WorkflowContext(storage=storage, seed=1))
    return variant


@contextlib.contextmanager
def session_server(storage, **online_kw):
    config = ServerConfig(ip="127.0.0.1", port=0, engine_id="sess-test",
                          engine_variant="sess-test")
    server = PredictionServer(config, storage, plugins=None,
                              online=OnlineConfig(**online_kw))
    try:
        yield server
    finally:
        server.shutdown()


def _tiny_model():
    # 4 trained items + the pad row, 3-dim embeddings, no jax needed
    emb = np.arange(15, dtype=np.float32).reshape(5, 3)
    return SessionRecModel(
        params={"emb": emb},
        item_ids=BiMap.string_int([f"i{k}" for k in range(4)]),
        user_windows={}, session_vecs={}, max_seq_len=3, n_heads=1)


class TestRecentWindow:
    """The ONE rule training and the online fold must share."""

    def test_keep_last_and_time_order(self):
        pairs = [("a", T0), ("b", T0 + timedelta(seconds=1)),
                 ("a", T0 + timedelta(seconds=2))]
        # a's position is its LATEST event: it moves behind b
        assert recent_window(pairs, 10) == ["b", "a"]

    def test_caps_to_most_recent(self):
        pairs = [(f"x{k}", T0 + timedelta(seconds=k)) for k in range(5)]
        assert recent_window(pairs, 3) == ["x2", "x3", "x4"]

    def test_arrival_order_is_irrelevant(self):
        pairs = [("a", T0), ("b", T0 + timedelta(seconds=1)),
                 ("c", T0 + timedelta(seconds=2))]
        shuffled = [pairs[2], pairs[0], pairs[1]]
        assert recent_window(pairs, 10) == recent_window(shuffled, 10)

    def test_time_ties_break_by_item_id(self):
        assert recent_window([("b", T0), ("a", T0)], 10) == ["a", "b"]


class TestSessionFold:
    def test_is_a_fold_model(self):
        assert issubclass(SessionFold, FoldModel)
        assert SessionFold.family == "sessionrec"
        assert ALSFold.family == "als"

    def test_fold_rebuilds_window_and_embedding(self):
        m = _tiny_model()
        hist = {"u1": [("i0", 1.0, T0),
                       ("i2", 1.0, T0 + timedelta(seconds=2)),
                       ("i1", 1.0, T0 + timedelta(seconds=1))]}
        folded, stats = SessionFold(max_seq_len=3).fold(m, hist)
        assert folded is not m and m.user_windows == {}  # input untouched
        assert folded.user_windows["u1"] == ("i0", "i1", "i2")
        assert np.array_equal(folded.session_vecs["u1"],
                              m.session_vec_of(("i0", "i1", "i2")))
        assert stats.folded_users == 1 and stats.new_items == 0

    def test_rewatched_item_moves_to_the_end(self):
        m = _tiny_model()
        hist = {"u1": [("i0", 1.0, T0),
                       ("i1", 1.0, T0 + timedelta(seconds=1)),
                       ("i2", 1.0, T0 + timedelta(seconds=2)),
                       ("i0", 1.0, T0 + timedelta(seconds=3))]}
        folded, _ = SessionFold(max_seq_len=3).fold(m, hist)
        assert folded.user_windows["u1"] == ("i1", "i2", "i0")

    def test_cold_items_dropped_and_counted(self):
        m = _tiny_model()
        base = SESSION_COLD_ITEMS.value
        hist = {"u1": [("i1", 1.0, T0),
                       ("never-trained", 1.0, T0 + timedelta(seconds=1))]}
        folded, stats = SessionFold(max_seq_len=3).fold(m, hist)
        assert folded.user_windows["u1"] == ("i1",)
        assert stats.new_items == 1
        assert SESSION_COLD_ITEMS.value == base + 1

    def test_replay_is_bit_identical(self):
        # at-least-once safety: re-applying the same history is a no-op
        # because the fold recomputes from keep-last state, not appends
        m = _tiny_model()
        hist = {"u1": [("i3", 1.0, T0), ("i0", 1.0, T0)]}
        fold = SessionFold(max_seq_len=3)
        once, _ = fold.fold(m, hist)
        twice, _ = fold.fold(once, hist)
        assert twice.user_windows["u1"] == once.user_windows["u1"]
        assert np.array_equal(twice.session_vecs["u1"],
                              once.session_vecs["u1"])

    def test_untouched_users_keep_their_state(self):
        m = _tiny_model()
        first, _ = SessionFold(3).fold(m, {"u1": [("i0", 1.0, T0)]})
        second, _ = SessionFold(3).fold(first, {"u2": [("i1", 1.0, T0)]})
        assert second.user_windows["u1"] == first.user_windows["u1"]
        assert second.session_vecs["u1"] is first.session_vecs["u1"]


class TestSessionPlaneEndToEnd:
    def test_view_events_fold_to_servable(self, memory_storage):
        app_id = ingest_views(memory_storage)
        train_session_variant(memory_storage)
        folded_base = SESSION_WINDOWS_FOLDED.value
        ch = ONLINE_FAMILY_FRESHNESS.labels(family="sessionrec")
        obs_base = ch.count
        with session_server(memory_storage, interval_s=0.05) as server:
            server.online.stop()  # drive polls by hand
            ctx = server.online._contexts[0]
            handles = [h for _, h in ctx.folds]
            assert any(isinstance(h, SessionFold) for h in handles)
            assert ctx.als == []  # compat view: no ALS arms here
            le = memory_storage.l_events()
            # event times must be live (ahead of the tailer's since-
            # training watermark), strictly ordered to pin the window
            now = datetime.now(timezone.utc)
            for j, item in enumerate(("i1", "i3", "i5")):
                le.insert(_view("fresh-u", item,
                                now + timedelta(milliseconds=j)), app_id)
            assert server.online.poll_once() > 0
            model = server._states["sess-test"].models[0]
            assert model.user_windows["fresh-u"] == ("i1", "i3", "i5")
            assert np.array_equal(
                model.session_vecs["fresh-u"],
                model.session_vec_of(("i1", "i3", "i5")))
            result, _ = server.serving.handle_query(
                {"user": "fresh-u", "num": 3}, {})
            scores = result.get("itemScores")
            assert scores, "fresh session user should be servable"
            # seen-exclusion reflects the freshly folded window
            assert all(s["item"] not in ("i1", "i3", "i5") for s in scores)
        assert SESSION_WINDOWS_FOLDED.value > folded_base
        assert ch.count > obs_base  # per-family slice observed

    def test_crash_replay_is_bit_identical(self, memory_storage):
        app_id = ingest_views(memory_storage)
        train_session_variant(memory_storage)
        prev = os.environ.get("PIO_FAULTS")
        try:
            with session_server(memory_storage, interval_s=0.05) as server:
                server.online.stop()
                le = memory_storage.l_events()
                server.online.poll_once()  # drain any startup backlog
                now = datetime.now(timezone.utc)
                for j, item in enumerate(("i2", "i4", "i6")):
                    le.insert(_view("crash-u", item,
                                    now + timedelta(milliseconds=j)),
                              app_id)
                os.environ["PIO_FAULTS"] = "online.pre_watermark=error"
                with pytest.raises(FaultInjected):
                    server.online.poll_once()
                model = server._states["sess-test"].models[0]
                window = model.user_windows.get("crash-u")
                assert window == ("i2", "i4", "i6")  # fold landed pre-crash
                vec = np.array(model.session_vecs["crash-u"], copy=True)
                scores0, _ = server.serving.handle_query(
                    {"user": "crash-u", "num": 3}, {})
                os.environ.pop("PIO_FAULTS", None)
                assert server.online.poll_once() > 0  # unacked replays
                model2 = server._states["sess-test"].models[0]
                assert model2.user_windows["crash-u"] == window
                assert np.array_equal(model2.session_vecs["crash-u"], vec)
                scores1, _ = server.serving.handle_query(
                    {"user": "crash-u", "num": 3}, {})
                assert scores0 == scores1
                assert server.online.poll_once() == 0  # nothing left
        finally:
            if prev is None:
                os.environ.pop("PIO_FAULTS", None)
            else:
                os.environ["PIO_FAULTS"] = prev


class TestSessionTelemetry:
    def test_session_families_render(self):
        from predictionio_tpu.telemetry.registry import REGISTRY

        text = REGISTRY.render()
        for family in ("online_family_event_to_servable_seconds",
                       "session_windows_folded_total",
                       "session_cold_items_total"):
            assert f"# TYPE {family} " in text
