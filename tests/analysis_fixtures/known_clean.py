"""Fixture: the same shape as known_racy, kept legal.

Every read-modify-write holds the lock; the request-side deque append
is a single GIL-atomic mutation (the deferred-bookkeeper pattern the
race rule must NOT outlaw).
"""

import threading
from collections import deque


class CleanWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._pending = deque()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._lock:
                self.count += 1

    def submit(self, item):
        self._pending.append(item)

    def drain(self):
        with self._lock:
            while self._pending:
                self._pending.popleft()
            self.count += 1
