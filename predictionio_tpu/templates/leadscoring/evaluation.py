"""Lead Scoring evaluation: AUC over k session folds, across a small
regularization grid (the upstream template evaluates its forest with
MLlib's BinaryClassificationMetrics [U]; here AUC lives in the metric
zoo — controller/metrics.AUC)."""

from __future__ import annotations

from predictionio_tpu.controller import (
    AUC,
    EngineParams,
    Evaluation,
    EngineParamsGenerator as BaseGenerator,
)
from predictionio_tpu.templates.leadscoring.engine import (
    DataSourceParams,
    LeadScoringEngine,
    LeadScoringParams,
)


class RegGridGenerator(BaseGenerator):
    """Grid over regParam — subclass or construct with your own values."""

    def __init__(self, app_name: str, eval_k: int = 3,
                 reg_params=(0.001, 0.01, 0.1)):
        self.engine_params_list = [
            EngineParams(
                data_source_params=DataSourceParams(appName=app_name,
                                                    evalK=eval_k),
                algorithm_params_list=[
                    ("leadscoring", LeadScoringParams(regParam=r))],
            )
            for r in reg_params
        ]


class LeadScoringEvaluation(Evaluation, RegGridGenerator):
    """CLI entry point (`pio eval ...leadscoring.evaluation.
    LeadScoringEvaluation`): app name from PIO_EVAL_APP_NAME (default
    "MyApp1"), same convention as the Recommendation evaluation."""

    engine = LeadScoringEngine().apply()

    def __init__(self):
        import os

        self.metric = AUC()
        RegGridGenerator.__init__(
            self, os.environ.get("PIO_EVAL_APP_NAME", "MyApp1"),
            eval_k=int(os.environ.get("PIO_EVAL_K", "3")))
