"""Runtime control plane: the supervisor that owns the worker-pool
lifecycle (autoscaling, rolling deploys, self-healing) and the chaos
gate that drills it."""

from predictionio_tpu.runtime.supervisor import (  # noqa: F401
    Supervisor,
    SupervisorConfig,
    run_worker_pool,
)
