"""Compute kernels: jitted XLA programs + Pallas kernels for the hot ops.

This is the rebuild's replacement for Spark MLlib (SURVEY.md §2.5): where
the reference calls `ALS.train`, `NaiveBayes.train`,
`LogisticRegressionWithSGD`, `Word2Vec.fit` on RDDs, these modules build
the same math as mesh-sharded XLA programs (einsum/solve on the MXU,
psum/all_gather over ICI).
"""
