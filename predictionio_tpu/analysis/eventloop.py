"""Rule pack (b): the event-loop blocking-call rule.

The selector transport (utils/httploop.py) runs routes registered
``blocking=False`` (the default) INLINE on the loop thread: one slow
call there stalls every connection the process owns. Routes doing real
work must register ``blocking=True`` to run on the worker pool.

The rule finds, per module, every non-blocking Router registration,
resolves the handler and its same-module call closure, and flags any
reachable call that can block:

- ``time.sleep``, ``subprocess.*``, ``os.fsync``/``fdatasync``/
  ``os.system``
- sqlite/DB-API surface: ``.execute``/``.executemany``/
  ``.executescript``/``.commit``/``.fetchall``/``.fetchone``
- blocking socket/HTTP calls: ``.sendall``, ``urlopen``,
  ``http.client`` requests via ``.getresponse``
- the storage accessors (``l_events``/``meta_apps``/
  ``meta_access_keys``/``meta_channels``/``p_events``) — each returns a
  sqlite-backed DAO, so touching one from the loop thread puts disk I/O
  on the event loop (the auth path's access-key lookup is the classic
  miss).

The loop driver itself (any function calling ``.select(...)``) and its
closure are held to the same list, so loop-internal helpers can't grow
a blocking call either.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.engine import Finding, Project, rule

# module-qualified calls that block: (module name, attr) — None attr
# matches any attribute of the module
_MODULE_CALLS = {
    ("time", "sleep"),
    ("os", "fsync"),
    ("os", "fdatasync"),
    ("os", "system"),
    ("subprocess", None),
    ("shutil", "copytree"),
}
# DB-API / blocking-socket method names (on any object)
_BLOCKING_ATTRS = {
    "execute", "executemany", "executescript", "commit", "fetchall",
    "fetchone", "sendall", "getresponse",
}
# storage accessors returning sqlite-backed DAOs
_STORAGE_ACCESSORS = {
    "l_events", "p_events", "meta_apps", "meta_access_keys",
    "meta_channels",
}
_BARE_CALLS = {"urlopen"}


def _blocking_calls(fn: ast.AST) -> List[Tuple[int, str]]:
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                for mod_name, attr in _MODULE_CALLS:
                    if f.value.id == mod_name and attr in (None, f.attr):
                        hits.append((node.lineno, f"{mod_name}.{f.attr}"))
                        break
                else:
                    if f.attr in _BLOCKING_ATTRS:
                        hits.append((node.lineno, f".{f.attr}()"))
                    elif f.attr in _STORAGE_ACCESSORS:
                        hits.append(
                            (node.lineno,
                             f".{f.attr}() (sqlite-backed storage)"))
            elif f.attr in _BLOCKING_ATTRS:
                hits.append((node.lineno, f".{f.attr}()"))
            elif f.attr in _STORAGE_ACCESSORS:
                hits.append(
                    (node.lineno, f".{f.attr}() (sqlite-backed storage)"))
        elif isinstance(f, ast.Name) and f.id in _BARE_CALLS:
            hits.append((node.lineno, f"{f.id}()"))
    return hits


def _loop_drivers(tree: ast.AST) -> List[ast.AST]:
    """Functions that drive a selector loop (call ``.select(...)``)."""
    out = []
    for name, fn in astutil.function_defs(tree).items():
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "select"):
                out.append(fn)
                break
    return out


def _fn_name(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


@rule("loop-blocking-call",
      "non-blocking route handlers and the selector loop must not "
      "reach blocking calls (sqlite, sleep, fsync, subprocess, "
      "sendall)")
def loop_blocking_call(project: Project) -> Iterable[Finding]:
    for mod in project.modules():
        if mod.tree is None:
            continue
        tree = mod.tree
        defs = astutil.function_defs(tree)
        seen: Set[Tuple[int, str]] = set()

        def _flag(root_desc: str, roots: List[ast.AST],
                  symbol: str) -> Iterable[Finding]:
            for fn in astutil.reachable_functions(tree, roots):
                for lineno, what in _blocking_calls(fn):
                    if (lineno, what) in seen:
                        continue
                    seen.add((lineno, what))
                    yield Finding(
                        "loop-blocking-call", mod.rel, lineno,
                        f"{_fn_name(fn)}() (reachable from {root_desc}) "
                        f"calls {what} on the event-loop thread — one "
                        f"slow call here stalls every connection",
                        symbol=symbol,
                        hint="register the route blocking=True (worker "
                             "pool) or move the call off the loop "
                             "thread")

        for reg in astutil.registration_details(tree):
            if reg.blocking:
                continue
            handler = reg.handler_node
            roots: List[ast.AST]
            if isinstance(handler, ast.Lambda):
                roots = [handler]
            elif reg.handler_name in defs:
                roots = [defs[reg.handler_name]]
            else:
                continue
            yield from _flag(
                f"non-blocking route {reg.method} {reg.path}", roots,
                symbol=f"{reg.method} {reg.path}")
        drivers = _loop_drivers(tree)
        if drivers:
            yield from _flag(
                f"the selector loop ({', '.join(sorted(_fn_name(d) for d in drivers))})",
                drivers, symbol="<loop>")
