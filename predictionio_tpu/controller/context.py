"""WorkflowContext — the rebuild's SparkContext analogue.

Parity with «core/.../workflow/WorkflowContext» (SURVEY.md §2.1 [U]): where
the reference builds a `SparkConf`/`SparkContext` and threads it through
every DASE call, we thread a context carrying the JAX device mesh, a PRNG
seed, workflow params, and storage access. jax is imported lazily so
storage-only processes (event server, CLI metadata verbs) never pay for it.
"""

from __future__ import annotations

import contextlib
import logging
from typing import TYPE_CHECKING, Any, Optional

if TYPE_CHECKING:
    import jax

log = logging.getLogger(__name__)


class WorkflowContext:
    def __init__(
        self,
        mesh_shape: Optional[dict[str, int]] = None,
        seed: int = 0,
        batch: str = "",
        verbose: int = 0,
        storage: Optional[Any] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        metrics: Optional[Any] = None,
    ):
        """Args:
        mesh_shape: axis name → size, e.g. ``{"data": 4, "model": 2}``.
            None = use all local devices on the ``data`` axis.
        seed: base PRNG seed for all algorithms in this run.
        batch: human-readable run label (the reference's `--batch`).
        verbose: debug verbosity (the reference's WorkflowParams.verbose).
        storage: Storage registry override (defaults to the process one).
        checkpoint_dir: when set, algorithms checkpoint trainer state here
            every `checkpoint_every` of their own step unit (ALS: epochs;
            W2V/LogReg: scan iterations) and resume from the latest step
            on re-run (SURVEY.md §5 'Checkpoint / resume').
        checkpoint_every: None = each algorithm picks its own default
            (ALS every epoch; step-loop trainers ~10 saves per run —
            `checkpoint_every_or`); an explicit value applies verbatim.
        metrics: a `utils.profiling.MetricsLogger` for per-epoch metric
            emission (default: log-only).
        """
        self.mesh_shape = mesh_shape
        self.seed = seed
        self.batch = batch
        self.verbose = verbose
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        # set by Engine.train/eval around each algorithm's train: ".N"
        # for the N-th duplicate of an algorithm class in one engine, so
        # same-class entries (legal in engine.json, «algorithmClassMap»
        # [U]) don't share — and purge — one checkpoint subdir
        self.algo_ckpt_suffix = ""
        self._metrics = metrics
        self._storage = storage
        self._mesh: Optional["jax.sharding.Mesh"] = None

    @property
    def metrics(self):
        if self._metrics is None:
            from predictionio_tpu.utils.profiling import NullMetricsLogger

            self._metrics = NullMetricsLogger()
        return self._metrics

    def checkpoint_every_or(self, default: int) -> int:
        """`--checkpoint-every` when the user passed one, else the
        algorithm's own sensible default (its step unit varies: ALS
        epochs are seconds each so every-1 is right; a 200-iteration
        Adam scan at every-1 would be 200 dispatches + saves)."""
        return self.checkpoint_every if self.checkpoint_every else default

    @contextlib.contextmanager
    def algo_checkpoint_scope(self, suffix: str):
        """Scoped override of `algo_ckpt_suffix` — the ONE way callers
        that train algorithm instances mark which instance is running,
        so collision-freedom is structural rather than a set/reset pair
        every site must remember."""
        prev = self.algo_ckpt_suffix
        self.algo_ckpt_suffix = suffix
        try:
            yield
        finally:
            self.algo_ckpt_suffix = prev

    def algorithm_checkpoint_dir(self, algo_name: str) -> Optional[str]:
        """Per-algorithm checkpoint subdirectory (None when disabled).
        `algo_name` is the algorithm's own tag (an algorithm may use
        several — the text template checkpoints `w2v` and `w2v-head`);
        `algo_ckpt_suffix` disambiguates duplicate same-class entries."""
        if not self.checkpoint_dir:
            return None
        import os

        return os.path.join(self.checkpoint_dir,
                            algo_name + self.algo_ckpt_suffix)

    def algorithm_cache_dir(self, algo_name: str) -> Optional[str]:
        """Per-algorithm on-disk cache directory for derived training
        inputs (e.g. the ALS bucketize result — VERDICT r2 #5). Lives
        under the storage basedir so re-running `pio train` in a fresh
        process hits it; PIO_BUCKET_CACHE=0 disables."""
        import os

        from predictionio_tpu.utils.fs import fs_basedir

        if os.environ.get("PIO_BUCKET_CACHE", "1") == "0":
            return None
        return os.path.join(fs_basedir(), "cache", algo_name)

    @property
    def storage(self):
        if self._storage is None:
            from predictionio_tpu.storage.registry import Storage

            self._storage = Storage.get()
        return self._storage

    @property
    def mesh(self) -> "jax.sharding.Mesh":
        """The device mesh, built on first use (SURVEY.md §2.6/§2.7: axes
        `data` and `model` are the two parallelism dimensions PredictionIO
        capability parity needs).

        Shape resolution: the explicit `mesh_shape` (the `--mesh` flag),
        else `PIO_MESH_SHAPE` (the pod-level env contract in
        parallel/distributed.py — how config 5's data×model shape reaches
        `pio train` without per-command flags), else all devices on
        `data`."""
        if self._mesh is None:
            from predictionio_tpu.parallel.distributed import global_mesh

            self._mesh = global_mesh(self.mesh_shape)
        return self._mesh

    def rng(self, salt: int = 0) -> "jax.Array":
        import jax

        return jax.random.key(self.seed + salt)

    def __repr__(self) -> str:
        return (
            f"WorkflowContext(mesh_shape={self.mesh_shape}, seed={self.seed}, "
            f"batch={self.batch!r})"
        )
