"""Device-plane observability: jit-cache inventory with retrace blame,
per-dispatch device-time attribution, the capped-label guard on
metered_jit, fleet merge sum-exactness, the `/debug/profile/device.json`
delegation contract, the device-memory alert rule, and the
`coverage-jit-metering` lint rule. The live HTTP + 4-worker fleet drills
run in `quality.py --telemetry-gate`."""

import http.client
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from predictionio_tpu.telemetry import device
from predictionio_tpu.telemetry.device import (
    UNTRACKED_ROUTE,
    diff_signatures,
    merge_device,
    signature_of,
)
from predictionio_tpu.telemetry.registry import (
    LABEL_OVERFLOW,
    capped_label,
    reset_label_caps,
)


@pytest.fixture(autouse=True)
def _clean_device_state():
    device.reset_state()
    yield
    device.reset_state()


# -- abstract signatures and diffing ------------------------------------------

class TestSignatures:
    def test_arrays_become_dtype_bracket_dims(self):
        sig = signature_of((np.zeros((4, 8), np.float32),), None)
        assert sig == ("arg0:float32[4,8]",)

    def test_scalars_and_kwargs_sorted(self):
        sig = signature_of((True, 3, 2.5), {"b": "s", "a": None})
        assert sig == ("arg0:bool(True)", "arg1:int(3)", "arg2:float(2.5)",
                       "a=None", "b=str(s)")

    def test_dimension_level_blame_same_dtype_rank(self):
        old = signature_of((np.zeros((4, 8), np.float32),), None)
        new = signature_of((np.zeros((64, 8), np.float32),), None)
        assert diff_signatures(old, new) == ["arg0 dim0: 4→64"]

    def test_dtype_change_is_spec_level(self):
        old = signature_of((np.zeros((4,), np.float32),), None)
        new = signature_of((np.zeros((4,), np.int32),), None)
        assert diff_signatures(old, new) == ["arg0: float32[4]→int32[4]"]

    def test_added_and_removed_arguments(self):
        assert diff_signatures(("arg0:int(1)",),
                               ("arg0:int(1)", "arg1:int(2)")) == \
            ["arg1:int(2) added"]
        assert diff_signatures(("arg0:int(1)", "arg1:int(2)"),
                               ("arg0:int(1)",)) == ["arg1:int(2) removed"]

    def test_kwarg_value_change(self):
        old = signature_of((), {"k": 10})
        new = signature_of((), {"k": 20})
        assert diff_signatures(old, new) == ["k: int(10)→int(20)"]


# -- retrace blame on the serving bucket ladder (real metered_jit) ------------

class TestRetraceBlameOnBucketLadder:
    def test_third_tier_shape_is_blamed_and_counters_agree(self):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from predictionio_tpu.utils.profiling import JIT_COMPILES, metered_jit

        label = "test_device.ladder_score"
        score = metered_jit(lambda x: jnp.sum(x * 2.0), label=label)
        compiles_before = JIT_COMPILES.labels(fn=label).value

        # warm two bucket tiers, then dispatch a shape outside the ladder
        for rows in (4, 16):
            for _ in range(2):
                score(jnp.zeros((rows, 8), jnp.float32))
        with device.attribution("/queries.json", tier="64"):
            score(jnp.zeros((64, 8), jnp.float32))

        _status, body = device.jit_payload()
        fn = body["fns"][label]
        # the escaped shape must carry dimension-level blame
        blames = fn["retrace_blame"]
        assert blames, "no retrace blame recorded for the escaped shape"
        assert any("dim0" in c and "64" in c
                   for b in blames for c in b["changed"])
        # exact agreement: the /metrics counter and the inventory saw the
        # same compiles (3 tiers traced once each on this fresh label)
        compiled_delta = JIT_COMPILES.labels(fn=label).value \
            - compiles_before
        assert fn["compiles_total"] == compiled_delta == 3
        # two warm tiers then one escape: exactly 2 retraces (tier 2's
        # warm-up compile counts as one by design)
        assert fn["retraces_total"] == 2
        assert len(fn["signatures"]) == 3
        assert sum(s["dispatches"] for s in fn["signatures"]) == \
            fn["dispatches_total"] == 5

    def test_attribution_context_labels_the_route(self):
        t0 = time.perf_counter()
        with device.attribution("/queries.json", tier="16"):
            device.record_dispatch("test_device.attr", (1,), out=None,
                                   t0=t0, t1=t0 + 0.001)
        device.record_dispatch("test_device.attr", (1,), out=None,
                               t0=t0, t1=t0 + 0.001)
        _status, body = device.jit_payload()
        rows = {(r["route"], r["tier"]): r
                for r in body["device_attribution"]
                if r["fn"] == "test_device.attr"}
        assert ("/queries.json", "16") in rows
        assert (UNTRACKED_ROUTE, "") in rows
        assert rows[("/queries.json", "16")]["us"] >= 900


# -- capped labels (the metered_jit label-collision guard) --------------------

class TestCappedLabel:
    def test_overflow_collapses_after_cap(self):
        group = "test_device_cap"
        reset_label_caps(group)
        try:
            admitted = [capped_label(group, f"fn{i}", cap=4)
                        for i in range(6)]
            assert admitted[:4] == ["fn0", "fn1", "fn2", "fn3"]
            assert admitted[4] == admitted[5] == LABEL_OVERFLOW
            # values admitted before the cap keep stable identity forever
            assert capped_label(group, "fn1", cap=4) == "fn1"
        finally:
            reset_label_caps(group)

    def test_metered_jit_labels_ride_the_jit_fn_group(self):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from predictionio_tpu.utils.profiling import metered_jit

        # a runtime-value-minted label (the old score_topk_k{k} bug
        # shape) must resolve through the shared "jit_fn" cap group
        f = metered_jit(lambda x: x + 1, label="test_device.capped")
        f(jnp.zeros((2,), jnp.float32))
        _status, body = device.jit_payload()
        assert "test_device.capped" in body["fns"]
        assert capped_label("jit_fn", "test_device.capped") == \
            "test_device.capped"


# -- /debug/profile/device.json delegation (satellite: moved envelope) --------

class TestDeviceMemoryEndpoint:
    def test_503_envelope_without_jax(self):
        # the contract is per-process; this test process may have jax
        # loaded, so probe a fresh interpreter that never imports it
        code = (
            "import json, sys\n"
            "from predictionio_tpu.telemetry import device\n"
            "assert 'jax' not in sys.modules\n"
            "s, b = device.memory_payload()\n"
            "from predictionio_tpu.telemetry import profiler\n"
            "s2, b2 = profiler.device_payload()\n"
            "assert 'jax' not in sys.modules, 'delegate imported jax'\n"
            "print(json.dumps([s, b, s2, b2]))\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        status, body, status2, body2 = json.loads(out.stdout)
        assert status == status2 == 503
        assert body == body2 == {
            "status": 503, "error": "jax not loaded in this process"}

    def test_profiler_delegate_matches_device_impl(self):
        pytest.importorskip("jax")
        from predictionio_tpu.telemetry import profiler

        status, body = profiler.device_payload()
        assert status == 200
        assert "live_buffers" in body and "memory_stats" in body


# -- fleet merge sum-exactness ------------------------------------------------

class TestMergeDevice:
    def _state(self, route, us, n=3, fn="f", retraces=1):
        return {"attribution": [[route, fn, "8", "cpu", us, n]],
                "fns": {fn: {"compiles": 2, "dispatches": n,
                             "retraces": retraces}},
                "total_us": us, "clock_running": True}

    def test_totals_are_sum_exact_inside_one_payload(self):
        merged = merge_device([
            ("w0", self._state("/queries.json", 1500)),
            ("w1", self._state("/queries.json", 2500)),
            ("w2", self._state("/events.json", 7)),
        ])
        assert merged["fleet"] is True
        assert merged["total_us"] == 4007
        # exactness is checkable from the single payload
        assert merged["total_us"] == sum(merged["workers"].values())
        assert merged["workers"] == {"w0": 1500, "w1": 2500, "w2": 7}
        assert merged["routes"] == {"/queries.json": 4000,
                                    "/events.json": 7}
        assert merged["fns"]["f"] == {"compiles": 6, "dispatches": 9,
                                      "retraces": 3}
        assert merged["clocks_running"] == 3

    def test_dead_worker_merges_as_zero_not_crash(self):
        merged = merge_device([("w0", self._state("/q", 10)),
                               ("w1", None)])
        assert merged["workers"] == {"w0": 10, "w1": 0}
        assert merged["total_us"] == 10

    def test_attribution_rows_merge_by_full_key(self):
        a = self._state("/q", 100)
        merged = merge_device([("w0", a), ("w1", a)])
        rows = merged["attribution"]
        assert len(rows) == 1
        assert rows[0]["us"] == 200 and rows[0]["dispatches"] == 6

    def test_export_state_round_trips_through_merge(self):
        t0 = time.perf_counter()
        with device.attribution("/queries.json", tier="4"):
            device.record_dispatch("test_device.rt", (1,), out=None,
                                   t0=t0, t1=t0 + 0.002)
        st = device.export_state()
        merged = merge_device([("w0", st), ("w1", st)])
        assert merged["total_us"] == 2 * st["total_us"] > 0
        assert merged["total_us"] == sum(merged["workers"].values())


# -- the device-memory headroom alert rule ------------------------------------

class TestHeadroomAlertRule:
    def test_min_stat_reduces_to_most_constrained_device(self):
        from predictionio_tpu.telemetry.alerts import AlertRule
        from predictionio_tpu.telemetry.history import MetricsHistory
        from predictionio_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        g = reg.gauge("device_mem_headroom_ratio", "t",
                      labelnames=("device",))
        hist = MetricsHistory(reg, interval_s=1.0, window_s=600)
        rule = AlertRule(name="device-headroom-5m", kind="threshold",
                         metric="device_mem_headroom_ratio",
                         stat="min", op="<", value=0.10, window_s=300.0)
        # silent while the gauge family has no samples (CPU deployments)
        assert rule.measure(hist) is None
        for t in range(3):
            g.labels(device="tpu:0").set(0.50)
            g.labels(device="tpu:1").set(0.04)   # the constrained one
            hist.sample_now(now=1000.0 + t)
        measured = rule.measure(hist)
        # min-agg picks tpu:1, not the healthy tpu:0
        assert measured == pytest.approx(0.04)
        assert rule.breached(measured)

    def test_default_rules_ship_the_headroom_page(self):
        from predictionio_tpu.telemetry.alerts import default_rules

        rules = {r.name: r for r in default_rules()}
        rule = rules["device-headroom-5m"]
        assert rule.metric == "device_mem_headroom_ratio"
        assert (rule.stat, rule.op) == ("min", "<")
        assert rule.severity == "page"


# -- memory sampler gauges ----------------------------------------------------

class TestMemorySampler:
    def test_sample_folds_live_bytes_and_high_water(self):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        keep = jnp.ones((256, 4), jnp.float32)  # pin a live buffer
        sampler = device.MemorySampler(interval_s=60.0)
        live = sampler.sample_now()
        assert live, "no live devices despite a pinned buffer"
        dev, nbytes = next(iter(live.items()))
        assert nbytes > 0
        assert sampler.high_water[dev] >= nbytes
        del keep

    def test_empty_without_jax_loaded(self):
        code = (
            "import sys\n"
            "from predictionio_tpu.telemetry import device\n"
            "s = device.MemorySampler(interval_s=60.0)\n"
            "assert s.sample_now() == {}\n"
            "assert 'jax' not in sys.modules\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr


# -- /debug/jit.json over HTTP ------------------------------------------------

class TestJitRoute:
    def test_route_serves_inventory_and_clock_block(self):
        from predictionio_tpu.utils.http import (
            HttpService,
            JsonRequestHandler,
        )

        class _OkHandler(JsonRequestHandler):
            def do_GET(self):
                self.read_body()
                self.send_json(200, {"ok": True})

        t0 = time.perf_counter()
        device.record_dispatch("test_device.http", (1,), out=None,
                               t0=t0, t1=t0 + 0.001)
        svc = HttpService("127.0.0.1", 0, _OkHandler,
                          server_name="devtestsvc")
        svc.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                              timeout=10)
            conn.request("GET", "/debug/jit.json")
            resp = conn.getresponse()
            status, body = resp.status, json.loads(resp.read())
            conn.close()
        finally:
            svc.shutdown()
        assert status == 200
        assert "test_device.http" in body["fns"]
        assert body["totals"]["dispatches"] >= 1
        assert set(body["clock"]) == {"enabled", "running", "queue_depth",
                                      "backend"}


# -- the coverage-jit-metering lint rule --------------------------------------

class TestCoverageJitMeteringRule:
    def _findings(self, tmp_path, source):
        from predictionio_tpu.analysis import engine
        from predictionio_tpu.analysis.engine import Project

        (tmp_path / "mod.py").write_text(source)
        return engine.run_rules(Project(str(tmp_path)),
                                ["coverage-jit-metering"])

    def test_flags_bare_call_decorator_and_partial(self, tmp_path):
        findings = self._findings(tmp_path, (
            "import functools\n"
            "import jax\n"
            "from jax import jit, pjit\n"
            "f = jax.jit(lambda x: x)\n"
            "g = pjit(lambda x: x)\n"
            "@jit\n"
            "def h(x):\n"
            "    return x\n"
            "k = functools.partial(jax.jit, static_argnums=(0,))\n"
            "def ok(x):\n"
            "    return x\n"))
        lines = sorted(f.line for f in findings)
        assert lines == [4, 5, 6, 9]
        assert all(f.rule == "coverage-jit-metering" for f in findings)

    def test_metered_sites_and_suppressions_pass(self, tmp_path):
        findings = self._findings(tmp_path, (
            "import jax\n"
            "from predictionio_tpu.utils.profiling import metered_jit\n"
            "a = metered_jit(lambda x: x, label='m.a')\n"
            "b = jax.jit(lambda x: x)"
            "  # pio-lint: disable=coverage-jit-metering\n"))
        assert findings == []

    def test_repo_is_triaged_to_zero(self):
        from predictionio_tpu.analysis import engine
        from predictionio_tpu.analysis.engine import Project

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        proj = Project(repo_root, subdirs=("predictionio_tpu",))
        assert engine.run_rules(proj, ["coverage-jit-metering"]) == []
