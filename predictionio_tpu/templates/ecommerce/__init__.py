"""E-Commerce Recommendation template — implicit ALS + serve-time business
rules (seen/unavailable/category filters, cold-start via recent views).

Parity with the reference E-Commerce Recommendation template (SURVEY.md
§2.4 [U]); the serve-time `LEventStore` lookups are TTL-cached because they
sit on the query hot path (SURVEY.md §7.3).
"""

from predictionio_tpu.templates.ecommerce.engine import (
    DataSource,
    DataSourceParams,
    ECommAlgorithm,
    ECommAlgorithmParams,
    ECommerceEngine,
    ECommModelData,
    Preparator,
    PreparedData,
    Query,
    TrainingData,
)

__all__ = [
    "ECommerceEngine",
    "ECommAlgorithm",
    "ECommAlgorithmParams",
    "ECommModelData",
    "DataSource",
    "DataSourceParams",
    "Preparator",
    "PreparedData",
    "TrainingData",
    "Query",
]
