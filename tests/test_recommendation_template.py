"""Recommendation template end-to-end: events in storage → DASE train via
CoreWorkflow → model persistence → query serving — the §7.2 step-4
'minimum end-to-end slice' (SURVEY.md)."""

import json
from datetime import datetime, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.recommendation.RecommendationEngine"


def ingest_ratings(storage, app_name="RecApp", n_users=12, n_items=8, seed=0):
    """Block structure: even users love even items, odd users love odd."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    rng = np.random.default_rng(seed)
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    expected = {}
    for u in range(n_users):
        liked = [i for i in range(n_items) if i % 2 == u % 2]
        disliked = [i for i in range(n_items) if i % 2 != u % 2]
        # rotate the held-out liked item so every item is rated by someone
        holdout = liked[(u // 2) % len(liked)]
        for i in liked:
            if i == holdout:
                continue
            le.insert(Event(event="rate", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}",
                            properties=DataMap({"rating": 5.0}), event_time=t0),
                      app_id)
        for i in disliked[: len(disliked) // 2]:
            le.insert(Event(event="rate", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}",
                            properties=DataMap({"rating": 1.0}), event_time=t0),
                      app_id)
        expected[f"u{u}"] = f"i{holdout}"
    # one "buy" event (implicit 4.0 path)
    le.insert(Event(event="buy", entity_type="user", entity_id="u0",
                    target_entity_type="item", target_entity_id="i2",
                    event_time=t0), app_id)
    return expected


def variant_dict(app_name="RecApp", rank=4, iters=15):
    return {
        "id": "rec-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": {
            "rank": rank, "numIterations": iters, "lambda": 0.05, "seed": 1}}],
    }


class TestRecommendationEndToEnd:
    def test_train_and_recommend(self, memory_storage):
        expected = ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        # reload through the persistence path, as deploy would
        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        result = engine.predict(ep, models, {"user": "u0", "num": 3})
        items = [s["item"] for s in result["itemScores"]]
        assert len(items) == 3
        # scores sorted descending
        scores = [s["score"] for s in result["itemScores"]]
        assert scores == sorted(scores, reverse=True)
        # the held-out liked item should be the top recommendation
        assert items[0] == expected["u0"]
        # seen items are excluded
        seen_items = {f"i{i}" for i in range(8)} - {expected["u0"]}
        assert not (set(items) & seen_items) or items[0] == expected["u0"]

    def test_batch_predict_matches_predict_and_takes_device_branch(
            self, monkeypatch):
        """`pio batchpredict`'s bulk route (VERDICT r2 #4): one vectorized
        top-k equals the per-query loop, and past SERVE_HOST_MAX_BATCH
        users it actually dispatches the accelerator branch instead of
        host matvecs."""
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.models.als_model import ALSModel, SeenItems
        from predictionio_tpu.ops import ranking
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithm, ALSAlgorithmParams,
        )

        rng = np.random.default_rng(3)
        n_u, n_i = 100, 40  # > SERVE_HOST_MAX_BATCH users
        model = ALSModel(
            user_factors=rng.normal(size=(n_u, 8)).astype(np.float32),
            item_factors=rng.normal(size=(n_i, 8)).astype(np.float32),
            user_ids=BiMap.string_int([f"u{i}" for i in range(n_u)]),
            item_ids=BiMap.string_int([f"i{i}" for i in range(n_i)]),
            seen=SeenItems(np.arange(n_u, dtype=np.int32),
                           np.arange(n_u, dtype=np.int32) % n_i, n_u),
        )
        algo = ALSAlgorithm(ALSAlgorithmParams())

        device_batches = []
        real = ranking._topk_fn

        def spy(k, masked):
            fn = real(k, masked)

            def wrapped(u, items, *rest):
                device_batches.append(u.shape[0])
                return fn(u, items, *rest)

            return wrapped

        monkeypatch.setattr(ranking, "_topk_fn", spy)
        queries = ([{"user": f"u{i}", "num": 5} for i in range(n_u)]
                   + [{"user": "nobody", "num": 5}, {"user": "u0", "num": 2}])
        batch = algo.batch_predict(model, queries)
        assert device_batches and max(device_batches) \
            > ranking.SERVE_HOST_MAX_BATCH, device_batches

        monkeypatch.setattr(ranking, "_topk_fn", real)  # per-query = host
        for q, got in zip(queries, batch):
            want = algo.predict(model, q)
            # device (XLA) and host (BLAS) dots differ in last-ulp float;
            # items and order must agree, scores to tolerance
            assert [s["item"] for s in got["itemScores"]] \
                == [s["item"] for s in want["itemScores"]], q
            assert [s["score"] for s in got["itemScores"]] == pytest.approx(
                [s["score"] for s in want["itemScores"]], rel=1e-5), q

    def test_unknown_user_empty_result(self, memory_storage):
        ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models_list = engine.train(ctx, ep)
        result = engine.predict(ep, models_list, {"user": "ghost", "num": 3})
        assert result == {"itemScores": []}

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptyApp"))
        variant = EngineVariant.from_dict(variant_dict("EmptyApp"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no rating events"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)
        rows = memory_storage.meta_engine_instances().get_all()
        assert rows[0].status == "FAILED"

    def test_evaluation_with_map_metric(self, memory_storage):
        ingest_ratings(memory_storage, n_users=16, n_items=10)
        variant = EngineVariant.from_dict({
            "id": "rec-eval",
            "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "RecApp", "evalK": 3}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 8, "lambda": 0.05}}],
        })
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        from predictionio_tpu.controller import OptionAverageMetric
        from predictionio_tpu.controller.evaluation import Evaluation, MetricEvaluator
        from predictionio_tpu.ops.ranking import average_precision_at_k

        class MAPat10(OptionAverageMetric):
            def calculate(self, q, p, a):
                predicted = np.asarray(
                    [s["item"] for s in p["itemScores"]], dtype=object)
                return average_precision_at_k(predicted, set(a["items"]), 10)

        class RecEval(Evaluation):
            pass

        RecEval.engine = engine
        RecEval.metric = MAPat10()
        ctx = WorkflowContext(storage=memory_storage, seed=0)
        result = MetricEvaluator.evaluate(ctx, RecEval(), [ep])
        score = result.best.scores["MAPat10"]
        assert 0.0 <= score <= 1.0
        assert not np.isnan(score)

    def test_template_engine_json_parses(self):
        import os
        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "recommendation", "engine.json")
        from predictionio_tpu.workflow.workflow_utils import read_engine_json
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert ep.algorithm_params_list[0][1].lambda_ == 0.01
        assert ep.algorithm_params_list[0][1].rank == 10


def multi_algo_variant(app_name="RecApp", rank=4, iters=15,
                       weights=(0.8, 0.2)):
    return {
        "id": "rec-multi",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [
            {"name": "als", "params": {
                "rank": rank, "numIterations": iters, "lambda": 0.05,
                "seed": 1}},
            {"name": "popular", "params": {}},
        ],
        "serving": {"name": "weighted",
                    "params": {"weights": list(weights)}},
    }


class TestPopularityAlgorithm:
    def _pd(self):
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.templates.recommendation.engine import (
            PreparedData,
        )

        # i1 rated 3×, i2 2×, i0 1×; u0 has seen i1
        user_idx = np.asarray([0, 1, 2, 1, 2, 2], dtype=np.int32)
        item_idx = np.asarray([1, 1, 1, 2, 2, 0], dtype=np.int32)
        ratings = np.asarray([5, 4, 3, 2, 1, 5], dtype=np.float32)
        return PreparedData(
            user_ids=BiMap.string_int(["u0", "u1", "u2"]),
            item_ids=BiMap.string_int(["i0", "i1", "i2"]),
            user_idx=user_idx, item_idx=item_idx, ratings=ratings)

    def _algo(self, **params):
        from predictionio_tpu.templates.recommendation.engine import (
            PopularityAlgorithm, PopularityParams,
        )

        return PopularityAlgorithm(PopularityParams(**params))

    def test_ranks_by_count_and_excludes_seen(self):
        from predictionio_tpu.controller import WorkflowContext

        model = self._algo().train(WorkflowContext(), self._pd())
        # unknown user: pure global popularity
        recs = model.recommend("stranger", 3)
        assert [i for i, _ in recs] == ["i1", "i2", "i0"]
        assert [s for _, s in recs] == [3.0, 2.0, 1.0]
        # u0 has seen i1 — excluded
        assert [i for i, _ in model.recommend("u0", 3)] == ["i2", "i0"]

    def test_weight_by_rating(self):
        from predictionio_tpu.controller import WorkflowContext

        model = self._algo(weightByRating=True).train(
            WorkflowContext(), self._pd())
        # mass: i1 = 5+4+3 = 12, i0 = 5, i2 = 2+1 = 3
        recs = model.recommend("stranger", 3)
        assert [i for i, _ in recs] == ["i1", "i0", "i2"]
        assert [s for _, s in recs] == [12.0, 5.0, 3.0]

    def test_predict_wire_shape(self):
        from predictionio_tpu.controller import WorkflowContext

        algo = self._algo()
        model = algo.train(WorkflowContext(), self._pd())
        out = algo.predict(model, {"user": "stranger", "num": 2})
        assert out == {"itemScores": [
            {"item": "i1", "score": 3.0}, {"item": "i2", "score": 2.0}]}


class TestWeightedServing:
    def _serving(self, weights=()):
        from predictionio_tpu.templates.recommendation.engine import (
            WeightedServing, WeightedServingParams,
        )

        return WeightedServing(WeightedServingParams(weights=list(weights)))

    def test_blends_normalized_scores(self):
        s = self._serving([0.5, 0.5])
        a = {"itemScores": [{"item": "x", "score": 10.0},
                            {"item": "y", "score": 0.0}]}
        b = {"itemScores": [{"item": "y", "score": 9.0},
                            {"item": "z", "score": 3.0}]}
        out = s.serve({"num": 3}, [a, b])
        # normalized: a → x=1, y=0; b → y=1, z=0
        assert out == {"itemScores": [
            {"item": "x", "score": 0.5}, {"item": "y", "score": 0.5},
            {"item": "z", "score": 0.0}]}

    def test_empty_prediction_contributes_nothing(self):
        """ALS on an unknown user returns [] — the blend must surface
        the baseline instead of failing or returning empty."""
        s = self._serving()
        out = s.serve({"num": 2}, [
            {"itemScores": []},
            {"itemScores": [{"item": "p", "score": 7.0},
                            {"item": "q", "score": 7.0}]}])
        # equal scores normalize to 1.0 each (span 0)
        assert out == {"itemScores": [
            {"item": "p", "score": 1.0}, {"item": "q", "score": 1.0}]}

    def test_weight_count_mismatch_fails_loudly(self):
        import pytest as _pytest

        s = self._serving([1.0])
        with _pytest.raises(ValueError, match="1 weights for 2"):
            s.serve({"num": 1}, [{"itemScores": []}, {"itemScores": []}])

    def test_weight_count_mismatch_fails_at_components_time(self):
        """A weights/algorithms mismatch must fail config extraction —
        at train/deploy entry — not 500 on every production query."""
        import pytest as _pytest

        variant = multi_algo_variant(weights=(0.8, 0.1, 0.1))
        engine = get_engine(variant["engineFactory"])
        ep = extract_engine_params(engine, EngineVariant.from_dict(variant))
        with _pytest.raises(ValueError, match="3 weights configured for 2"):
            engine.components(ep)


class TestMultiAlgorithmEngine:
    """VERDICT r4 missing #2: the multi-algorithm capability carried by
    a REAL shipped template — both models train, persist as one blob,
    and contribute to the served result."""

    def test_train_persists_both_models_and_blend_serves(
            self, memory_storage):
        from predictionio_tpu.models.als_model import ALSModel
        from predictionio_tpu.templates.recommendation.engine import (
            PopularityModel,
        )

        ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(multi_algo_variant())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert [n for n, _ in ep.algorithm_params_list] == ["als", "popular"]
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        assert len(models) == 2
        assert isinstance(models[0], ALSModel)
        assert isinstance(models[1], PopularityModel)

        # known user: blended result, descending, correct wire shape
        result = engine.predict(ep, models, {"user": "u0", "num": 3})
        scores = [s["score"] for s in result["itemScores"]]
        assert len(scores) == 3 and scores == sorted(scores, reverse=True)

        # unknown user: ALS contributes nothing, the popularity baseline
        # serves through the blend — the observable proof algorithm #2
        # reaches the served result (FirstServing returned [] here)
        cold = engine.predict(ep, models, {"user": "stranger", "num": 3})
        assert len(cold["itemScores"]) == 3

    def test_shipped_engine_json_is_multi_algorithm(self):
        import json as _json
        import pathlib as _pathlib

        ej = _json.loads((_pathlib.Path(
            "predictionio_tpu/templates/recommendation/engine.json"
        )).read_text())
        assert [a["name"] for a in ej["algorithms"]] == ["als", "popular"]
        assert ej["serving"]["name"] == "weighted"
        variant = EngineVariant.from_dict(ej)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)  # params typecheck
        assert ep.serving_name == "weighted"


class TestDuplicateAlgorithmCheckpoints:
    """Two entries of the SAME algorithm class in one engine (legal in
    engine.json, «algorithmClassMap» [U]) must not share a checkpoint
    subdir: without per-instance suffixes the second train's
    different-config fingerprint would purge the first's saves, silently
    degrading crash-resume to retrain-from-scratch."""

    def test_duplicate_class_checkpoints_do_not_collide(
            self, memory_storage, tmp_path):
        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        ingest_ratings(memory_storage)
        v = {
            "id": "rec-dup",
            "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "RecApp"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3,
                                           "lambda": 0.05, "seed": 1}},
                {"name": "als", "params": {"rank": 4, "numIterations": 5,
                                           "lambda": 0.2, "seed": 2}},
            ],
            "serving": {"name": "weighted",
                        "params": {"weights": [0.5, 0.5]}},
        }
        variant = EngineVariant.from_dict(v)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1,
                              checkpoint_dir=str(tmp_path),
                              checkpoint_every=1)
        models = engine.train(ctx, ep)
        assert len(models) == 2
        # each instance kept its own full checkpoint history
        assert CheckpointManager(str(tmp_path / "als")).latest_step() == 3
        assert CheckpointManager(str(tmp_path / "als.1")).latest_step() == 5
        assert ctx.algo_ckpt_suffix == ""  # reset after the loop

        # a re-run fully resumes BOTH instances (nothing was purged)
        again = engine.train(ctx, ep)
        for got, want in zip(again, models):
            np.testing.assert_allclose(got.user_factors, want.user_factors,
                                       rtol=1e-5, atol=1e-6)

    def test_eval_grid_keeps_duplicate_class_subdirs_separate(
            self, memory_storage, tmp_path):
        """The eval-grid sequential fallback runs under the same
        per-position suffixes Engine.train uses — positions 0 and 1 of
        a two-ALS engine must land in distinct subdirs. (WITHIN a
        position, per-ep cells still share that subdir last-writer-wins
        — pre-existing eval semantics, documented at the eval_grid
        suffix comment.) Cells get DIFFERENT ranks so no two batch:
        grid-batched cells deliberately skip checkpointing; the
        fallback is the checkpointing path."""
        from predictionio_tpu.workflow.checkpoint import CheckpointManager

        ingest_ratings(memory_storage)

        def ep_for(rank):
            v = {
                "id": "rec-dup-grid",
                "engineFactory": FACTORY,
                "datasource": {"params": {"appName": "RecApp", "evalK": 2}},
                "algorithms": [
                    {"name": "als", "params": {
                        "rank": rank, "numIterations": 3, "lambda": 0.05,
                        "seed": 1}},
                    {"name": "als", "params": {
                        "rank": rank, "numIterations": 3, "lambda": 0.05,
                        "seed": 2}},
                ],
                "serving": {"name": "weighted",
                            "params": {"weights": [0.5, 0.5]}},
            }
            variant = EngineVariant.from_dict(v)
            return get_engine(variant.engine_factory), \
                extract_engine_params(get_engine(variant.engine_factory),
                                      variant)

        engine, ep_a = ep_for(4)
        _, ep_b = ep_for(6)
        ctx = WorkflowContext(storage=memory_storage, seed=1,
                              checkpoint_dir=str(tmp_path),
                              checkpoint_every=1)
        results = engine.eval_grid(ctx, [ep_a, ep_b])
        assert results is not None and len(results) == 2
        assert ctx.algo_ckpt_suffix == ""
        # both positions checkpointed, into distinct namespaces
        assert CheckpointManager(str(tmp_path / "als")).latest_step() == 3
        assert CheckpointManager(str(tmp_path / "als.1")).latest_step() == 3
