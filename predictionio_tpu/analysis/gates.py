"""The three pre-existing ad-hoc AST gates, ported onto the engine.

Each rule reproduces its legacy gate's static scan byte-for-byte
(message text, sentinel checks, exemptions), so
``serving/gate.py``, ``ingest/gate.py`` and ``utils/hotpath_gate.py``
can delegate their static layer here — same CLI flags, same pass/fail
behavior — while the duplicated walk/resolve code lives in
:mod:`predictionio_tpu.analysis.astutil` only.

``legacy_lines()`` reconstructs the exact strings the old
``_static_scan()`` implementations printed: ``file:line: message`` when
a line is known, ``file: message`` for file-scoped findings, and the
bare message for project-scoped sentinels.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence

from predictionio_tpu.analysis import astutil
from predictionio_tpu.analysis.engine import (
    Finding,
    Project,
    rule,
    run_rules as engine_run_rules,
)

# -- shared helpers ---------------------------------------------------------


def _exempt(mod_rel: str, suffixes: Sequence[str]) -> bool:
    return any(mod_rel == s or mod_rel.endswith("/" + s) for s in suffixes)


def legacy_lines(findings: Iterable[Finding]) -> List[str]:
    out = []
    for f in findings:
        if f.line:
            out.append(f"{f.file}:{f.line}: {f.message}")
        elif f.file:
            out.append(f"{f.file}: {f.message}")
        else:
            out.append(f.message)
    return out


def run_legacy_static(rule_id: str, pkg_dir: str) -> List[str]:
    """The old per-gate ``_static_scan()`` surface: run one migrated
    rule over the package dir and return the legacy problem strings
    (file findings in scan order, project-scoped sentinels last, as the
    old scanners printed them)."""
    project = Project(pkg_dir)
    findings = engine_run_rules(project, [rule_id])
    return legacy_lines([f for f in findings if f.file]
                        + [f for f in findings if not f.file])


# -- hotpath: no bare json on the hot routes --------------------------------

_HOT_EXEMPT = ("utils/hotpath_gate.py",)
_HOT_ROUTES = (
    ("POST", "/queries.json"),
    ("POST", "/events.json"),
    ("POST", "/batch/events.json"),
)
_BARE_JSON = {"dumps", "loads"}


def _bare_json_calls(fn: ast.AST) -> list:
    """(lineno, name) for every `json.dumps(...)`/`json.loads(...)`
    call inside fn. fastjson.dumps/loads spell the module differently and
    don't match."""
    hits = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BARE_JSON
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "json"):
            hits.append((node.lineno, f"json.{node.func.attr}"))
    return hits


@rule("gate-hotpath-json",
      "hot-route handlers (and their same-module call closure) must "
      "use utils.fastjson, not bare json.dumps/loads")
def gate_hotpath_json(project: Project) -> Iterable[Finding]:
    found = 0
    for mod in project.modules():
        if _exempt(mod.rel, _HOT_EXEMPT):
            continue
        if mod.tree is None:
            yield Finding("gate-hotpath-json", mod.rel, 0,
                          f"unparseable ({mod.error})")
            continue
        for method, route in _HOT_ROUTES:
            handlers = astutil.handlers_for(mod.tree, route, method=method)
            if not handlers:
                continue
            found += 1
            for fn in astutil.reachable_functions(mod.tree, handlers):
                for lineno, name in _bare_json_calls(fn):
                    fn_name = getattr(fn, "name", "<lambda>")
                    yield Finding(
                        "gate-hotpath-json", mod.rel, lineno,
                        f"{fn_name} (reachable from "
                        f"{method} {route}) calls bare {name}() on the hot "
                        f"path — use utils.fastjson (bound encoder, cached "
                        f"envelopes) so encode cost and envelope bytes stay "
                        f"pinned",
                        symbol=fn_name,
                        hint="route the encode through utils.fastjson")
    if found < len(_HOT_ROUTES):
        # the gate must notice if the hot routes stop being resolvable —
        # an empty scan proves nothing
        yield Finding(
            "gate-hotpath-json", "", 0,
            f"static: only {found}/{len(_HOT_ROUTES)} hot routes "
            f"resolved to router-registered handlers; the hot-path gate "
            f"has nothing to hold",
            symbol="<sentinel>")


# -- serving: /queries.json must pass admission -----------------------------

_SERVING_EXEMPT = ("serving/gate.py",)
_QUERY_ROUTE = "/queries.json"
_DIRECT_DISPATCH = {"predict", "predict_batch"}
_PLANE_ENTRY = "handle_query"


def _contains_query_route(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == _QUERY_ROUTE:
            return True
    return False


def _scan_query_handler(fn: ast.FunctionDef, rel: str
                        ) -> Iterable[Finding]:
    calls = astutil.attr_calls(fn)
    if _PLANE_ENTRY not in calls:
        yield Finding(
            "gate-serving-admission", rel, fn.lineno,
            f"{fn.name} routes {_QUERY_ROUTE} without "
            f"calling the serving plane's {_PLANE_ENTRY}() — predict "
            f"requests must pass admission control",
            symbol=fn.name,
            hint="dispatch through ServingPlane.handle_query")
    direct = calls & _DIRECT_DISPATCH
    if direct:
        yield Finding(
            "gate-serving-admission", rel, fn.lineno,
            f"{fn.name} calls {sorted(direct)} directly "
            f"in the {_QUERY_ROUTE} handler — dispatch belongs behind "
            f"ServingPlane.{_PLANE_ENTRY} (queue bound, deadlines, shed)",
            symbol=fn.name,
            hint="remove the direct engine dispatch")


@rule("gate-serving-admission",
      "every /queries.json handler must go through "
      "ServingPlane.handle_query (admission control)")
def gate_serving_admission(project: Project) -> Iterable[Finding]:
    found_route = False
    for mod in project.modules():
        if _exempt(mod.rel, _SERVING_EXEMPT):
            continue
        if mod.tree is None:
            yield Finding("gate-serving-admission", mod.rel, 0,
                          f"unparseable ({mod.error})")
            continue
        # legacy transport: do_* methods with the route constant inline
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.FunctionDef)
                    and node.name.startswith("do_")
                    and _contains_query_route(node)):
                found_route = True
                yield from _scan_query_handler(node, mod.rel)
        # event-loop transport: resolve router.post("/queries.json", fn)
        # back to fn's FunctionDef and hold it to the same contract
        for handler in astutil.handlers_for(mod.tree, _QUERY_ROUTE,
                                            method="POST"):
            found_route = True
            if isinstance(handler, ast.FunctionDef):
                yield from _scan_query_handler(handler, mod.rel)
            else:
                yield Finding(
                    "gate-serving-admission", mod.rel, 0,
                    f"{_QUERY_ROUTE} is registered to a lambda — the "
                    f"predict handler must be a named function the gate can "
                    f"hold to the admission contract",
                    symbol="<lambda>",
                    hint="register a named handler function")
    if not found_route:
        # the gate must notice if the predict route itself disappears —
        # an empty scan proves nothing
        yield Finding(
            "gate-serving-admission", "", 0,
            f"static: no in-package handler routes {_QUERY_ROUTE}; "
            f"the serving gate has nothing to hold",
            symbol="<sentinel>")


# -- ingest: /events.json writes must use the write plane -------------------

_INGEST_EXEMPT = ("ingest/gate.py",)
_EVENTS_ROUTE = "/events.json"
_PLANE_ENTRIES = {"submit", "_insert_event"}


def _routes_single_events(fn: ast.AST) -> bool:
    """True when fn routes single-event POSTs: contains the /events.json
    constant (the batch route is a distinct constant and may also be
    present in the same do_POST — that's fine, we check the single-event
    funnel, not the batch path)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == _EVENTS_ROUTE:
            return True
    return False


@rule("gate-ingest-funnel",
      "every POST /events.json handler must funnel through "
      "_insert_event/submit (the group-commit write plane)")
def gate_ingest_funnel(project: Project) -> Iterable[Finding]:
    found_route = False
    found_funnel = False
    for mod in project.modules():
        if _exempt(mod.rel, _INGEST_EXEMPT):
            continue
        if mod.tree is None:
            yield Finding("gate-ingest-funnel", mod.rel, 0,
                          f"unparseable ({mod.error})")
            continue
        tree = mod.tree
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            # write handlers only: GET /events.json is the read/find route
            # and legitimately never touches the write plane
            if node.name in ("do_POST", "do_PUT") \
                    and _routes_single_events(node):
                found_route = True
                if not (_PLANE_ENTRIES & astutil.attr_calls(node)):
                    yield Finding(
                        "gate-ingest-funnel", mod.rel, node.lineno,
                        f"{node.name} routes "
                        f"{_EVENTS_ROUTE} without dispatching through the "
                        f"ingest write plane (_insert_event/submit) — "
                        f"single-event writes must get group commit and "
                        f"backpressure",
                        symbol=node.name,
                        hint="dispatch through _insert_event/submit")
        # event-loop transport: resolve router.post("/events.json", fn)
        # back to fn's FunctionDef and hold it to the same funnel
        # contract (POST only — GET /events.json is the read route)
        for handler in astutil.handlers_for(tree, _EVENTS_ROUTE,
                                            method="POST"):
            found_route = True
            if not isinstance(handler, ast.FunctionDef):
                yield Finding(
                    "gate-ingest-funnel", mod.rel, 0,
                    f"POST {_EVENTS_ROUTE} is registered to a lambda — "
                    f"the write handler must be a named function the gate "
                    f"can hold to the write-plane contract",
                    symbol="<lambda>",
                    hint="register a named handler function")
            elif not (_PLANE_ENTRIES & astutil.attr_calls(handler)):
                yield Finding(
                    "gate-ingest-funnel", mod.rel, handler.lineno,
                    f"{handler.name} routes "
                    f"{_EVENTS_ROUTE} without dispatching through the ingest "
                    f"write plane (_insert_event/submit) — single-event "
                    f"writes must get group commit and backpressure",
                    symbol=handler.name,
                    hint="dispatch through _insert_event/submit")
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name == "_insert_event":
                found_funnel = True
                calls = astutil.attr_calls(node)
                if "submit" not in calls:
                    yield Finding(
                        "gate-ingest-funnel", mod.rel, node.lineno,
                        f"_insert_event does not call "
                        f"the write plane's submit() — the 201 would not be "
                        f"group-committed or admission-bounded",
                        symbol="_insert_event",
                        hint="call GroupCommitWriter.submit")
                if "insert" in calls:
                    yield Finding(
                        "gate-ingest-funnel", mod.rel, node.lineno,
                        f"_insert_event calls a bare "
                        f"storage insert() — durable writes belong behind "
                        f"GroupCommitWriter.submit (coalescing, shed path)",
                        symbol="_insert_event",
                        hint="remove the bare insert")
    if not found_route:
        # the gate must notice if the ingest route itself disappears —
        # an empty scan proves nothing
        yield Finding(
            "gate-ingest-funnel", "", 0,
            f"static: no in-package handler routes {_EVENTS_ROUTE}; "
            f"the ingest gate has nothing to hold",
            symbol="<sentinel>")
    if found_route and not found_funnel:
        yield Finding(
            "gate-ingest-funnel", "", 0,
            "static: no in-package _insert_event funnel found; the "
            "single-event write path is unverifiable",
            symbol="<sentinel-funnel>")
