"""Cross-worker metric aggregation for SO_REUSEPORT pools.

The kernel balances *connections* across a pool's workers, so a
`/metrics` scrape of the shared port answers from ONE arbitrary worker
and under-reports every fleet counter N-fold. This module closes that
hole:

- Each worker runs a :class:`SnapshotServer` — a loopback socket that
  answers every connection with a JSON snapshot of the process registry
  and closes. The port rides to the supervisor in the worker's READY
  control message.
- The supervisor calls :func:`fetch_snapshot` per worker, merges with
  :func:`merge_snapshots` (counters and histogram buckets are summed
  exactly; gauges get a ``worker`` label so per-process points stay
  distinguishable), and serves the fleet view from its control
  endpoint's `/metrics` via :func:`render_merged`.

Worker identity comes from ``PIO_METRICS_WORKER_LABEL`` (the supervisor
sets ``slot<N>`` per child; standalone processes may set their own —
default ``pid<pid>``). Every process also exposes
``pio_worker{worker="…"} 1`` so even a direct scrape of the shared port
tells you *which* worker answered — the single-worker scrape is then at
least attributable for non-pool consumers.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, Iterable, List, Optional

from predictionio_tpu.telemetry.registry import (
    REGISTRY,
    Histogram,
    MetricsRegistry,
    _escape_help,
    _format_value,
    _render_labels,
)

SNAPSHOT_TIMEOUT_S = 2.0


def worker_label() -> str:
    """This process's identity in merged output (env override or pid)."""
    return os.environ.get("PIO_METRICS_WORKER_LABEL") or f"pid{os.getpid()}"


WORKER_INFO = REGISTRY.gauge(
    "pio_worker", "Identity of the process that answered this scrape",
    labelnames=("worker",))


def refresh_worker_info() -> None:
    """(Re)point the pio_worker info gauge at the current identity —
    called at import and after fork, when the pid (and the supervisor's
    per-slot label) change."""
    with WORKER_INFO._lock:
        WORKER_INFO._children.clear()
    WORKER_INFO.labels(worker=worker_label()).set(1)


def snapshot_registry(registry: MetricsRegistry = REGISTRY,
                      worker: Optional[str] = None,
                      refresh: bool = True) -> Dict:
    """JSON-serialisable snapshot of every family in the registry.

    ``refresh`` recomputes scrape-time gauges (SLO windows) first, same
    as the `/metrics` route does, so a merged view is as current as a
    direct scrape."""
    if refresh:
        from predictionio_tpu.telemetry import slo
        slo.refresh()
    families = []
    for m in registry.families():
        fam: Dict = {
            "name": m.name, "help": m.help, "type": m.type,
            "labelnames": list(m.labelnames),
        }
        if isinstance(m, Histogram):
            fam["buckets"] = list(m.buckets)
            fam["children"] = [[list(k), [counts, total, count]]
                               for k, (counts, total, count) in m.collect()]
            ex = m.collect_exemplars()
            if ex:
                fam["exemplars"] = [[list(k), slots] for k, slots in ex]
        else:
            fam["children"] = [[list(k), v] for k, v in m.collect()]
        families.append(fam)
    # The profiler's collapsed-stack state rides the same channel: one
    # fetch gives the supervisor both the metric merge and the fleet
    # flamegraph inputs, with no second socket or race between them.
    try:
        from predictionio_tpu.telemetry import profiler
        profile = profiler.export_state()
    except Exception:  # noqa: BLE001 — snapshots must not break on this
        profile = None
    # The lineage recorder's timelines + exact stage counts ride along the
    # same way, so the supervisor's fleet lineage view needs no extra hop.
    try:
        from predictionio_tpu.telemetry import lineage as _lineage
        lineage = _lineage.export_state()
    except Exception:  # noqa: BLE001 — snapshots must not break on this
        lineage = None
    # The device plane's microsecond attribution + jit-cache counts ride
    # along too — the supervisor's fleet device view is sum-exact because
    # these are the same integers the workers accumulated.
    try:
        from predictionio_tpu.telemetry import device as _device
        device = _device.export_state()
    except Exception:  # noqa: BLE001 — snapshots must not break on this
        device = None
    # The tenant meter's integer cells ride along so the fleet per-app
    # view merges sum-exact (sum over tenant labels == untagged totals).
    try:
        from predictionio_tpu.telemetry import tenant as _tenant
        tenant = _tenant.export_state()
    except Exception:  # noqa: BLE001 — snapshots must not break on this
        tenant = None
    return {"worker": worker or worker_label(), "pid": os.getpid(),
            "ts": time.time(), "families": families, "profile": profile,
            "lineage": lineage, "device": device, "tenant": tenant}


class SnapshotServer:
    """Loopback one-shot snapshot socket: connect → receive the JSON
    registry snapshot → EOF. Not HTTP — this is a private supervisor↔
    worker channel; the public `/metrics` stays on the shared port."""

    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 host: str = "127.0.0.1"):
        self._registry = registry
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(8)
        self.port: int = self._sock.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(
            target=self._serve, name="pio-metrics-snapshot", daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed
            try:
                conn.settimeout(SNAPSHOT_TIMEOUT_S)
                payload = json.dumps(
                    snapshot_registry(self._registry)).encode("utf-8")
                conn.sendall(payload)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def fetch_snapshot(port: int,
                   timeout_s: float = SNAPSHOT_TIMEOUT_S) -> Dict:
    """Pull one worker's snapshot off its loopback snapshot port."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        chunks: List[bytes] = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    return json.loads(b"".join(chunks).decode("utf-8"))


def merge_snapshots(snapshots: Iterable[Dict]) -> Dict:
    """Merge per-worker registry snapshots into one fleet view.

    Counters and histograms are summed per label set — the merged total
    is exactly the sum of the per-worker registries. Gauges are
    *points*, not flows: each series gains a ``worker`` label (unless
    the family already carries one) so nothing is averaged away.
    Histogram exemplars keep the newest exemplar per bucket fleet-wide.
    """
    merged: Dict[str, Dict] = {}
    workers: List[str] = []
    for snap in snapshots:
        wlabel = str(snap.get("worker", "?"))
        workers.append(wlabel)
        for fam in snap.get("families", ()):
            name = fam["name"]
            out = merged.get(name)
            if out is None:
                out = merged[name] = {
                    "name": name, "help": fam.get("help", ""),
                    "type": fam["type"],
                    "labelnames": tuple(fam.get("labelnames", ())),
                    "buckets": tuple(fam.get("buckets", ())),
                    "children": {}, "exemplars": {},
                }
                if (out["type"] == "gauge"
                        and "worker" not in out["labelnames"]):
                    out["labelnames"] = out["labelnames"] + ("worker",)
                    out["per_worker"] = True
                else:
                    out["per_worker"] = False
            elif (out["type"] != fam["type"]
                  or (not out["per_worker"]
                      and out["labelnames"] != tuple(
                          fam.get("labelnames", ())))):
                continue  # shape clash across workers: first shape wins
            children = out["children"]
            for rawkey, value in fam.get("children", ()):
                key = tuple(str(k) for k in rawkey)
                if out["per_worker"]:
                    children[key + (wlabel,)] = value
                elif out["type"] == "histogram":
                    counts, total, count = value
                    prev = children.get(key)
                    if prev is None:
                        children[key] = [list(counts), float(total),
                                         int(count)]
                    else:
                        for i, n in enumerate(counts):
                            prev[0][i] += n
                        prev[1] += total
                        prev[2] += count
                elif out["type"] == "counter":
                    children[key] = children.get(key, 0.0) + float(value)
                else:  # gauge that already carries a worker label
                    children[key] = float(value)
            for rawkey, slots in fam.get("exemplars", ()):
                key = tuple(str(k) for k in rawkey)
                prev = out["exemplars"].get(key)
                if prev is None:
                    out["exemplars"][key] = [tuple(e) if e else None
                                             for e in slots]
                else:
                    for i, e in enumerate(slots):
                        if e and (prev[i] is None or e[2] > prev[i][2]):
                            prev[i] = tuple(e)
    return {"workers": workers, "families": merged}


def render_merged(merged: Dict) -> str:
    """Prometheus text exposition of a merge_snapshots() result."""
    lines: List[str] = []
    for name in sorted(merged["families"]):
        fam = merged["families"][name]
        lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        labelnames = fam["labelnames"]
        if fam["type"] == "histogram":
            buckets = fam["buckets"]
            for key in sorted(fam["children"]):
                counts, total, count = fam["children"][key]
                slots = fam["exemplars"].get(key)
                cum = 0
                for i, (bound, n) in enumerate(zip(buckets, counts)):
                    cum += n
                    labels = _render_labels(
                        labelnames, key,
                        extra=[("le", _format_value(bound))])
                    lines.append(f"{name}_bucket{labels} {cum}"
                                 f"{_exemplar_suffix(slots, i)}")
                inf_labels = _render_labels(labelnames, key,
                                            extra=[("le", "+Inf")])
                lines.append(f"{name}_bucket{inf_labels} {count}"
                             f"{_exemplar_suffix(slots, len(buckets))}")
                labels = _render_labels(labelnames, key)
                lines.append(f"{name}_sum{labels} {_format_value(total)}")
                lines.append(f"{name}_count{labels} {count}")
        else:
            for key in sorted(fam["children"]):
                labels = _render_labels(labelnames, key)
                value = fam["children"][key]
                lines.append(f"{name}{labels} {_format_value(value)}")
    return "\n".join(lines) + "\n"


def _exemplar_suffix(slots, i: int) -> str:
    from predictionio_tpu.telemetry.registry import _render_exemplar
    return _render_exemplar(slots, i) if slots else ""


def counter_totals(snapshot: Dict, name: str,
                   where: Optional[Dict[str, str]] = None) -> float:
    """Sum of one counter family's children in a single snapshot,
    optionally restricted to children matching the ``where`` labels."""
    for fam in snapshot.get("families", ()):
        if fam["name"] == name and fam["type"] == "counter":
            labelnames = fam.get("labelnames", ())
            total = 0.0
            for k, v in fam.get("children", ()):
                if where:
                    kv = dict(zip(labelnames, k))
                    if any(kv.get(lk) != lv for lk, lv in where.items()):
                        continue
                total += float(v)
            return total
    return 0.0


def reset_inherited_counters(
        registry: MetricsRegistry = REGISTRY,
        drop_prefixes: tuple = ("supervisor_", "worker_pool_")) -> None:
    """Zero counter/histogram children in a freshly forked pool worker.

    fork() copies the parent's registry, so without this a respawned
    worker would re-report every request the supervisor (or the worker
    it was forked from) already counted — and the fleet merge would sum
    that inherited history twice. Control-plane families are dropped
    outright (a worker has no pool view); gauges are left alone — they
    are points the worker immediately re-owns."""
    for m in registry.families():
        if m.name.startswith(drop_prefixes):
            with m._lock:
                m._children.clear()
            continue
        if m.type == "counter":
            with m._lock:
                for c in m._children.values():
                    c._value = 0.0
        elif m.type == "histogram":
            with m._lock:
                for c in m._children.values():
                    c.counts = [0] * len(c.counts)
                    c.sum = 0.0
                    c.count = 0
                    if c.exemplars is not None:
                        c.exemplars = [None] * len(c.exemplars)


def _reinit_after_fork() -> None:
    # Runs after registry._reinit_locks_after_fork (registration order):
    # the child is a new worker — re-label its info gauge.
    refresh_worker_info()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)

refresh_worker_info()
