"""Multi-host control plane e2e: 2 real processes × 4 CPU devices each
federate into one 8-device world via `jax.distributed` and assemble a
correct global sharded array — the TPU-native replacement for the
reference's Spark driver↔executor bootstrap (SURVEY.md §2.7). Runs the
same `PIO_COORDINATOR_ADDRESS`/`PIO_NUM_PROCESSES`/`PIO_PROCESS_ID`
contract `pio train` uses on a real pod."""

import json
import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["PIO_TEST_REPO"])
    import numpy as np
    from predictionio_tpu.parallel import distributed

    # PIO_JAX_PLATFORM=cpu in the env exercises the platform override
    # inside initialize_from_env (the production path on CPU-only hosts)
    assert distributed.initialize_from_env()
    import jax
    import jax.numpy as jnp

    mesh = distributed.global_mesh()
    lo, hi = distributed.process_row_range(16)
    local = (np.arange(lo, hi, dtype=np.float32).reshape(-1, 1)
             * np.ones((1, 4), np.float32))
    garr = distributed.make_global_array(mesh, local)
    total = float(jax.jit(jnp.sum)(garr))
    out = {
        "pid": jax.process_index(),
        "devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "sum": total,
        "rows": [int(lo), int(hi)],
        "mesh": dict(mesh.shape),
    }
    with open(os.environ["PIO_TEST_OUT"], "w") as f:
        json.dump(out, f)
""")


def _run_global_mesh_world(tmp_path, n_procs, dev_per_proc):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    procs = []
    for pid in range(n_procs):
        env = dict(os.environ)
        env.pop("PIO_CONF_DIR", None)
        env.update(
            PIO_JAX_PLATFORM="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={dev_per_proc}",
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES=str(n_procs),
            PIO_PROCESS_ID=str(pid),
            PIO_TEST_REPO=str(REPO),
            PIO_TEST_OUT=str(tmp_path / f"out{pid}.json"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    return [json.loads((tmp_path / f"out{i}.json").read_text())
            for i in range(n_procs)]


@pytest.mark.e2e
def test_two_process_global_mesh(tmp_path):
    results = _run_global_mesh_world(tmp_path, 2, 4)
    expected_sum = float(sum(range(16)) * 4)
    for pid, r in enumerate(results):
        assert r["pid"] == pid
        assert r["devices"] == 8 and r["local_devices"] == 4
        assert r["sum"] == expected_sum  # every rank sees the global sum
        assert r["mesh"] == {"data": 8, "model": 1}
    # the two ranks fed disjoint halves of the global rows
    assert results[0]["rows"] == [0, 8] and results[1]["rows"] == [8, 16]


@pytest.mark.e2e
def test_four_process_global_mesh(tmp_path):
    """4-process world (VERDICT r2 #9): bootstrap, global mesh, and
    disjoint host row-feeding still hold past the 2-process special
    case (coordinator + 3 remote clients)."""
    results = _run_global_mesh_world(tmp_path, 4, 2)
    expected_sum = float(sum(range(16)) * 4)
    for pid, r in enumerate(results):
        assert r["pid"] == pid
        assert r["devices"] == 8 and r["local_devices"] == 2
        assert r["sum"] == expected_sum
        assert r["mesh"] == {"data": 8, "model": 1}
    assert [r["rows"] for r in results] == [[0, 4], [4, 8], [8, 12],
                                            [12, 16]]



TRAIN_ENV_KEYS = dict(
    PIO_STORAGE_REPOSITORIES_METADATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE="SQL",
    PIO_STORAGE_SOURCES_SQL_TYPE="sqlite",
)


def _seed_ratings(db, app_name, n_events, n_users, n_items, seed):
    """App + random rate events straight through the storage layer."""
    import numpy as np

    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.events import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.sqlite import SQLiteBackend

    backend = SQLiteBackend(str(db))
    app_id = backend.apps().insert(App(id=0, name=app_name))
    rng = np.random.default_rng(seed)
    backend.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=str(u),
               target_entity_type="item", target_entity_id=str(i),
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, n_users, n_events),
                            rng.integers(0, n_items, n_events),
                            rng.integers(1, 6, n_events))],
        app_id=app_id)
    backend.close()


def _write_engine_json(path, app_name, engine_id, rank, iters, **algo_params):
    params = {"rank": rank, "numIterations": iters, "lambda": 0.05, "seed": 1}
    params.update(algo_params)
    path.write_text(json.dumps({
        "id": engine_id, "engineFactory":
            "predictionio_tpu.templates.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": params}],
    }))


def _train_env(db, basedir, n_local_devices, **extra):
    """THE pod-contract env (storage + CPU mesh + PYTHONPATH) shared by
    every CLI-train harness; tests state only what differs."""
    env = dict(os.environ)
    env.pop("PIO_CONF_DIR", None)
    env.update(
        TRAIN_ENV_KEYS,
        PIO_STORAGE_SOURCES_SQL_PATH=str(db),
        PIO_FS_BASEDIR=str(basedir),
        PIO_JAX_PLATFORM="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={n_local_devices}",
        PYTHONPATH=f"{REPO}{os.pathsep}" + os.environ.get("PYTHONPATH", ""),
    )
    env.update(extra)
    return env


def _run_world_train(engine_json, db, basedir, n_ranks=2, dev_per_rank=4,
                     extra_env=None, faults_by_rank=None, extra_args=(),
                     check=True, timeout=300):
    """Launch an n-rank `bin/pio train` world federated via
    PIO_COORDINATOR_* — THE pod-contract launcher shared with the
    failure-path suite. `faults_by_rank` arms PIO_FAULTS on chosen ranks;
    `check=False` returns (returncodes, outputs) without asserting."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(n_ranks):
        env = _train_env(
            db, basedir, dev_per_rank,
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES=str(n_ranks),
            PIO_PROCESS_ID=str(pid),
            **(extra_env or {}),
        )
        env.pop("PIO_FAULTS", None)
        if faults_by_rank and pid in faults_by_rank:
            env["PIO_FAULTS"] = faults_by_rank[pid]
        procs.append(subprocess.Popen(
            [str(REPO / "bin" / "pio"), "train",
             "--engine-json", str(engine_json), *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    if check:
        for p, o in zip(procs, outs):
            assert p.returncode == 0, o
        return outs
    return [p.returncode for p in procs], outs


def _run_two_rank_train(engine_json, db, basedir, extra_env=None):
    return _run_world_train(engine_json, db, basedir, extra_env=extra_env)


@pytest.mark.e2e
def test_two_process_pio_train_cli(tmp_path):
    """The real pod contract end-to-end: TWO `bin/pio train` processes
    federate via PIO_COORDINATOR_* into one 8-device world over a shared
    file store; every rank trains (collectives need all of them), rank 0
    alone persists the model + COMPLETED instance, and the persisted
    model loads and answers a query."""
    import sqlite3

    db = tmp_path / "pio.db"
    _seed_ratings(db, "MHApp", 3000, 48, 32, seed=3)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "MHApp", "mh", rank=8, iters=3)

    outs = _run_two_rank_train(engine_json, db, tmp_path)

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT id FROM engine_instances WHERE status='COMPLETED'"
    ).fetchall()
    assert len(completed) == 1  # rank 0 only — no duplicate instances
    models = conn.execute("SELECT count(*) FROM models").fetchone()[0]
    assert models == 1
    conn.close()
    # rank 0 reported the REAL persisted instance id (rank 1 prints a
    # worker placeholder)
    assert f"Engine instance ID: {completed[0][0]}" in outs[0]

    # the persisted model must load and answer a query (single process);
    # seen-item exclusion may leave fewer than `num` candidates — the
    # claim is that the persisted model answers, not the exact count
    engine, ep, models_obj = _load_completed_model(db, engine_json)
    r = engine.predict(ep, models_obj, {"user": "1", "num": 3})
    assert 1 <= len(r["itemScores"]) <= 3


def _load_completed_model(db, engine_json):
    """Load the single COMPLETED instance's persisted model back through
    the engine; returns (engine, engine_params, model)."""
    import sqlite3

    from predictionio_tpu.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )
    from predictionio_tpu.workflow.workflow_utils import (
        EngineVariant, extract_engine_params, get_engine,
    )

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT id FROM engine_instances WHERE status='COMPLETED'"
    ).fetchall()
    conn.close()
    assert len(completed) == 1, completed
    src = SourceConfig(name="SQL", type="sqlite", path=str(db))
    storage = Storage(StorageConfig(metadata=src, modeldata=src,
                                    eventdata=src))
    try:
        variant = EngineVariant.from_dict(
            json.loads(pathlib.Path(engine_json).read_text()))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        blob = storage.model_data_models().get(completed[0][0]).models
        models = engine.deserialize_models(blob, completed[0][0], ep)
        return engine, ep, models
    finally:
        storage.close()


SHARDED_LOG = "training factors model-sharded ('model', None)"


def _run_single_pio_train(engine_json, db, basedir, mesh_shape, metrics_file):
    """One `bin/pio train` subprocess on the 8-virtual-device CPU mesh with
    the pod-level PIO_MESH_SHAPE env contract; returns its merged output."""
    env = _train_env(db, basedir, 8,
                     PIO_MESH_SHAPE=mesh_shape, PIO_LOG_LEVEL="INFO")
    proc = subprocess.run(
        [str(REPO / "bin" / "pio"), "train",
         "--engine-json", str(engine_json),
         "--metrics-file", str(metrics_file)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout
    return proc.stdout


def _split_counts(out):
    """Hot-row segment counts from the als_train bucketize log line
    ('... (N buckets, caps [...], S split) ...') — [users_split,
    items_split]."""
    import re

    m = re.findall(r"(\d+) split\)", out)
    assert len(m) >= 2, f"no als_train bucketize log in output:\n{out[-2000:]}"
    return [int(x) for x in m[:2]]


def _final_rmse(metrics_file):
    rmses = [json.loads(line)["rmse"]
             for line in pathlib.Path(metrics_file).read_text().splitlines()
             if "rmse" in json.loads(line)]
    assert rmses, f"no rmse records in {metrics_file}"
    return rmses[-1]


@pytest.mark.e2e
def test_pio_train_cli_model_axis_rank128(tmp_path):
    """Config 5's capability through the USER-FACING path (VERDICT r2 #1):
    `bin/pio train` with PIO_MESH_SHAPE=data=4,model=2 at rank 128 with
    hot-row segmentation forced. The in-product invariant in als_train
    raises unless the training factors really shard P('model'), its INFO
    log proves which mesh served the run, and the final RMSE matches a
    data-only-mesh train of the same data to MLlib-parity tolerance."""
    db = tmp_path / "pio.db"
    # 40 users × 24 items × 3000 events: after the Preparator's (user,
    # item) dedup most of the 960 pairs survive (~38 ratings/item, ~23/
    # user), so splitCap=16 forces hot-row segments on BOTH half-steps
    _seed_ratings(db, "C5App", 3000, 40, 24, seed=7)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "C5App", "c5", rank=128, iters=2,
                       computeRMSE=True, splitCap=16)

    out_m = _run_single_pio_train(engine_json, db, tmp_path,
                                  "data=4,model=2", tmp_path / "m.jsonl")
    assert SHARDED_LOG in out_m
    assert "'data': 4, 'model': 2" in out_m
    assert "Training completed" in out_m
    u_split, i_split = _split_counts(out_m)
    assert u_split > 0 and i_split > 0, (u_split, i_split)

    out_d = _run_single_pio_train(engine_json, db, tmp_path,
                                  "data=8,model=1", tmp_path / "d.jsonl")
    assert SHARDED_LOG not in out_d  # data-only mesh: replicated factors

    rmse_m = _final_rmse(tmp_path / "m.jsonl")
    rmse_d = _final_rmse(tmp_path / "d.jsonl")
    assert rmse_m == pytest.approx(rmse_d, rel=1e-3)

    import sqlite3

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT count(*) FROM engine_instances WHERE status='COMPLETED'"
    ).fetchone()[0]
    conn.close()
    assert completed == 2


@pytest.mark.e2e
def test_pio_train_bucket_cache_across_processes(tmp_path):
    """Re-running `pio train` on unchanged events skips the host
    bucketize via the on-disk cache under PIO_FS_BASEDIR (VERDICT r2 #5);
    ingesting one more event invalidates it."""
    db = tmp_path / "pio.db"
    _seed_ratings(db, "CacheApp", 1200, 32, 24, seed=13)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "CacheApp", "cache", rank=8, iters=2)

    env = _train_env(db, tmp_path, 8, PIO_LOG_LEVEL="INFO",
                     PIO_BUCKET_CACHE="1")  # conftest disables globally
    cmd = [str(REPO / "bin" / "pio"), "train",
           "--engine-json", str(engine_json)]

    def train():
        proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stdout
        return proc.stdout

    assert "bucket cache miss" in train()
    assert "bucket cache hit" in train()  # fresh process, same events

    # one new event from a NEW user → the prepared COO grows a row and a
    # user code → fingerprint changes → rebucketize
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.events import Event
    from predictionio_tpu.storage.sqlite import SQLiteBackend

    backend = SQLiteBackend(str(db))
    app_id = backend.apps().get_by_name("CacheApp").id
    backend.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id="99",
               target_entity_type="item", target_entity_id="2",
               properties=DataMap({"rating": 5.0}))], app_id=app_id)
    backend.close()
    out = train()
    assert "bucket cache miss" in out and "bucket cache hit" not in out


@pytest.mark.e2e
def test_two_process_pio_train_model_axis(tmp_path):
    """The 2-process pod world with model>1 (VERDICT r2 #1/weak #1): two
    `bin/pio train` ranks federate into a (data=4, model=2) global mesh
    from PIO_MESH_SHAPE alone; every rank's training factors shard
    P('model') across the world, rank 0 persists, and the model loads."""
    import sqlite3

    db = tmp_path / "pio.db"
    # post-dedup: ~29 ratings/item, ~22/user → splitCap=16 segments both
    _seed_ratings(db, "MHC5App", 2000, 32, 24, seed=11)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "MHC5App", "mhc5", rank=16, iters=2,
                       splitCap=16)

    outs = _run_two_rank_train(engine_json, db, tmp_path, extra_env={
        "PIO_MESH_SHAPE": "data=4,model=2",
        "PIO_LOG_LEVEL": "INFO",
    })
    for o in outs:  # BOTH ranks trained on the model-sharded mesh
        assert SHARDED_LOG in o, o
        assert "'data': 4, 'model': 2" in o
        u_split, i_split = _split_counts(o)
        assert u_split > 0 and i_split > 0, (u_split, i_split)

    conn = sqlite3.connect(db)
    models = conn.execute("SELECT count(*) FROM models").fetchone()[0]
    assert models == 1
    conn.close()

    # the persisted model answers a query (single process reload)
    engine, ep, models_obj = _load_completed_model(db, engine_json)
    r = engine.predict(ep, models_obj, {"user": "1", "num": 3})
    assert 1 <= len(r["itemScores"]) <= 3


@pytest.mark.e2e
def test_two_process_train_persists_to_object_store(tmp_path):
    """Multi-host deployments without a shared filesystem point MODELDATA
    at the s3 source (docs/operations.md); rank 0's model blob must land
    in the object store and load back."""
    import sqlite3

    from predictionio_tpu.storage.objectstore import S3Client
    from predictionio_tpu.storage.objectstore_server import ObjectStoreServer

    srv = ObjectStoreServer(str(tmp_path / "objects")).start()
    try:
        db = tmp_path / "pio.db"
        _seed_ratings(db, "MHS3App", 1500, 32, 24, seed=5)
        engine_json = tmp_path / "engine.json"
        _write_engine_json(engine_json, "MHS3App", "mhs3", rank=6, iters=2)

        _run_two_rank_train(engine_json, db, tmp_path, extra_env={
            "PIO_STORAGE_SOURCES_OBJ_TYPE": "s3",
            "PIO_STORAGE_SOURCES_OBJ_PATH":
                f"s3://pio/models?endpoint=http://127.0.0.1:{srv.port}",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ",
        })

        conn = sqlite3.connect(db)
        (instance_id,) = conn.execute(
            "SELECT id FROM engine_instances WHERE status='COMPLETED'"
        ).fetchone()
        conn.close()
        # exactly one model object, named by the instance, fetchable
        blobs = os.listdir(tmp_path / "objects" / "pio" / "models")
        assert blobs == [f"{instance_id}.model"]
        data = S3Client(f"http://127.0.0.1:{srv.port}", "pio").get_object(
            f"models/{instance_id}.model")
        assert data and len(data) > 1000
    finally:
        srv.shutdown()


@pytest.mark.e2e
def test_eight_process_train_with_nonzero_persist_rank(tmp_path):
    """VERDICT r3 #7: (a) an EIGHT-process `bin/pio train` world — double
    the previous drill ceiling — and (b) the persister/coordinator SPLIT:
    the jax coordinator is pinned to process 0, but PIO_PERSIST_RANK=3
    moves model/instance persistence to rank 3. Exactly one COMPLETED
    instance (written by rank 3), workers print placeholders, and the
    persisted model answers a query."""
    import sqlite3

    db = tmp_path / "pio.db"
    _seed_ratings(db, "OctApp", 2000, 48, 32, seed=8)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "OctApp", "oct", rank=8, iters=2)

    outs = _run_world_train(
        engine_json, db, tmp_path, n_ranks=8, dev_per_rank=1,
        extra_env={"PIO_PERSIST_RANK": "3",
                   "PIO_COORDINATOR_TIMEOUT_S": "60"},
        timeout=600)

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT id FROM engine_instances WHERE status='COMPLETED'"
    ).fetchall()
    assert len(completed) == 1  # ONE writer — no duplicate instances
    assert conn.execute("SELECT count(*) FROM models").fetchone()[0] == 1
    conn.close()
    # rank 3 (not the rank-0 coordinator) reported the persisted id;
    # every other rank printed the worker placeholder naming rank 3
    assert f"Engine instance ID: {completed[0][0]}" in outs[3]
    for pid in (0, 1, 2, 4, 5, 6, 7):
        assert "rank 3 persists" in outs[pid], outs[pid][-500:]

    engine, ep, models_obj = _load_completed_model(db, engine_json)
    r = engine.predict(ep, models_obj, {"user": "1", "num": 3})
    assert 1 <= len(r["itemScores"]) <= 3


@pytest.mark.e2e
def test_persist_rank_out_of_range_fails_loud(tmp_path):
    """PIO_PERSIST_RANK >= world size must fail the job with a clear
    error, not silently persist nowhere."""
    db = tmp_path / "pio.db"
    _seed_ratings(db, "BadRankApp", 500, 16, 12, seed=9)
    engine_json = tmp_path / "engine.json"
    _write_engine_json(engine_json, "BadRankApp", "badrank", rank=4,
                       iters=1)
    rcs, outs = _run_world_train(
        engine_json, db, tmp_path, n_ranks=2, dev_per_rank=1,
        extra_env={"PIO_PERSIST_RANK": "5"}, check=False, timeout=300)
    assert all(rc != 0 for rc in rcs), rcs
    assert any("PIO_PERSIST_RANK=5 out of range" in o for o in outs), (
        outs[0][-500:])
