"""Tenant attribution: per-app usage metering across every plane.

PredictionIO is multi-app by construction (``pio app new``, per-app
access keys), yet PRs 2–16 built every observability layer tenant-blind
— a noisy neighbor burning the fleet is invisible until *work* can be
attributed to an *app id*. This module is that measurement plane:

- a **tenant context** on the same contextvar discipline as
  ``telemetry.tracing``: the app id is resolved once at the trust
  boundary (access-key auth at ingest, the engine/variant binding at
  serving), activated around the request, and joined into the
  ``pio_lineage`` envelope so attribution survives every async hop the
  event takes (request thread → group commit → tailer → fold → swap);

- a **TenantMeter** that lands every unit of work under a capped tenant
  label (``registry.capped_label`` group ``"tenant"`` — cardinality is
  bounded, apps admitted before the cap keep stable series identity,
  the rest collapse to ``<other>``). Families:

  ===============================  ===========================================
  ``tenant_requests_total``        requests handled, by app × server × outcome
  ``tenant_device_seconds_total``  attributed device time (rides the device
                                   clock's dispatch accounting)
  ``tenant_storage_rows``          event rows committed to the event store
  ``tenant_commit_bytes_total``    approximate payload bytes group-committed
  ``tenant_folded_events_total``   events folded into a served model
  ``tenant_event_to_servable_seconds``  per-app freshness histogram
  ===============================  ===========================================

**Sum-exactness is the contract.** The meter keeps a plain-int mirror
(like the device plane's microsecond ledger): every ``add`` bumps the
per-app cell *and* the family's untagged total under one lock, so
``sum(by_app.values()) == untagged`` holds per family by construction.
``export_state()`` ships both through the PR 9 snapshot channel and
``merge_tenants`` re-asserts the invariant on the fleet-merged view —
a tenant breakdown that doesn't add up to the untagged total is a bug,
not a rounding artifact. Work with no resolvable app lands under the
``"-"`` label rather than being dropped, which is what keeps the sums
exact instead of merely close.

Per-tenant SLOs: the first unit of work for an app registers an SLO
objective under server ``"tenant"`` route ``<app>`` (``slo.py``), so
``slo_error_budget_burn_rate{server="tenant",route="<app>"}`` answers
"which app is burning its budget" and the ``tenant-burn-5m`` alert rule
pages on it.

Operability: ``GET /debug/tenants.json`` (both transports) serves the
top-K usage/burn view; the supervisor overrides it with the fleet merge;
``history.py`` samples ``tenant_*`` families; the dashboard grows a
Tenants panel. Runbook: docs/observability.md §Tenants.

Knobs (docs/operations.md):

- ``PIO_TENANT_METER=0``      disable metering (context still propagates)
- ``PIO_TENANT_LABEL_CAP``    distinct app labels before ``<other>`` (64)
- ``PIO_TENANT_TOPK``         rows in /debug/tenants.json (10)
- ``PIO_TENANT_SLO_TARGET``   per-tenant availability target (0.999)
- ``PIO_TENANT_SLO_LATENCY_MS``  per-tenant latency threshold (250)

Fork hygiene mirrors ``aggregate.reset_inherited_counters``: a forked
worker clears the inherited meter (and reinits its lock) in an at-fork
hook, so fleet sums never double-count the parent's pre-fork work.
"""

from __future__ import annotations

import contextvars
import os
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from predictionio_tpu.telemetry import slo
from predictionio_tpu.telemetry.registry import (
    DEFAULT_LABEL_CAP,
    REGISTRY,
    capped_label,
)

# app id for work no tenant context could be resolved for — metered, not
# dropped, so per-family sums stay exact against the untagged totals
UNATTRIBUTED = "-"

_LABEL_GROUP = "tenant"


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


_ENABLED = _env_flag("PIO_TENANT_METER", True)
LABEL_CAP = _env_int("PIO_TENANT_LABEL_CAP", DEFAULT_LABEL_CAP)
TOP_K = _env_int("PIO_TENANT_TOPK", 10)
SLO_TARGET = _env_float("PIO_TENANT_SLO_TARGET", 0.999)
SLO_LATENCY_S = _env_float("PIO_TENANT_SLO_LATENCY_MS", 250.0) / 1000.0

# SLO server name the per-tenant objectives register under
SLO_SERVER = "tenant"


def enabled() -> bool:
    return _ENABLED


# -- tenant context ------------------------------------------------------------
#
# Same discipline as tracing._current: a contextvar carrying a tiny
# slotted object, activate() returning the reset token, deactivate()
# restoring the outer binding. contextvars (not a threading.local) so the
# binding survives executor hops that copy context.


class TenantContext:
    """The resolved tenant for the work currently executing."""

    __slots__ = ("app", "source")

    def __init__(self, app: str, source: str = ""):
        self.app = str(app)
        # where the binding came from: "access_key" | "variant" | "lineage"
        self.source = source

    def __repr__(self) -> str:  # debugging only
        return f"TenantContext(app={self.app!r}, source={self.source!r})"


_current: contextvars.ContextVar = contextvars.ContextVar(
    "pio_tenant_context", default=None)


def activate(app, source: str = "") -> "contextvars.Token":
    """Bind the tenant for this execution context; returns the token for
    deactivate(). `app` is coerced to str (app ids are ints in storage)."""
    return _current.set(TenantContext(app, source))


def deactivate(token: "contextvars.Token") -> None:
    _current.reset(token)


def current() -> Optional[TenantContext]:
    return _current.get()


def current_app() -> Optional[str]:
    ctx = _current.get()
    return ctx.app if ctx is not None else None


class bound:
    """``with tenant.bound(app_id, "access_key"): ...`` — cheap class-based
    context manager (no @contextmanager generator overhead), mirroring
    tracing.span."""

    __slots__ = ("app", "source", "_token")

    def __init__(self, app, source: str = ""):
        self.app = app
        self.source = source

    def __enter__(self):
        self._token = activate(self.app, self.source)
        return self

    def __exit__(self, exc_type, exc, tb):
        deactivate(self._token)
        return False


def tenant_label(app: Optional[str]) -> str:
    """The bounded label for an app id: admitted per capped_label group
    "tenant" up to PIO_TENANT_LABEL_CAP, then `<other>`."""
    if app is None:
        return UNATTRIBUTED
    return capped_label(_LABEL_GROUP, str(app), LABEL_CAP)


# -- registry mirrors ----------------------------------------------------------

# same shape as online_event_to_servable_seconds so per-tenant p95s are
# comparable against the untagged north star
_E2S_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 30.0)

TENANT_REQUESTS = REGISTRY.counter(
    "tenant_requests_total",
    "Requests handled under a tenant binding, by app, server and outcome",
    labelnames=("app", "server", "outcome"))
TENANT_DEVICE_SECONDS = REGISTRY.counter(
    "tenant_device_seconds_total",
    "Device time attributed to each app by the device clock's dispatch "
    "accounting",
    labelnames=("app",))
TENANT_STORAGE_ROWS = REGISTRY.counter(
    "tenant_storage_rows",
    "Event rows committed to the event store, by app",
    labelnames=("app",))
TENANT_COMMIT_BYTES = REGISTRY.counter(
    "tenant_commit_bytes_total",
    "Approximate event payload bytes group-committed, by app",
    labelnames=("app",))
TENANT_FOLDED = REGISTRY.counter(
    "tenant_folded_events_total",
    "Events folded into a served model by the online plane, by app",
    labelnames=("app",))
TENANT_FRESHNESS = REGISTRY.histogram(
    "tenant_event_to_servable_seconds",
    "Per-app event_time → served-model swap latency (per-tenant slice of "
    "the online_event_to_servable_seconds north star)",
    labelnames=("app",), buckets=_E2S_BUCKETS)


# -- the meter -----------------------------------------------------------------

# plain-int families the sum-exact contract is asserted over; device time
# is metered in integer microseconds (like device._ATTR_TOTALS) so fleet
# merges add exactly
FAMILIES = ("requests", "device_us", "storage_rows", "commit_bytes",
            "folded_events")


class TenantMeter:
    """Per-app usage ledger with an untagged mirror updated in the same
    critical section — sum-exactness by construction, not by sampling."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_app: Dict[str, Dict[str, int]] = {f: {} for f in FAMILIES}
        self._untagged: Dict[str, int] = {f: 0 for f in FAMILIES}
        # apps that already have a ("tenant", app) SLO objective
        self._slo_registered: set = set()

    def add(self, family: str, app: str, n: int = 1) -> None:
        with self._lock:
            cells = self._by_app[family]
            cells[app] = cells.get(app, 0) + n
            self._untagged[family] += n

    def ensure_slo(self, app: str) -> None:
        """Register the per-tenant SLO objective once per admitted app
        label (burn gauges then come free from slo.refresh())."""
        if app == UNATTRIBUTED:
            return
        with self._lock:
            if app in self._slo_registered:
                return
            self._slo_registered.add(app)
        slo.set_objective(SLO_SERVER, app,
                          availability_target=SLO_TARGET,
                          latency_target=SLO_TARGET,
                          latency_threshold_s=SLO_LATENCY_S)

    def export_state(self) -> Dict:
        """Snapshot for the PR 9 aggregate channel: per-app cells plus the
        untagged totals they must sum to."""
        with self._lock:
            return {
                "by_app": {f: dict(cells)
                           for f, cells in self._by_app.items()},
                "untagged": dict(self._untagged),
            }

    def totals(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {f: dict(cells) for f, cells in self._by_app.items()}

    def reset(self) -> None:
        with self._lock:
            self._by_app = {f: {} for f in FAMILIES}
            self._untagged = {f: 0 for f in FAMILIES}
            self._slo_registered = set()


METER = TenantMeter()

# Hot-path child caches: Family.labels() pays a labelname set-compare +
# family lock per call, which is real money on per-request/per-dispatch
# paths (the serving batcher's ≤5% machinery bar). Keys are resolved
# (capped) labels, so both dicts are bounded. Fork-safe without a hook:
# reset_inherited_counters zeroes and _reinit_locks_after_fork re-points
# locks on these same child objects in place.
_REQ_CHILDREN: Dict[Tuple[str, str, str], object] = {}
_DEV_CHILDREN: Dict[str, object] = {}


def _resolve(app: Optional[str]) -> str:
    if app is None:
        app = current_app()
    return tenant_label(app)


# -- metering entry points (one per unit of work) ------------------------------


def record_request(server: str, outcome: str, app: Optional[str] = None,
                   status: int = 200, duration_s: float = 0.0) -> None:
    """One handled request. Feeds the per-tenant SLO tracker too, so the
    app's availability/latency burn is computed from the same stream."""
    if not _ENABLED:
        return
    label = _resolve(app)
    METER.add("requests", label)
    key = (label, server, outcome)
    child = _REQ_CHILDREN.get(key)
    if child is None:
        child = _REQ_CHILDREN[key] = TENANT_REQUESTS.labels(
            app=label, server=server, outcome=outcome)
    child.inc()
    if label != UNATTRIBUTED:
        METER.ensure_slo(label)
        slo.observe(SLO_SERVER, label, status, duration_s)


def record_device_us(us: int, app: Optional[str] = None) -> None:
    """Device time for one dispatch, integer microseconds (called from
    device._account with the same value it lands in _ATTR_TOTALS)."""
    if not _ENABLED or us < 0:
        return
    label = _resolve(app)
    METER.add("device_us", label, int(us))
    child = _DEV_CHILDREN.get(label)
    if child is None:
        child = _DEV_CHILDREN[label] = TENANT_DEVICE_SECONDS.labels(app=label)
    child.inc(us / 1e6)


def record_storage_rows(app, rows: int, nbytes: int = 0) -> None:
    """Rows (and approximate payload bytes) group-committed for one app."""
    if not _ENABLED or rows <= 0:
        return
    label = _resolve(app if app is None else str(app))
    METER.add("storage_rows", label, int(rows))
    TENANT_STORAGE_ROWS.labels(app=label).inc(rows)
    if nbytes > 0:
        METER.add("commit_bytes", label, int(nbytes))
        TENANT_COMMIT_BYTES.labels(app=label).inc(nbytes)


def record_commit_bytes(app, nbytes: int) -> None:
    """Approximate payload bytes committed for one app (the request body
    length at the API layer — free to measure, close enough to rank
    tenants by write volume)."""
    if not _ENABLED or nbytes <= 0:
        return
    label = _resolve(app if app is None else str(app))
    METER.add("commit_bytes", label, int(nbytes))
    TENANT_COMMIT_BYTES.labels(app=label).inc(nbytes)


def record_folded(app, n: int) -> None:
    """Events folded into a served model for one app."""
    if not _ENABLED or n <= 0:
        return
    label = _resolve(app if app is None else str(app))
    METER.add("folded_events", label, int(n))
    TENANT_FOLDED.labels(app=label).inc(n)


def observe_freshness(app, seconds: float) -> None:
    """One per-event event→servable latency under the app's label."""
    if not _ENABLED:
        return
    label = _resolve(app if app is None else str(app))
    TENANT_FRESHNESS.labels(app=label).observe(seconds)


# -- export / fleet merge ------------------------------------------------------


def export_state() -> Dict:
    """This process's tenant ledger for aggregate.snapshot_registry."""
    return METER.export_state()


def merge_tenants(parts: Iterable[Tuple[str, Optional[Dict]]]) -> Dict:
    """Merge (worker_label, export_state()) pairs into one fleet tenant
    view. Integer cells sum exactly, the per-worker request totals ship
    in the same payload, and the sum-exact invariant — per family,
    ``sum(by_app.values()) == untagged`` — is re-asserted on the merged
    result (a worker whose breakdown doesn't add up poisons the fleet
    view loudly, not silently)."""
    by_app: Dict[str, Dict[str, int]] = {f: {} for f in FAMILIES}
    untagged: Dict[str, int] = {f: 0 for f in FAMILIES}
    workers: Dict[str, int] = {}
    for wlabel, state in parts:
        if state is None:
            # dead/old worker: present in the roster, contributes zero
            workers.setdefault(str(wlabel), 0)
            continue
        part_requests = 0
        for family in FAMILIES:
            cells = state.get("by_app", {}).get(family, {})
            dst = by_app[family]
            for app, n in cells.items():
                dst[app] = dst.get(app, 0) + int(n)
                if family == "requests":
                    part_requests += int(n)
            untagged[family] += int(state.get("untagged", {}).get(family, 0))
        workers[str(wlabel)] = workers.get(str(wlabel), 0) + part_requests
    for family in FAMILIES:
        total = sum(by_app[family].values())
        if total != untagged[family]:
            raise AssertionError(
                f"tenant merge not sum-exact for {family!r}: "
                f"sum(by_app)={total} != untagged={untagged[family]}")
    return {
        "fleet": True,
        "workers": workers,
        "by_app": by_app,
        "untagged": untagged,
    }


def payload(top_k: Optional[int] = None,
            merged: Optional[Dict] = None) -> Dict:
    """The /debug/tenants.json body: top-K apps by usage with per-family
    counts, the untagged totals they sum to, and (single-process view)
    each app's worst 5m SLO burn. Pass a merge_tenants() result as
    `merged` for the supervisor's fleet view (burn is per-process tracker
    state, so the fleet payload reports usage only)."""
    if top_k is None:
        top_k = TOP_K
    fleet = merged is not None
    state = merged if fleet else export_state()
    by_app = state["by_app"]
    untagged = state["untagged"]
    apps = set()
    for cells in by_app.values():
        apps.update(cells)
    rows: List[Dict] = []
    for app in apps:
        device_us = by_app["device_us"].get(app, 0)
        row = {
            "app": app,
            "requests": by_app["requests"].get(app, 0),
            "device_seconds": round(device_us / 1e6, 6),
            "storage_rows": by_app["storage_rows"].get(app, 0),
            "commit_bytes": by_app["commit_bytes"].get(app, 0),
            "folded_events": by_app["folded_events"].get(app, 0),
        }
        if not fleet and app != UNATTRIBUTED:
            burn, window_requests = slo.current_burn(SLO_SERVER, app)
            row["burn_5m"] = round(burn, 3)
            row["slo_window_requests"] = window_requests
        rows.append(row)
    rows.sort(key=lambda r: (-r["device_seconds"], -r["requests"],
                             -r["storage_rows"], r["app"]))
    out = {
        "enabled": _ENABLED,
        "label_cap": LABEL_CAP,
        "apps_total": len(apps),
        "top_k": top_k,
        "tenants": rows[:top_k],
        "untagged": {
            "requests": untagged["requests"],
            "device_seconds": round(untagged["device_us"] / 1e6, 6),
            "device_us": untagged["device_us"],
            "storage_rows": untagged["storage_rows"],
            "commit_bytes": untagged["commit_bytes"],
            "folded_events": untagged["folded_events"],
        },
        # asserted at merge time; restated here so one fetch carries the
        # receipt ("the breakdown adds up") next to the breakdown itself
        "sum_exact": all(
            sum(by_app[f].values()) == untagged[f] for f in FAMILIES),
    }
    if fleet:
        out["fleet"] = True
        out["workers"] = state.get("workers", {})
    return out


def payload_response(top_k: Optional[int] = None) -> Tuple[int, Dict]:
    """(status, body) for the middleware route handlers."""
    return 200, payload(top_k=top_k)


# -- lifecycle -----------------------------------------------------------------


def reset_inherited() -> None:
    """Forked-worker hygiene, mirroring aggregate.reset_inherited_counters:
    the child's ledger starts from zero so the fleet merge never counts
    the parent's pre-fork work twice (the registry-side tenant_* counters
    are zeroed by reset_inherited_counters itself)."""
    METER.reset()


def reset_state() -> None:
    """Tests: drop all tenant state (ledger only; registry families are
    reset by the callers that own them)."""
    METER.reset()


def _reinit_after_fork() -> None:
    # fresh lock (parent threads may hold it mid-fork) AND a fresh ledger:
    # inherited per-tenant cells in a respawned worker would double-count
    # in the fleet merge, same reasoning as lineage._reset_after_fork
    METER._lock = threading.Lock()
    METER.reset()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)
