"""Controller API — the DASE abstraction (Data source, Algorithm, Serving,
Evaluation), the framework's public face.

Parity with «core/.../controller/» (SURVEY.md §2.1 [U]): `Engine`,
`EngineFactory`, `EngineParams`, `PDataSource`/`LDataSource`,
`PPreparator`, `P2LAlgorithm`/`PAlgorithm`/`LAlgorithm`, `LServing`,
`Evaluation`, `Metric`, `Params`, `PersistentModel`, `SanityCheck`.

TPU-first redesign notes (SURVEY.md §7.1):
- The reference's P (RDD/parallel) vs L (local) split collapses: training
  data is host-side numpy handed to jitted, mesh-sharded XLA programs, so
  one `DataSource`/`Algorithm` API serves both roles. Aliases with the
  reference names are provided for familiarity.
- `Algorithm.train` should be a pure function of (ctx, prepared_data) whose
  heavy lifting is `jax.jit`-ed under `ctx.mesh`; models are pytrees (or
  pickleable host objects wrapping them).
- Reflective `Doer` instantiation survives as `Doer(cls, params)`.
"""

from predictionio_tpu.controller.params import (
    EmptyParams,
    Params,
    params_from_dict,
    params_to_dict,
)
from predictionio_tpu.controller.context import WorkflowContext
from predictionio_tpu.controller.base import (
    Algorithm,
    DataSource,
    Doer,
    LAlgorithm,
    LDataSource,
    LPreparator,
    LServing,
    P2LAlgorithm,
    PAlgorithm,
    PDataSource,
    PPreparator,
    PersistentModel,
    PersistentModelLoader,
    Preparator,
    SanityCheck,
    Serving,
    FirstServing,
    AverageServing,
    IdentityPreparator,
)
from predictionio_tpu.controller.engine import Engine, EngineFactory, EngineParams
from predictionio_tpu.controller.metrics import (
    AUC,
    AverageMetric,
    Metric,
    MAPatK,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
)

__all__ = [
    "Params",
    "EmptyParams",
    "params_from_dict",
    "params_to_dict",
    "WorkflowContext",
    "DataSource",
    "PDataSource",
    "LDataSource",
    "Preparator",
    "PPreparator",
    "LPreparator",
    "IdentityPreparator",
    "Algorithm",
    "P2LAlgorithm",
    "PAlgorithm",
    "LAlgorithm",
    "Serving",
    "LServing",
    "FirstServing",
    "AverageServing",
    "PersistentModel",
    "PersistentModelLoader",
    "SanityCheck",
    "Doer",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "Metric",
    "AUC",
    "AverageMetric",
    "MAPatK",
    "OptionAverageMetric",
    "StdevMetric",
    "SumMetric",
    "ZeroMetric",
    "Evaluation",
    "MetricEvaluator",
    "EngineParamsGenerator",
]
