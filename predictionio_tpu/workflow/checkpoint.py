"""Per-epoch checkpoint/resume for training runs.

The reference has no mid-training checkpointing — MLlib ALS only truncates
RDD lineage («sc.setCheckpointDir», SURVEY.md §5 'Checkpoint / resume'
[U]); recovery is whole-model persistence after train. JAX has no lineage
to recompute from, so the rebuild provides the stronger contract SURVEY.md
§5 prescribes: factor matrices / opt state checkpointed every N epochs,
`pio train --checkpoint-dir` resumable after interruption, while `deploy`
keeps the reference's latest-COMPLETED-EngineInstance contract.

Format: one directory per step holding `arrays.npz` (the numpy pytree
leaves) + `meta.json` (tree structure + user metadata). Writes go to a
temp dir then `os.replace` — a crash mid-write never corrupts the latest
complete step, which is the same atomicity story orbax's finalized-commit
protocol gives (orbax itself is deliberately not used: its async layout
churns across versions, and these checkpoints are small host-side numpy
state, not sharded jax.Arrays).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
from typing import Any, Optional

import numpy as np

from predictionio_tpu.telemetry import spans
from predictionio_tpu.telemetry.registry import REGISTRY
from predictionio_tpu.utils import faults

log = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")

CKPT_SAVE_SECONDS = REGISTRY.histogram(
    "checkpoint_save_seconds", "Checkpoint save latency in seconds")
CKPT_RESTORE_SECONDS = REGISTRY.histogram(
    "checkpoint_restore_seconds", "Checkpoint restore latency in seconds")
CKPT_SAVES = REGISTRY.counter(
    "checkpoint_saves_total", "Checkpoint steps saved")
CKPT_RESTORES = REGISTRY.counter(
    "checkpoint_restores_total", "Checkpoint steps restored")


def _flatten(tree: Any, prefix: str = "") -> tuple[dict, Any]:
    """Flatten a (dict|list|scalar|ndarray) pytree → ({path: ndarray}, spec).

    The spec mirrors the tree with leaf positions replaced by their path
    string, so restore can rebuild the exact structure.
    """
    if isinstance(tree, dict):
        arrays: dict = {}
        spec = {}
        for k in sorted(tree):
            sub_arrays, sub_spec = _flatten(tree[k], f"{prefix}{k}/")
            arrays.update(sub_arrays)
            spec[k] = sub_spec
        return arrays, {"__dict__": spec}
    if isinstance(tree, (list, tuple)):
        arrays = {}
        spec_items = []
        for idx, item in enumerate(tree):
            sub_arrays, sub_spec = _flatten(item, f"{prefix}{idx}/")
            arrays.update(sub_arrays)
            spec_items.append(sub_spec)
        return arrays, {"__list__": spec_items, "__tuple__": isinstance(tree, tuple)}
    path = prefix.rstrip("/") or "value"
    return {path: np.asarray(tree)}, {"__leaf__": path}


def _unflatten(spec: Any, arrays: dict) -> Any:
    if "__dict__" in spec:
        return {k: _unflatten(v, arrays) for k, v in spec["__dict__"].items()}
    if "__list__" in spec:
        items = [_unflatten(v, arrays) for v in spec["__list__"]]
        return tuple(items) if spec.get("__tuple__") else items
    return arrays[spec["__leaf__"]]


class CheckpointManager:
    """Save/restore numpy pytrees keyed by integer step.

    API shape follows orbax's CheckpointManager (`save`, `restore`,
    `latest_step`, `all_steps`) so a swap to orbax for multi-host sharded
    state is a drop-in later.
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = max(1, keep)
        os.makedirs(self.directory, exist_ok=True)
        # salvage a step renamed aside by a save() that crashed between
        # rename-aside and publish (see save's overwrite protocol): the
        # aside copy is the only complete version of that step
        for name in os.listdir(self.directory):
            if not name.endswith(".old"):
                continue
            orig = os.path.join(self.directory, name[: -len(".old")])
            aside = os.path.join(self.directory, name)
            if _STEP_RE.match(name[: -len(".old")]):
                if os.path.exists(orig):
                    shutil.rmtree(aside, ignore_errors=True)  # publish won
                else:
                    os.rename(aside, orig)
                    log.info("checkpoint: salvaged %s from interrupted "
                             "overwrite", orig)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def all_steps(self) -> list[int]:
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name, "meta.json")):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, tree: Any, metadata: Optional[dict] = None) -> str:
        with spans.span(f"checkpoint.save step_{step}"), \
                CKPT_SAVE_SECONDS.time():
            out = self._save(step, tree, metadata)
        CKPT_SAVES.inc()
        return out

    def _save(self, step: int, tree: Any, metadata: Optional[dict]) -> str:
        arrays, spec = _flatten(tree)
        tmp = os.path.join(self.directory, f".tmp_step_{step}_{os.getpid()}")
        final = self._step_dir(step)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, "spec": spec,
                           "metadata": metadata or {}}, f)
            # overwrite protocol: rename the existing step ASIDE (not
            # rmtree — a crash between delete and publish would lose the
            # old step too), publish, then drop the aside copy. A crash in
            # the window leaves `step_N.old`, salvaged on next init.
            old = None
            if os.path.exists(final):
                old = final + ".old"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(final, old)
            faults.inject("checkpoint.pre_replace")
            os.replace(tmp, final)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        log.info("checkpoint: saved step %d → %s", step, final)
        return final

    def restore(self, step: Optional[int] = None) -> tuple[Any, dict]:
        """→ (tree, metadata). step=None restores the latest."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"No checkpoints under {self.directory}")
        with spans.span(f"checkpoint.restore step_{step}"), \
                CKPT_RESTORE_SECONDS.time():
            d = self._step_dir(step)
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
            with np.load(os.path.join(d, "arrays.npz")) as z:
                arrays = {k: z[k] for k in z.files}
            out = _unflatten(meta["spec"], arrays), meta.get("metadata", {})
        CKPT_RESTORES.inc()
        return out

    def _gc(self) -> None:
        steps = self.all_steps()
        for step in steps[: -self.keep]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)

    def keep_only(self, step: Optional[int]) -> None:
        """Delete every saved step except `step` (None = delete all).

        Called at run start once the resume point is decided: stale steps
        from a previous run with different data/config/iteration-count
        must not shadow the new run's saves (the retention GC keeps the
        *highest* steps, so leftovers above the new run's range would
        immediately garbage-collect its fresh saves)."""
        for s in self.all_steps():
            if s != step:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
