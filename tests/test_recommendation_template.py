"""Recommendation template end-to-end: events in storage → DASE train via
CoreWorkflow → model persistence → query serving — the §7.2 step-4
'minimum end-to-end slice' (SURVEY.md)."""

import json
from datetime import datetime, timezone

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.recommendation.RecommendationEngine"


def ingest_ratings(storage, app_name="RecApp", n_users=12, n_items=8, seed=0):
    """Block structure: even users love even items, odd users love odd."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    rng = np.random.default_rng(seed)
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    expected = {}
    for u in range(n_users):
        liked = [i for i in range(n_items) if i % 2 == u % 2]
        disliked = [i for i in range(n_items) if i % 2 != u % 2]
        # rotate the held-out liked item so every item is rated by someone
        holdout = liked[(u // 2) % len(liked)]
        for i in liked:
            if i == holdout:
                continue
            le.insert(Event(event="rate", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}",
                            properties=DataMap({"rating": 5.0}), event_time=t0),
                      app_id)
        for i in disliked[: len(disliked) // 2]:
            le.insert(Event(event="rate", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}",
                            properties=DataMap({"rating": 1.0}), event_time=t0),
                      app_id)
        expected[f"u{u}"] = f"i{holdout}"
    # one "buy" event (implicit 4.0 path)
    le.insert(Event(event="buy", entity_type="user", entity_id="u0",
                    target_entity_type="item", target_entity_id="i2",
                    event_time=t0), app_id)
    return expected


def variant_dict(app_name="RecApp", rank=4, iters=15):
    return {
        "id": "rec-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": {
            "rank": rank, "numIterations": iters, "lambda": 0.05, "seed": 1}}],
    }


class TestRecommendationEndToEnd:
    def test_train_and_recommend(self, memory_storage):
        expected = ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        # reload through the persistence path, as deploy would
        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        result = engine.predict(ep, models, {"user": "u0", "num": 3})
        items = [s["item"] for s in result["itemScores"]]
        assert len(items) == 3
        # scores sorted descending
        scores = [s["score"] for s in result["itemScores"]]
        assert scores == sorted(scores, reverse=True)
        # the held-out liked item should be the top recommendation
        assert items[0] == expected["u0"]
        # seen items are excluded
        seen_items = {f"i{i}" for i in range(8)} - {expected["u0"]}
        assert not (set(items) & seen_items) or items[0] == expected["u0"]

    def test_batch_predict_matches_predict_and_takes_device_branch(
            self, monkeypatch):
        """`pio batchpredict`'s bulk route (VERDICT r2 #4): one vectorized
        top-k equals the per-query loop, and past SERVE_HOST_MAX_BATCH
        users it actually dispatches the accelerator branch instead of
        host matvecs."""
        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.models.als_model import ALSModel, SeenItems
        from predictionio_tpu.ops import ranking
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithm, ALSAlgorithmParams,
        )

        rng = np.random.default_rng(3)
        n_u, n_i = 100, 40  # > SERVE_HOST_MAX_BATCH users
        model = ALSModel(
            user_factors=rng.normal(size=(n_u, 8)).astype(np.float32),
            item_factors=rng.normal(size=(n_i, 8)).astype(np.float32),
            user_ids=BiMap.string_int([f"u{i}" for i in range(n_u)]),
            item_ids=BiMap.string_int([f"i{i}" for i in range(n_i)]),
            seen=SeenItems(np.arange(n_u, dtype=np.int32),
                           np.arange(n_u, dtype=np.int32) % n_i, n_u),
        )
        algo = ALSAlgorithm(ALSAlgorithmParams())

        device_batches = []
        real = ranking._topk_fn

        def spy(k, masked):
            fn = real(k, masked)

            def wrapped(u, items, *rest):
                device_batches.append(u.shape[0])
                return fn(u, items, *rest)

            return wrapped

        monkeypatch.setattr(ranking, "_topk_fn", spy)
        queries = ([{"user": f"u{i}", "num": 5} for i in range(n_u)]
                   + [{"user": "nobody", "num": 5}, {"user": "u0", "num": 2}])
        batch = algo.batch_predict(model, queries)
        assert device_batches and max(device_batches) \
            > ranking.SERVE_HOST_MAX_BATCH, device_batches

        monkeypatch.setattr(ranking, "_topk_fn", real)  # per-query = host
        for q, got in zip(queries, batch):
            want = algo.predict(model, q)
            # device (XLA) and host (BLAS) dots differ in last-ulp float;
            # items and order must agree, scores to tolerance
            assert [s["item"] for s in got["itemScores"]] \
                == [s["item"] for s in want["itemScores"]], q
            assert [s["score"] for s in got["itemScores"]] == pytest.approx(
                [s["score"] for s in want["itemScores"]], rel=1e-5), q

    def test_unknown_user_empty_result(self, memory_storage):
        ingest_ratings(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models_list = engine.train(ctx, ep)
        result = engine.predict(ep, models_list, {"user": "ghost", "num": 3})
        assert result == {"itemScores": []}

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptyApp"))
        variant = EngineVariant.from_dict(variant_dict("EmptyApp"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no rating events"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)
        rows = memory_storage.meta_engine_instances().get_all()
        assert rows[0].status == "FAILED"

    def test_evaluation_with_map_metric(self, memory_storage):
        ingest_ratings(memory_storage, n_users=16, n_items=10)
        variant = EngineVariant.from_dict({
            "id": "rec-eval",
            "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "RecApp", "evalK": 3}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 8, "lambda": 0.05}}],
        })
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        from predictionio_tpu.controller import OptionAverageMetric
        from predictionio_tpu.controller.evaluation import Evaluation, MetricEvaluator
        from predictionio_tpu.ops.ranking import average_precision_at_k

        class MAPat10(OptionAverageMetric):
            def calculate(self, q, p, a):
                predicted = np.asarray(
                    [s["item"] for s in p["itemScores"]], dtype=object)
                return average_precision_at_k(predicted, set(a["items"]), 10)

        class RecEval(Evaluation):
            pass

        RecEval.engine = engine
        RecEval.metric = MAPat10()
        ctx = WorkflowContext(storage=memory_storage, seed=0)
        result = MetricEvaluator.evaluate(ctx, RecEval(), [ep])
        score = result.best.scores["MAPat10"]
        assert 0.0 <= score <= 1.0
        assert not np.isnan(score)

    def test_template_engine_json_parses(self):
        import os
        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "recommendation", "engine.json")
        from predictionio_tpu.workflow.workflow_utils import read_engine_json
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert ep.algorithm_params_list[0][1].lambda_ == 0.01
        assert ep.algorithm_params_list[0][1].rank == 10
