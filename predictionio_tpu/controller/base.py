"""DASE component base classes.

Parity with «core/.../core/Base*.scala» + «core/.../controller/*» (SURVEY.md
§2.1 [U]). The reference's P*/L* split (RDD vs local JVM) collapses on TPU
(see package docstring); `P2LAlgorithm`, `PAlgorithm`, `LAlgorithm`,
`PDataSource`, ... are kept as aliases so template code reads like the
originals.
"""

from __future__ import annotations

import abc
import logging
from typing import Any, Generic, Optional, Sequence, Type, TypeVar

from predictionio_tpu.controller.context import WorkflowContext
from predictionio_tpu.controller.params import Params

log = logging.getLogger(__name__)

TD = TypeVar("TD")  # training data
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")  # model
Q = TypeVar("Q")  # query
R = TypeVar("R")  # predicted result
A = TypeVar("A")  # actual result


class Doer:
    """Reflective component instantiation («core/.../core/AbstractDoer ::
    Doer.apply» [U]): constructs a DASE component class with its Params.

    Components take their params object as the single constructor arg;
    components with no params may omit the constructor entirely.
    """

    @staticmethod
    def apply(cls: Type, params: Optional[Params] = None):
        if params is None:
            return cls()
        # Inspect rather than try/except: a TypeError raised *inside* a
        # valid constructor must not silently drop the user's params.
        import inspect

        try:
            sig = inspect.signature(cls)
            takes_params = len(sig.parameters) >= 1
        except (TypeError, ValueError):
            takes_params = True
        if not takes_params:
            raise TypeError(
                f"{cls.__name__} declares params but its constructor takes no "
                "arguments; accept the params object in __init__."
            )
        return cls(params)


class DataSource(abc.ABC, Generic[TD, Q, A]):
    """Reads training data from the event store.

    `read_training` ≈ `PDataSource.readTraining(sc)` [U]; `read_eval` ≈
    `readEval` — returns k (training data, [(query, actual)]) folds.
    """

    @abc.abstractmethod
    def read_training(self, ctx: WorkflowContext) -> TD: ...

    def read_eval(
        self, ctx: WorkflowContext
    ) -> list[tuple[TD, Sequence[tuple[Q, A]]]]:
        raise NotImplementedError(
            f"{type(self).__name__} does not implement read_eval; "
            "evaluation is unavailable for this engine."
        )


class Preparator(abc.ABC, Generic[TD, PD]):
    """`PPreparator.prepare` [U]: TrainingData → PreparedData (feature
    extraction, id indexing, device-ready array packing)."""

    @abc.abstractmethod
    def prepare(self, ctx: WorkflowContext, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator):
    def prepare(self, ctx: WorkflowContext, training_data):
        return training_data


class Algorithm(abc.ABC, Generic[PD, M, Q, R]):
    """`P2LAlgorithm`/`PAlgorithm`/`LAlgorithm` collapsed [U].

    `train` should build jitted XLA programs under `ctx.mesh`; `predict`
    serves one query from an in-memory model (the serving hot path);
    `batch_predict` is the bulk-scoring path used by evaluation
    (`batchPredictBase` [U]) and by the serving micro-batcher — override
    it with a vmapped/jitted version for speed, the default just loops
    `predict`.
    """

    # Checkpoint-subdir tags this class passes to
    # ctx.algorithm_checkpoint_dir during train. Engine._ckpt_suffixes
    # keys duplicate detection on these so two DIFFERENT classes sharing
    # a tag (e.g. two ALS variants both tagged "als") get distinct
    # suffixes instead of purging each other's checkpoints. () means
    # "no persistent checkpoints" and falls back to per-class keying.
    checkpoint_tags: tuple = ()

    # True for algorithms whose predict is cheap enough (and needs no
    # per-user state) to answer under saturation — the serving plane's
    # degraded-mode fallback (e.g. a popularity model).
    degraded_capable: bool = False

    @abc.abstractmethod
    def train(self, ctx: WorkflowContext, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> R: ...

    def batch_predict(self, model: M, queries: Sequence[Q]) -> list[R]:
        return [self.predict(model, q) for q in queries]

    @classmethod
    def train_grid(cls, ctx: WorkflowContext, prepared_data: PD,
                   algos: Sequence["Algorithm"]) -> Optional[list[M]]:
        """Train N param variants of this algorithm as ONE device program
        (SURVEY.md §2.6 strategy 4's TPU-native form — the eval param grid
        batched instead of re-trained per cell).

        Return a model per entry of `algos` (instances of `cls` differing
        only in params), or None when this grid isn't batchable — the
        evaluator then falls back to sequential `train` calls. The default
        is not-batchable; algorithms with a grid-vmappable train (see
        templates/recommendation ALSAlgorithm → ops/als_grid) override."""
        return None


class Serving(abc.ABC, Generic[Q, R]):
    """`LServing.serve` [U]: combine per-algorithm predictions into one."""

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[R]) -> R: ...


class FirstServing(Serving):
    """`LFirstServing` [U]."""

    def serve(self, query, predictions):
        if not predictions:
            raise ValueError("No predictions to serve.")
        return predictions[0]


class AverageServing(Serving):
    """`LAverageServing` [U] — averages numeric predictions."""

    def serve(self, query, predictions):
        if not predictions:
            raise ValueError("No predictions to serve.")
        return sum(predictions) / len(predictions)


class PersistentModel(abc.ABC):
    """Models that persist themselves («controller/PersistentModel.scala»
    [U]) — e.g. large factor matrices checkpointed via orbax — instead of
    being pickled into the Models blob store.

    `save` returns True if the model handled its own persistence. The
    class must also provide `load(id, params)` (the reference's
    `PersistentModelLoader.apply`).
    """

    @abc.abstractmethod
    def save(self, instance_id: str, params: Params) -> bool: ...

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params) -> "PersistentModel": ...


class PersistentModelLoader:
    """Dispatch helper mirroring the reference loader object [U]."""

    @staticmethod
    def apply(cls: Type[PersistentModel], instance_id: str, params: Params):
        return cls.load(instance_id, params)


class SanityCheck(abc.ABC):
    """Optional hook («controller/SanityCheck.scala» [U]): training/prepared
    data and models may self-check after each DASE stage (unless
    --skip-sanity-check)."""

    @abc.abstractmethod
    def sanity_check(self) -> None: ...


def run_sanity_check(obj: Any, stage: str) -> None:
    if isinstance(obj, SanityCheck):
        log.info("SanityCheck %s (%s)", stage, type(obj).__name__)
        obj.sanity_check()


# Reference-spelling aliases (P = parallel/RDD, L = local in the original;
# one implementation here — SURVEY.md §7.1).
PDataSource = DataSource
LDataSource = DataSource
PPreparator = Preparator
LPreparator = Preparator
P2LAlgorithm = Algorithm
PAlgorithm = Algorithm
LAlgorithm = Algorithm
LServing = Serving
