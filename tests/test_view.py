"""Batch-view tests — parity with the reference's 0.9.x view layer
(«data/.../data/view/{LBatchView,PBatchView}.scala» — SURVEY.md §2.2 [U]):
windowed event snapshots, writeToPropsMap aggregation, per-entity ordered
folds, and our columnar device-feed variant."""

from datetime import datetime, timezone

import numpy as np

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.data.view import LBatchView, PBatchView
from predictionio_tpu.storage.base import App


def ts(h, m=0):
    return datetime(2026, 1, 1, h, m, 0, tzinfo=timezone.utc)


def _seed(storage):
    apps = storage.meta_apps()
    app_id = apps.insert(App(id=0, name="ViewApp"))
    events = storage.l_events()
    rows = [
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"plan": "free", "age": 30}), event_time=ts(1)),
        Event(event="$set", entity_type="user", entity_id="u1",
              properties=DataMap({"plan": "pro"}), event_time=ts(2)),
        Event(event="$unset", entity_type="user", entity_id="u1",
              properties=DataMap({"age": None}), event_time=ts(3)),
        Event(event="$set", entity_type="user", entity_id="u2",
              properties=DataMap({"plan": "free", "age": 22}), event_time=ts(2)),
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              properties=DataMap({"rating": 4.0}), event_time=ts(4)),
        Event(event="rate", entity_type="user", entity_id="u2",
              target_entity_type="item", target_entity_id="i2",
              properties=DataMap({"rating": 3.0}), event_time=ts(5)),
        Event(event="view", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i2",
              event_time=ts(6)),
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i2",
              properties=DataMap({"rating": 5.0}), event_time=ts(7)),
    ]
    for e in rows:
        events.insert(e, app_id)
    return app_id


class TestLBatchView:
    def test_events_ordered_and_windowed(self, memory_storage):
        _seed(memory_storage)
        view = LBatchView("ViewApp")
        assert [e.event_time for e in view.events] == sorted(
            e.event_time for e in view.events
        )
        assert len(view.events) == 8
        windowed = LBatchView("ViewApp", start_time=ts(4), until_time=ts(6))
        assert [e.event for e in windowed.events] == ["rate", "rate"]

    def test_aggregate_properties(self, memory_storage):
        _seed(memory_storage)
        props = LBatchView("ViewApp").aggregate_properties("user")
        assert props["u1"].to_dict() == {"plan": "pro"}  # age $unset
        assert props["u2"].to_dict() == {"plan": "free", "age": 22}

    def test_aggregate_by_entity_ordered(self, memory_storage):
        _seed(memory_storage)
        view = LBatchView("ViewApp")
        # last-rated-item per user: order matters (u1 rated i1 then i2)
        last = view.aggregate_by_entity_ordered(
            lambda e: e.event == "rate", None, lambda _, e: e.target_entity_id
        )
        assert last == {"u1": "i2", "u2": "i2"}
        counts = view.aggregate_by_entity_ordered(
            lambda e: e.event in ("rate", "view"), 0, lambda acc, _: acc + 1
        )
        assert counts == {"u1": 3, "u2": 1}


class TestPBatchView:
    def test_to_columns(self, memory_storage):
        _seed(memory_storage)
        cols = PBatchView("ViewApp").to_columns(value_key="rating")
        # special events excluded; default event vocabulary is sorted
        assert cols.event_names == ["rate", "view"]
        assert len(cols) == 4
        # decode back: the rate rows carry their ratings, the view row NaN
        rate = cols.event_codes == cols.event_names.index("rate")
        assert np.allclose(np.sort(cols.values[rate]), [3.0, 4.0, 5.0])
        assert np.isnan(cols.values[~rate]).all()
        users = cols.entity_bimap.from_index(cols.entity_ids)
        items = cols.target_bimap.from_index(cols.target_ids)
        assert set(zip(users, items, cols.event_names[0:1] * 4)) >= {
            ("u1", "i1", "rate"), ("u2", "i2", "rate")
        }
        # rows keep time order
        assert (np.diff(cols.times) >= 0).all()

    def test_to_columns_subset_vocabulary(self, memory_storage):
        _seed(memory_storage)
        cols = PBatchView("ViewApp").to_columns(event_names=["view"])
        assert len(cols) == 1 and cols.event_names == ["view"]
        assert cols.entity_bimap.from_index(cols.entity_ids) == ["u1"]

    def test_property_matrix(self, memory_storage):
        _seed(memory_storage)
        mat, bimap = PBatchView("ViewApp").property_matrix("user", ["age"])
        assert mat.shape == (2, 1)
        assert np.isnan(mat[bimap["u1"], 0])  # age was $unset
        assert mat[bimap["u2"], 0] == 22.0
