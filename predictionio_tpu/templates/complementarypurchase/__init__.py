"""Complementary Purchase template — market-basket association rules.

Parity with the upstream gallery template
«template-scala-parallel-complementarypurchase» [U]: `buy` events are
sessionized into baskets, pairwise "bought i → also buys j" rules are
mined with support/confidence/lift thresholds (co-occurrence counted as a
one-hot Gram on the MXU — ops/basket.py), and cart queries return top
complements per condition item.
"""

from predictionio_tpu.templates.complementarypurchase.engine import (
    AssociationAlgorithm,
    AssociationParams,
    ComplementaryPurchaseEngine,
    CPModel,
    DataSource,
    DataSourceParams,
    Preparator,
    PreparatorParams,
    PreparedData,
    Query,
    TrainingData,
)

__all__ = [
    "ComplementaryPurchaseEngine",
    "CPModel",
    "DataSource",
    "DataSourceParams",
    "Preparator",
    "PreparatorParams",
    "PreparedData",
    "TrainingData",
    "AssociationAlgorithm",
    "AssociationParams",
    "Query",
]
