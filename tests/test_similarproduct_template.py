"""Similar Product template end-to-end: view events + $set item categories
→ implicit ALS → item-item cosine queries with filters (SURVEY.md §2.4
Similar Product row; §7.2 step 7)."""

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.similarproduct.SimilarProductEngine"


def ingest_views(storage, app_name="SimApp", n_users=16, n_groups=2,
                 items_per_group=4):
    """Users in group g repeatedly view group-g items: items co-viewed
    within a group should come out more similar than across groups."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    for g in range(n_groups):
        for j in range(items_per_group):
            le.insert(
                Event(event="$set", entity_type="item", entity_id=f"g{g}i{j}",
                      properties=DataMap({"categories": [f"cat{g}"]})),
                app_id)
    for u in range(n_users):
        g = u % n_groups
        # each user views all but one item of their group (rotating holdout)
        for j in range(items_per_group):
            if j == u % items_per_group:
                continue
            le.insert(
                Event(event="view", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"g{g}i{j}"),
                app_id)


def variant_dict(app_name="SimApp", rank=4, iters=15):
    return {
        "id": "sim-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": "als", "params": {
            "rank": rank, "numIterations": iters, "lambda": 0.05,
            "alpha": 2.0, "seed": 1}}],
    }


class TestSimilarProductEndToEnd:
    def test_train_and_similar(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        r = engine.predict(ep, models, {"items": ["g0i0"], "num": 3})
        items = [s["item"] for s in r["itemScores"]]
        assert len(items) == 3
        assert "g0i0" not in items  # basket excluded
        # co-viewed group-0 items must outrank group-1 items
        assert set(items[:2]) <= {f"g0i{j}" for j in range(4)}
        scores = [s["score"] for s in r["itemScores"]]
        assert scores == sorted(scores, reverse=True)

    def test_filters(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=1)
        models = engine.train(ctx, ep)

        # whiteList restricts candidates
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "whiteList": ["g1i0", "g1i1"]})
        assert {s["item"] for s in r["itemScores"]} <= {"g1i0", "g1i1"}
        # blackList removes candidates
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "blackList": ["g0i1"]})
        assert "g0i1" not in {s["item"] for s in r["itemScores"]}
        # categories filter keeps only matching items
        r = engine.predict(ep, models, {
            "items": ["g0i0"], "num": 10, "categories": ["cat1"]})
        got = {s["item"] for s in r["itemScores"]}
        assert got and got <= {f"g1i{j}" for j in range(4)}

    def test_unknown_items_empty(self, memory_storage):
        ingest_views(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        r = engine.predict(ep, models, {"items": ["nope"], "num": 3})
        assert r == {"itemScores": []}

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptySim"))
        variant = EngineVariant.from_dict(variant_dict("EmptySim"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no view events"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)

    def test_template_engine_json_parses(self):
        import os

        from predictionio_tpu.workflow.workflow_utils import read_engine_json

        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "similarproduct", "engine.json")
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert ep.algorithm_params_list[0][0] == "als"
        assert ep.algorithm_params_list[0][1].rank == 10
