"""Fixture: a thread-spawning class with two textbook races.

`count` is read-modify-written without a lock from both the background
thread and a public method (lost updates); `items` is published outside
the lock that orders its sibling `log` write in the same functions —
readers pairing the two can see them torn (the history-store bug
shape).
"""

import threading


class RacyWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = {}
        self.log = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self.count += 1
            self.items["tick"] = self.count
            with self._lock:
                self.log.append(("tick", self.count))

    def poke(self):
        self.count += 1
        self.items["poke"] = self.count
        with self._lock:
            self.log.append(("poke", self.count))
