"""online_* telemetry families for the online-learning plane.

Module-level families (registered once on import, merged fleet-true by
the supervisor aggregate like every other family). The north-star series
is `online_event_to_servable_seconds`: observed once per folded event as
(swap time − event_time), i.e. the full event→servable path including
group-commit visibility, tail-poll latency, fold-in solve, and the hot
delta-swap. `bench.py --freshness` reads its p95.
"""

from predictionio_tpu.telemetry.registry import REGISTRY

# event→servable spans group-commit + poll interval + solve + swap, so
# the interesting range is tenths of a second up to the 5 s bar and a
# decade past it for regressions
_E2S_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 30.0)
_FOLD_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                 2.5, 5.0)

ONLINE_EVENTS_FOLDED = REGISTRY.counter(
    "online_events_folded_total",
    "Events consumed by the online plane and reflected in a served model")
ONLINE_ROWS_FOLDED = REGISTRY.counter(
    "online_rows_folded_total",
    "Factor rows re-solved by fold-in, by side", ("side",))
ONLINE_COLD_START_ROWS = REGISTRY.counter(
    "online_cold_start_rows_total",
    "Factor rows appended for never-seen entity ids, by side", ("side",))
ONLINE_SWAPS = REGISTRY.counter(
    "online_swaps_total",
    "Hot delta-swaps published into the served-state table", ("variant",))
ONLINE_STALE_SWAPS = REGISTRY.counter(
    "online_stale_swaps_total",
    "Delta-swaps dropped because a full /reload landed mid-fold (the "
    "batch is replayed against the new state on the next poll)")
ONLINE_FOLD_ERRORS = REGISTRY.counter(
    "online_fold_errors_total",
    "Fold passes that raised; the tail loop survives and replays")
ONLINE_FOLDIN_SECONDS = REGISTRY.histogram(
    "online_foldin_seconds",
    "Wall time of one fold pass (history gather + solves + swap)",
    buckets=_FOLD_BUCKETS)
ONLINE_EVENT_TO_SERVABLE = REGISTRY.histogram(
    "online_event_to_servable_seconds",
    "North star: event_time → served-model swap latency, one observation "
    "per folded event",
    buckets=_E2S_BUCKETS, exemplars=True)
ONLINE_FAMILY_FRESHNESS = REGISTRY.histogram(
    "online_family_event_to_servable_seconds",
    "Per-model-family slice of event→servable latency (family=als|"
    "sessionrec|…), one observation per folded event per family that "
    "folded it; bench.py --freshness reports the per-family p95 split",
    ("family",), buckets=_E2S_BUCKETS)
SESSION_WINDOWS_FOLDED = REGISTRY.counter(
    "session_windows_folded_total",
    "Per-user session windows rebuilt (and session embeddings "
    "recomputed) by the online session fold")
SESSION_COLD_ITEMS = REGISTRY.counter(
    "session_cold_items_total",
    "Distinct item ids dropped from session windows because the last "
    "retrain never embedded them (cold items fold in at the next "
    "retrain, mirroring ALS cold opposing rows)")
ONLINE_LAG = REGISTRY.gauge(
    "online_lag_seconds",
    "Age of the fold watermark at the end of the latest poll")
ONLINE_PARITY_DRIFT = REGISTRY.gauge(
    "online_parity_drift",
    "Max |served − re-solved| factor element over common rows at the "
    "latest full-retrain parity check, by variant", ("variant",))
ONLINE_PARITY_CHECKS = REGISTRY.counter(
    "online_parity_checks_total",
    "Full-retrain parity checks completed", ("variant",))
