"""Back-compat shim: the gates' shared AST helpers moved to
:mod:`predictionio_tpu.analysis.astutil` (the pio-lint engine's canonical
resolver, which also follows locally-assigned handler aliases like
``h = self._handle_query; router.post(..., h)``). Import from there;
this module just re-exports the old surface for existing callers.
"""

from __future__ import annotations

from predictionio_tpu.analysis.astutil import (  # noqa: F401
    attr_calls,
    function_defs,
    handlers_for,
    reachable_functions,
    registrations,
)

__all__ = ["attr_calls", "function_defs", "handlers_for",
           "reachable_functions", "registrations"]
