"""Product Ranking engine template (DASE components).

Parity with the upstream gallery template
«template-scala-parallel-productranking» [U]: rank a GIVEN list of items
for a user (e.g. re-order a landing page or a search result) by the
user's predicted preference, instead of searching the whole catalog.

Reuses the Recommendation template's data path and ALS training wholesale
(same events, same `ops/als.py` mesh-sharded train); only serving
differs: the query names the candidate items, scores come from one tiny
host-side dot product, and — matching the upstream contract — when the
model cannot rank (unknown user) the original item order comes back with
`"isOriginal": true`. Items unknown to the model keep their incoming
relative order after the ranked ones, at score 0.

Wire shapes:
    query:  {"user": "u1", "items": ["i3", "i1", "i9"]}
    result: {"itemScores": [{"item": "i1", "score": 3.2}, ...],
             "isOriginal": false}
"""

from __future__ import annotations

import numpy as np

from predictionio_tpu.controller import Engine, EngineFactory, FirstServing
from predictionio_tpu.models.als_model import ALSModel
from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm as _RecommendationALS,
    DataSource,
    DataSourceParams,
    Preparator,
    PreparedData,
    TrainingData,
)

Query = dict
PredictedResult = dict


class RankingALSAlgorithm(_RecommendationALS):
    """Recommendation's ALS train + ranking-specific serving."""

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        items = [str(i) for i in (query.get("items") or [])]
        user = str(query.get("user", ""))
        urow = model.user_ids.get(user)
        if urow is None or not items:
            # upstream contract: can't personalize → echo the original
            # order and say so
            return {"itemScores": [{"item": i, "score": 0.0}
                                   for i in items],
                    "isOriginal": True}
        uvec = model.user_factors[int(urow)]
        known_rows = [model.item_ids.get(i) for i in items]
        scored = []
        unknown = []
        for pos, (item, row) in enumerate(zip(items, known_rows)):
            if row is None:
                unknown.append((pos, item))
            else:
                scored.append(
                    (float(uvec @ model.item_factors[int(row)]), pos, item))
        # ranked items first (score desc, stable by incoming position),
        # then unknown items in their original relative order at score 0
        scored.sort(key=lambda t: (-t[0], t[1]))
        out = [{"item": item, "score": s} for s, _, item in scored]
        out += [{"item": item, "score": 0.0} for _, item in unknown]
        return {"itemScores": out, "isOriginal": False}


class ProductRankingEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            data_source_class_map=DataSource,
            preparator_class_map=Preparator,
            algorithm_class_map={"als": RankingALSAlgorithm},
            serving_class_map=FirstServing,
        )


__all__ = [
    "ProductRankingEngine",
    "RankingALSAlgorithm",
    "DataSource",
    "DataSourceParams",
    "Preparator",
    "PreparedData",
    "TrainingData",
    "Query",
]
