"""Python client SDK — the rebuild's L7 (SURVEY.md §1).

The reference keeps REST client SDKs in separate repos
(`PredictionIO/PredictionIO-Python-SDK` et al. — SURVEY.md §1 'L7 Client
SDKs' [U]); the rebuild ships one in-tree. API surface follows that SDK:

    from predictionio_tpu.sdk import EventClient, EngineClient
    ec = EventClient(access_key=K, url="http://localhost:7070")
    ec.create_event(event="rate", entity_type="user", entity_id="u1",
                    target_entity_type="item", target_entity_id="i1",
                    properties={"rating": 5})
    ec.set_user("u2", properties={"plan": "pro"})
    eng = EngineClient(url="http://localhost:8000")
    eng.send_query({"user": "u1", "num": 4})

Stdlib urllib only (SDKs must not drag server deps); raises
`NotFoundError` on 404 and `PredictionIOError` (with status + server
message) on any other non-2xx.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.parse
import uuid
from datetime import datetime, timezone
from typing import Any, Optional, Sequence, Union

from predictionio_tpu.telemetry import tracing


class PredictionIOError(Exception):
    """Non-2xx server response; `.status` and `.message` carry details."""

    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class NotFoundError(PredictionIOError):
    def __init__(self, message: str = "Not Found"):
        super().__init__(404, message)


def _format_time(t: Union[None, str, datetime]) -> Optional[str]:
    if t is None or isinstance(t, str):
        return t
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t.isoformat()


class _BaseClient:
    """Keep-alive transport: one persistent HTTP/1.1 connection per thread
    (a fresh TCP handshake per event caps SDK ingest at ~1k events/s;
    keep-alive measures ~5× that). Broken connections reconnect once."""

    def __init__(self, url: str, timeout: float = 10.0,
                 busy_retries: int = 2,
                 busy_backoff_base_s: float = 0.2,
                 busy_backoff_cap_s: float = 5.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        # 429/503 (shed / deadline pressure) retry posture: how many
        # replays after the first answer, and the jittered-exponential
        # backoff bounds between them. Server Retry-After can stretch a
        # wait up to the cap but never past it. busy_retries=0 restores
        # the old fail-fast behavior.
        self.busy_retries = busy_retries
        self.busy_backoff_base_s = busy_backoff_base_s
        self.busy_backoff_cap_s = busy_backoff_cap_s
        # Trace id echoed by the server on the most recent response —
        # the client-side half of end-to-end X-PIO-Trace-Id propagation.
        self.last_trace_id: Optional[str] = None
        parts = urllib.parse.urlsplit(self.url)
        if parts.scheme not in ("http", "https", ""):
            raise ValueError(
                f"Unsupported URL scheme in {url!r} (http/https only)")
        self._https = parts.scheme == "https"
        self._host = parts.hostname or "localhost"
        self._port = parts.port or (443 if self._https else 80)
        self._prefix = parts.path.rstrip("/")
        self._tl = threading.local()

    def _conn(self) -> tuple[http.client.HTTPConnection, bool]:
        """(connection, fresh): `fresh` is True when this call created it —
        retry policy depends on whether a keep-alive could be stale."""
        conn = getattr(self._tl, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._https
                   else http.client.HTTPConnection)
            conn = cls(self._host, self._port, timeout=self.timeout)
            self._tl.conn = conn
            return conn, True
        return conn, False

    def _drop_conn(self) -> None:
        conn = getattr(self._tl, "conn", None)
        if conn is not None:
            conn.close()
            self._tl.conn = None

    def close(self) -> None:
        """Close this thread's persistent connection (optional; idle
        connections are reaped by the server side too)."""
        self._drop_conn()

    def _busy_delay_s(self, busy_attempt: int, retry_after: Optional[str]
                      ) -> float:
        """Jittered exponential backoff for a 429/503 replay, stretched
        (never shrunk) by the server's Retry-After and capped either
        way — a malicious or confused header can't park the client."""
        delay = min(self.busy_backoff_cap_s,
                    self.busy_backoff_base_s * (2 ** busy_attempt))
        delay *= 0.5 + random.random()
        if retry_after:
            try:
                delay = max(delay, min(float(retry_after),
                                       self.busy_backoff_cap_s))
            except ValueError:
                pass
        return delay

    def _request(self, method: str, path: str,
                 query: Optional[dict] = None,
                 body: Optional[Any] = None,
                 idempotent: bool = False,
                 retry_busy: Optional[bool] = None) -> Any:
        q = {k: v for k, v in (query or {}).items() if v is not None}
        target = self._prefix + path
        if q:
            target += "?" + urllib.parse.urlencode(q)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        # Every call carries a trace id: the active context's when the
        # caller opened `tracing.trace(...)`, else a fresh one per request.
        # The retry loop reuses the same id — a replay is the same request.
        sent_trace_id = tracing.inject_headers(headers)
        idempotent = idempotent or method in ("GET", "DELETE")
        # 429/503 replays follow idempotency unless the caller overrides:
        # the server answered, so the request may re-run later — only
        # safe when re-running is provably the same request (see
        # create_event for the /events.json carve-out).
        if retry_busy is None:
            retry_busy = idempotent
        busy_attempt = 0
        while True:
            for attempt in (0, 1):
                conn, fresh = self._conn()
                sent = False
                try:
                    conn.request(method, target, data, headers)
                    sent = True
                    resp = conn.getresponse()
                    payload = resp.read()
                    status = resp.status
                    self.last_trace_id = (resp.getheader(tracing.TRACE_HEADER)
                                          or sent_trace_id)
                    break
                except (http.client.HTTPException, ConnectionError, OSError) as e:
                    self._drop_conn()
                    # Retry exactly once, and ONLY on a reused keep-alive where
                    # retrying is safe: failure at send time (request bytes
                    # never completed), or — for idempotent requests only —
                    # RemoteDisconnected from getresponse (the stale keep-alive
                    # race). A close without a response does NOT prove the
                    # server skipped the request (it may have died after
                    # processing but before replying), so non-idempotent POSTs
                    # are never replayed on it; event POSTs are made idempotent
                    # by the client-set eventId (see create_event), which turns
                    # a replay into a duplicate-rejection by the store's
                    # uniqueness constraint. Timeouts and mid-response failures
                    # are never retried.
                    can_retry = (not attempt and not fresh
                                 and (not sent
                                      or (idempotent and isinstance(
                                          e, http.client.RemoteDisconnected))))
                    if not can_retry:
                        raise
            # Admission shed (429) and deadline pressure (503) are the
            # server saying "later, not never": back off and replay, up
            # to busy_retries times. Both arrive BEFORE the request took
            # effect on the serving plane, but a replay is still a
            # re-send, so the retry_busy gate above applies.
            if (status in (429, 503) and retry_busy
                    and busy_attempt < self.busy_retries):
                time.sleep(self._busy_delay_s(
                    busy_attempt, resp.getheader("Retry-After")))
                busy_attempt += 1
                continue
            break
        if 300 <= status < 400:
            # the reference stack never redirects; auto-following would
            # silently re-send bodies across hosts — surface it instead
            raise PredictionIOError(
                status, "unexpected redirect to "
                        f"{resp.getheader('Location', '?')} (not followed)")
        if status >= 400:
            detail = payload.decode(errors="replace")
            try:
                detail = json.loads(detail).get("message", detail)
            except (json.JSONDecodeError, AttributeError):
                pass
            if status == 404:
                raise NotFoundError(detail)
            raise PredictionIOError(status, detail)
        return json.loads(payload) if payload else None


class EventClient(_BaseClient):
    """Client for the event server (:7070)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0,
                 **transport):
        super().__init__(url, timeout, **transport)
        self.access_key = access_key
        self.channel = channel

    def _auth(self, extra: Optional[dict] = None) -> dict:
        q = {"accessKey": self.access_key, "channel": self.channel}
        q.update(extra or {})
        return q

    # -- core event API ----------------------------------------------------

    def create_event(self, event: str, entity_type: str, entity_id: str,
                     target_entity_type: Optional[str] = None,
                     target_entity_id: Optional[str] = None,
                     properties: Optional[dict] = None,
                     event_time: Union[None, str, datetime] = None,
                     event_id: Optional[str] = None) -> str:
        """POST /events.json → eventId.

        When `event_id` is not given, a fresh uuid is set client-side so
        the POST is idempotent: a stale-keep-alive replay that hits an
        already-committed first attempt is rejected by the store's
        eventId uniqueness constraint, which this client maps back to
        success (the id is fresh, so the only possible duplicate is our
        own earlier attempt). Caller-supplied ids get no such mapping —
        a duplicate then is a real error the caller must see.
        """
        generated = event_id is None
        eid = event_id or uuid.uuid4().hex
        body: dict[str, Any] = {
            "event": event,
            "entityType": entity_type,
            "entityId": entity_id,
            "eventId": eid,
        }
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = target_entity_id
        if properties:
            body["properties"] = properties
        if event_time:
            body["eventTime"] = _format_time(event_time)
        try:
            # only a client-generated id is replay-safe: its duplicate
            # rejection provably means our own earlier attempt committed.
            # A caller-supplied id gets no retry — a replay's 400 would be
            # indistinguishable from the caller's own real duplicate.
            #
            # Busy (429/503) replays are the inverse: OFF for generated
            # ids — a generated id proves OUR replay is harmless, but the
            # analytics semantics of single-event appends mean a delayed
            # replay can land out of order behind the caller's NEXT event,
            # so only a caller who brought an explicit idempotency key
            # (event_id) has declared the event safe to re-send late.
            out = self._request("POST", "/events.json", self._auth(), body,
                                idempotent=generated,
                                retry_busy=event_id is not None)
        except PredictionIOError as e:
            if generated and e.status == 400 and "duplicate eventId" in e.message:
                return eid
            raise
        return out["eventId"]

    def create_reward(self, user: str, variant: str, reward: float,
                      event_time: Union[None, str, datetime] = None,
                      event_id: Optional[str] = None) -> str:
        """POST a `$reward` event crediting `reward` ∈ [0, 1] to one
        engine variant (the experiment plane's bandit feedback —
        docs/experimentation.md). Returns the eventId.

        Rewards ride the full idempotent busy-retry path: unlike a
        plain append, a `$reward` is keyed by its eventId and carries
        its own variant/value, so a late replay after a 429/503 cannot
        land "behind" anything — re-sending is always safe. The id is
        therefore ALWAYS pinned client-side (caller-supplied or
        generated here), busy replays are ON, and a duplicate rejection
        for an id generated in this call maps back to success (our own
        earlier attempt committed)."""
        generated = event_id is None
        eid = event_id or uuid.uuid4().hex
        body: dict[str, Any] = {
            "event": "$reward",
            "entityType": "user",
            "entityId": user,
            "eventId": eid,
            "properties": {"variant": variant, "reward": float(reward)},
        }
        if event_time:
            body["eventTime"] = _format_time(event_time)
        try:
            out = self._request("POST", "/events.json", self._auth(), body,
                                idempotent=True, retry_busy=True)
        except PredictionIOError as e:
            if generated and e.status == 400 and "duplicate eventId" in e.message:
                return eid
            raise
        return out["eventId"]

    def create_batch_events(self, events: Sequence[dict]) -> list[dict]:
        """POST /batch/events.json (≤50 events) → per-event results.

        Events lacking an `eventId` get a client-generated uuid (same
        replay-safety contract as `create_event`); a duplicate rejection
        for an id generated in this call means the row committed on a
        previous send attempt and is reported as 201.
        """
        generated: set[str] = set()
        payload = []
        for d in events:
            d = dict(d)
            if not d.get("eventId"):
                d["eventId"] = uuid.uuid4().hex
                generated.add(d["eventId"])
            payload.append(d)
        # replay-safe only when EVERY row's id was generated here (a
        # replayed caller-set row would surface as a spurious 400)
        results = self._request("POST", "/batch/events.json", self._auth(),
                                payload,
                                idempotent=len(generated) == len(payload))
        for d, r in zip(payload, results):
            if (d["eventId"] in generated and isinstance(r, dict)
                    and r.get("status") == 400
                    and "duplicate eventId" in r.get("message", "")):
                r.clear()
                r.update({"status": 201, "eventId": d["eventId"]})
        return results

    def get_event(self, event_id: str) -> dict:
        return self._request(
            "GET", f"/events/{urllib.parse.quote(event_id)}.json", self._auth())

    def delete_event(self, event_id: str) -> None:
        self._request(
            "DELETE", f"/events/{urllib.parse.quote(event_id)}.json",
            self._auth())

    def find_events(self, start_time=None, until_time=None,
                    entity_type: Optional[str] = None,
                    entity_id: Optional[str] = None,
                    event: Optional[str] = None,
                    target_entity_type: Optional[str] = None,
                    target_entity_id: Optional[str] = None,
                    limit: Optional[int] = None,
                    reversed: bool = False) -> list[dict]:
        """GET /events.json with the reference's filter params."""
        return self._request("GET", "/events.json", self._auth({
            "startTime": _format_time(start_time),
            "untilTime": _format_time(until_time),
            "entityType": entity_type,
            "entityId": entity_id,
            "event": event,
            "targetEntityType": target_entity_type,
            "targetEntityId": target_entity_id,
            "limit": limit,
            "reversed": "true" if reversed else None,
        }))

    def get_status(self) -> dict:
        return self._request("GET", "/")

    def get_stats(self) -> dict:
        """GET /stats.json (server must run with --stats)."""
        return self._request("GET", "/stats.json", self._auth())

    # -- entity-property conveniences (official SDK surface) ---------------

    def set_user(self, uid: str, properties: Optional[dict] = None,
                 event_time=None) -> str:
        return self.create_event("$set", "user", uid,
                                 properties=properties or {},
                                 event_time=event_time)

    def unset_user(self, uid: str, properties: dict, event_time=None) -> str:
        return self.create_event("$unset", "user", uid,
                                 properties=properties, event_time=event_time)

    def delete_user(self, uid: str, event_time=None) -> str:
        return self.create_event("$delete", "user", uid,
                                 event_time=event_time)

    def set_item(self, iid: str, properties: Optional[dict] = None,
                 event_time=None) -> str:
        return self.create_event("$set", "item", iid,
                                 properties=properties or {},
                                 event_time=event_time)

    def unset_item(self, iid: str, properties: dict, event_time=None) -> str:
        return self.create_event("$unset", "item", iid,
                                 properties=properties, event_time=event_time)

    def delete_item(self, iid: str, event_time=None) -> str:
        return self.create_event("$delete", "item", iid,
                                 event_time=event_time)

    def record_user_action_on_item(self, action: str, uid: str, iid: str,
                                   properties: Optional[dict] = None,
                                   event_time=None) -> str:
        return self.create_event(action, "user", uid,
                                 target_entity_type="item",
                                 target_entity_id=iid,
                                 properties=properties,
                                 event_time=event_time)


class EngineClient(_BaseClient):
    """Client for a deployed engine's prediction server (:8000)."""

    def __init__(self, url: str = "http://localhost:8000",
                 timeout: float = 10.0, **transport):
        super().__init__(url, timeout, **transport)

    def send_query(self, data: dict) -> dict:
        """POST /queries.json → PredictedResult. Queries are side-effect
        free, so the request is idempotent: stale-keep-alive replays and
        busy (429/503) backoff-retries both apply."""
        return self._request("POST", "/queries.json", body=data,
                             idempotent=True)
