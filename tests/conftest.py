"""Test harness config.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax is
imported anywhere, so SPMD/sharding tests get real 8-device semantics
without TPU hardware (SURVEY.md §4.2 note: this beats the reference's
`local[n]` SparkContext trick because the collectives actually run).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# keep in-process template trains from writing bucket caches into the
# real ~/.pio_tpu; cache-specific tests re-enable it in subprocess envs
os.environ["PIO_BUCKET_CACHE"] = "0"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize registers the axon TPU backend (and imports
# jax) before this file runs, so the env var alone is too late — override
# the already-initialized config too. Must happen before any backend use.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def memory_storage():
    """A fresh in-memory Storage wired as the process singleton."""
    from predictionio_tpu.storage.registry import SourceConfig, Storage, StorageConfig

    src = SourceConfig(name="TEST", type="memory")
    storage = Storage(StorageConfig(metadata=src, modeldata=src, eventdata=src))
    Storage.reset(storage)
    yield storage
    storage.close()
    Storage.reset(None)
