"""Supervisor control plane for the SO_REUSEPORT serving pool.

This module merges the lifecycle halves that used to be split between
`workflow/worker_pool.py` (fork + reap) and `workflow/create_server.py`
(serve + reload) into one control loop that owns the pool end to end —
the ROADMAP item-5 refactor. Three responsibilities:

**Autoscaling.** Workers heartbeat their admission in-flight count and
their worst 5m `slo_*` burn rate over the supervisor pipe; the control
tick resizes the pool within `[min_workers, max_workers]` — sustained
queue pressure or elevated burn spawns a worker, sustained idleness
drains one (SIGUSR2: stop accepting, finish in-flight, exit). While the
pool is resizing the admission planes keep shedding with 429/503 +
Retry-After, so resize never queues into collapse.

**Rolling deploys.** `/reload` (or SIGHUP to the supervisor) swaps
engine versions worker-by-worker with drain-then-reload semantics:
SIGUSR1 makes one worker stop accepting (closing its listener removes
it from the kernel's SO_REUSEPORT hash — new connections go to its
peers; established keep-alive connections keep being served), wait for
in-flight to hit zero or the drain deadline, hot-swap the served state,
health-check `/metrics`, and re-open the listener (the supervisor's
never-listening reservation socket guarantees the rebind). One worker
at a time ⇒ a version swap under load completes with zero non-2xx
responses — drilled by `tests/test_worker_pool.py` and
`bench.py --rolling-deploy`.

**Self-healing.** The ready-fd channel is now a persistent heartbeat
pipe (40-byte atomic messages). The tick detects death (reaped), hang
(heartbeat silence, or in-flight > 0 with zero completions past the
hang timeout → SIGKILL), and sick workers (error-ratio or burn-rate
over threshold → drain + restart). Restarts use jittered exponential
backoff, and a per-slot circuit breaker opens after N rapid failures
instead of crash-looping; a pool whose every slot trips its breaker
before any worker was ever ready fails fast with exit code 1 (the old
fail-fast contract, now with N retries of grace).

Chaos drills for all of the above live in `runtime/gate.py`
(`quality.py --chaos-gate`), armed through `utils/faults.py` runtime
modes (`delay:<ms>`, `error`) and `PIO_SUPERVISOR_WORKER_FAULTS`.

Everything is configured by `PIO_SUPERVISOR_*` env vars (table in
docs/operations.md § Supervisor) so posture crosses the fork the same
way the serving/ingest planes' env posture does.
"""

from __future__ import annotations

import dataclasses
import importlib
import logging
import os
import random
import signal
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.telemetry import aggregate
from predictionio_tpu.telemetry import history as metrics_history
from predictionio_tpu.telemetry import middleware as telemetry_middleware
from predictionio_tpu.telemetry import slo
from predictionio_tpu.telemetry.registry import REGISTRY
from predictionio_tpu.utils import faults
from predictionio_tpu.utils.http import HttpService, JsonRequestHandler

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Control-pipe protocol: worker → supervisor, fixed 40-byte messages.
# Pipe writes ≤ PIPE_BUF (4096) are atomic, so concurrent writers (the
# heartbeat thread and a drain thread) never interleave, and the reader
# always gets whole messages.

MSG_FMT = "!iiqqqq"  # (kind, pid, a, b, c, d)
MSG_SIZE = struct.calcsize(MSG_FMT)

MSG_READY = 1      # a = server port, b = metrics-snapshot port
MSG_HEARTBEAT = 2  # a = in-flight, b = completed, c = bad, d = burn×1000
MSG_RELOADED = 3   # a = drain ms, b = 1 healthy / 0 failed
MSG_DRAINED = 4    # a = drain ms (scale-down drain finished, exiting)

# legacy alias kept for the old ready-mark name used around the tree
_READY_FMT = MSG_FMT


def pack_msg(kind: int, pid: int, a: int = 0, b: int = 0, c: int = 0,
             d: int = 0) -> bytes:
    return struct.pack(MSG_FMT, kind, pid, a, b, c, d)


def unpack_msg(buf: bytes) -> Tuple[int, int, int, int, int, int]:
    return struct.unpack(MSG_FMT, buf)


# ---------------------------------------------------------------------------
# Telemetry. The worker_pool_* family keeps its historical names (dashboards
# and tests read them); the supervisor_* family is the new control-plane
# view required by the runbook.

POOL_WORKERS = REGISTRY.gauge(
    "worker_pool_workers", "Live workers in the SO_REUSEPORT pool")
POOL_SPAWNED = REGISTRY.counter(
    "worker_pool_spawned_total", "Workers forked over the pool's lifetime")
POOL_RESPAWNS = REGISTRY.counter(
    "worker_pool_respawns_total", "Workers respawned after dying ready")
POOL_STARTUP_FAILURES = REGISTRY.counter(
    "worker_pool_startup_failures_total",
    "Workers that died before ever becoming ready")

SUP_WORKERS = REGISTRY.gauge(
    "supervisor_workers",
    "Pool size by state (target = slots, live = forked, ready = serving)",
    labelnames=("state",))
SUP_RESTARTS = REGISTRY.counter(
    "supervisor_restarts_total",
    "Worker restarts initiated by the supervisor, by detected cause",
    labelnames=("reason",))
SUP_SCALE_EVENTS = REGISTRY.counter(
    "supervisor_scale_events_total",
    "Autoscaler resize decisions", labelnames=("direction",))
SUP_DRAIN_SECONDS = REGISTRY.histogram(
    "supervisor_drain_seconds",
    "Time a worker spent draining (accept paused → reloaded/exited)")
SUP_BREAKER_STATE = REGISTRY.gauge(
    "supervisor_breaker_state",
    "Per-slot circuit breaker (0 closed, 1 open, 2 half-open)",
    labelnames=("slot",))
SUP_ROLLING = REGISTRY.counter(
    "supervisor_rolling_reloads_total",
    "Rolling (worker-by-worker drain-then-reload) deploys started")
# Instantaneous autoscaler inputs, published every tick so the metrics
# history store can smooth them — the autoscaler reads the 1m/5m means
# back instead of acting on a single tick's point read.
SUP_POOL_UTIL = REGISTRY.gauge(
    "supervisor_pool_utilization",
    "Mean ready-worker in-flight / per-worker queue budget")
SUP_POOL_BURN = REGISTRY.gauge(
    "supervisor_pool_burn_avg",
    "Mean ready-worker 5m SLO burn rate")


# ---------------------------------------------------------------------------
# Config

_TRUTHY = {"1", "true", "yes", "on"}


def _env(name: str) -> Optional[str]:
    return os.environ.get(f"PIO_SUPERVISOR_{name}")


@dataclasses.dataclass
class SupervisorConfig:
    """Pool posture; every field resolves from `PIO_SUPERVISOR_<FIELD>`
    (upper-cased) so it crosses the fork like the serving/ingest env
    posture does. min/max_workers of 0 mean "the --workers count"."""

    min_workers: int = 0
    max_workers: int = 0
    poll_interval_s: float = 1.0       # control tick
    heartbeat_interval_s: float = 0.5  # worker → supervisor
    heartbeat_timeout_s: float = 5.0   # silence ⇒ process wedged ⇒ SIGKILL
    hang_timeout_s: float = 4.0        # in-flight>0, no completions ⇒ hung
    drain_deadline_s: float = 5.0      # max wait for in-flight to reach 0
    breaker_threshold: int = 3         # rapid failures before breaker opens
    breaker_reset_s: float = 30.0      # open → half-open retry window
    backoff_base_s: float = 0.5        # jittered exponential respawn backoff
    backoff_cap_s: float = 8.0
    rapid_fail_s: float = 30.0         # died sooner than this after ready ⇒ rapid
    scale_up_util: float = 0.5         # avg in-flight / queue budget
    scale_down_util: float = 0.05
    scale_up_burn: float = 6.0         # avg 5m burn that triggers scale-up
    scale_stable_ticks: int = 2        # consecutive ticks before scaling up
    scale_down_stable_s: float = 30.0  # sustained idleness before scale-down
    scale_up_window_s: float = 60.0    # smoothing window for scale-up signals
    scale_down_window_s: float = 300.0  # smoothing window for scale-down
    error_restart_ratio: float = 0.5   # bad/total over the error window
    error_min_requests: int = 8        # min window traffic for ratio/burn rules
    error_window_s: float = 5.0
    burn_restart: float = 30.0         # worker 5m burn that forces a restart
    burn_grace_s: float = 2.0          # ignore burn this soon after ready
    control_ip: str = "127.0.0.1"
    control_port: Optional[int] = 0    # None disables the control endpoint
    worker_faults: str = ""            # "spawn_idx:PIO_FAULTS-spec;..." (drills)

    @classmethod
    def from_env(cls) -> "SupervisorConfig":
        cfg = cls()
        for f in dataclasses.fields(cls):
            if f.name == "control_port":
                continue
            raw = _env(f.name.upper())
            if raw is None:
                continue
            try:
                if f.type in ("int", int):
                    setattr(cfg, f.name, int(raw))
                elif f.type in ("float", float):
                    setattr(cfg, f.name, float(raw))
                else:
                    setattr(cfg, f.name, raw)
            except ValueError:
                log.warning("ignoring unparseable PIO_SUPERVISOR_%s=%r",
                            f.name.upper(), raw)
        raw = _env("PORT")
        if raw is not None:
            raw = raw.strip().lower()
            if raw in ("off", "none", "disabled"):
                cfg.control_port = None
            else:
                try:
                    port = int(raw)
                    cfg.control_port = None if port < 0 else port
                except ValueError:
                    log.warning("ignoring unparseable PIO_SUPERVISOR_PORT=%r",
                                raw)
        return cfg


def parse_worker_faults(spec: str) -> Dict[int, str]:
    """`"4:serving.pre_dispatch=delay:500;5:worker.startup"` →
    {4: "serving.pre_dispatch=delay:500", 5: "worker.startup"} — a
    PIO_FAULTS value keyed by global spawn index, set in that child's
    environment only. The chaos gate uses this to arm the Nth respawn."""
    out: Dict[int, str] = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        idx, _, fault = part.partition(":")
        out[int(idx)] = fault
    return out


def backoff_s(failures: int, base_s: float, cap_s: float,
              rng: Optional[random.Random] = None) -> float:
    """Jittered (±50%) exponential backoff: base·2^(failures−1), capped.
    Full jitter on the high half so simultaneous crashers decorrelate."""
    r = rng or random
    raw = min(cap_s, base_s * (2 ** max(0, failures - 1)))
    return raw * (0.5 + r.random())


class CircuitBreaker:
    """Per-slot crash-loop protection. `record_failure` counts rapid
    failures; after `threshold` the breaker opens for `reset_s` (no
    spawns). The first spawn after the window is the half-open probe;
    a READY mark closes the breaker."""

    CLOSED, OPEN, HALF_OPEN = 0, 1, 2

    def __init__(self, threshold: int, reset_s: float):
        self.threshold = threshold
        self.reset_s = reset_s
        self.failures = 0
        self.open_until = 0.0
        self.half_open = False

    def record_failure(self, now: float, rapid: bool) -> None:
        # only the reap path (supervisor main loop) calls this; the reader
        # thread's record_success does plain stores, and _on_death's
        # ready-drain beat sequences a dying worker's READY before its
        # failure is counted
        # pio-lint: disable=race-shared-state
        self.failures = self.failures + 1 if rapid else 1
        self.half_open = False
        if self.failures >= self.threshold:
            self.open_until = now + self.reset_s

    def record_success(self) -> None:
        self.failures = 0
        self.open_until = 0.0
        self.half_open = False

    def allows_spawn(self, now: float) -> bool:
        if now < self.open_until:
            return False
        if self.open_until:
            self.half_open = True  # probing after the open window
        return True

    def state(self, now: float) -> int:
        if now < self.open_until:
            return self.OPEN
        if self.half_open:
            return self.HALF_OPEN
        return self.CLOSED


# ---------------------------------------------------------------------------
# Worker side

def _resolve_factory():
    """`PIO_SUPERVISOR_FACTORY=module:callable` overrides the server the
    workers build — the chaos gate injects a stub that serves through the
    real ServingPlane without loading jax or a trained model. Returns
    (factory, is_default)."""
    spec = os.environ.get("PIO_SUPERVISOR_FACTORY", "").strip()
    if spec:
        mod, _, attr = spec.partition(":")
        return getattr(importlib.import_module(mod), attr), False

    def _default(config, supervisor_pid):
        from predictionio_tpu.workflow.create_server import PredictionServer
        return PredictionServer(config, reuse_port=True,
                                supervisor_pid=supervisor_pid)

    return _default, True


def _query_totals(server_name: str) -> Tuple[int, int]:
    """(completed, bad) request totals for this worker's /queries.json,
    summed from the registry. Only the query route counts as progress:
    `/metrics` scrapes and `GET /` probes are served by independent
    handler threads and would mask a hung dispatch."""
    fam = REGISTRY.get("http_requests_total")
    total = bad = 0
    if fam is None:
        return 0, 0
    for key, value in fam.collect():
        srv, _method, route, status = key
        if srv != server_name or route != "/queries.json":
            continue
        n = int(value)
        total += n
        try:
            code = int(status)
        except ValueError:
            continue
        if code >= 500 or code in (429, 503):
            bad += n
    return total, bad


class _CtlChannel:
    """Serialized writes on the worker's end of the control pipe."""

    def __init__(self, fd: int):
        self._fd = fd
        self._lock = threading.Lock()

    def send(self, kind: int, a: int = 0, b: int = 0, c: int = 0,
             d: int = 0) -> None:
        msg = pack_msg(kind, os.getpid(), a, b, c, d)
        try:
            with self._lock:
                os.write(self._fd, msg)
        except OSError:
            pass  # supervisor gone; SIGTERM will follow


def _worker_main(config, supervisor_pid: int, ctl_fd: int,
                 cfg: SupervisorConfig) -> int:
    """Runs inside a forked child: build the server, report readiness,
    heartbeat, serve until told to stop.

    Signals: SIGTERM → graceful stop; SIGHUP → plain hot reload;
    SIGUSR1 → drain-then-reload in place (rolling deploy leg);
    SIGUSR2 → drain-then-exit (scale-down)."""
    ctl = _CtlChannel(ctl_fd)
    # The fork copied the parent's registry: zero inherited counters so
    # this worker's series (and the fleet merge summing them) reflect
    # only its own life, and re-label pio_worker for this slot.
    aggregate.reset_inherited_counters()
    aggregate.refresh_worker_info()
    snapshot_srv: Optional[aggregate.SnapshotServer] = None
    try:
        faults.inject("worker.startup")  # crash-loop / breaker drills
        factory, is_default = _resolve_factory()
        server = factory(config, supervisor_pid)
        snapshot_srv = aggregate.SnapshotServer()
    except Exception as e:
        print(f"Deploy failed in worker {os.getpid()}: {e}", file=sys.stderr)
        sys.stderr.flush()
        os.close(ctl_fd)
        return 1

    stop = threading.Event()
    name = server.server_name
    in_flight_child = telemetry_middleware.HTTP_IN_FLIGHT.labels(server=name)

    def _serving_in_flight() -> int:
        plane = getattr(server, "serving", None)
        return plane.admission.admitted if plane is not None else 0

    def _quiesce(deadline_s: float) -> None:
        # Request quiescence, not connection count: established keep-alive
        # connections stay parked on this worker — what must reach zero is
        # work in progress. Three gauges cover both transports: HTTP
        # handlers running (in-flight), admitted queries, and — on the
        # event-loop transport — requests the loop has parsed but not
        # fully answered (dispatched to a worker, or pipelined behind one
        # and waiting their turn), which no handler-level gauge sees yet.
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if in_flight_child.value <= 0 and _serving_in_flight() <= 0 \
                    and server.busy_requests() <= 0:
                return
            time.sleep(0.02)

    def _healthy() -> bool:
        # the /metrics health-check: the exact text a scrape would see
        # must render, and the server-specific check (served state
        # present) must pass, before the worker re-enters the pool
        try:
            slo.refresh()
            if not REGISTRY.render():
                return False
            check = getattr(server, "health_check", None)
            return bool(check()) if check is not None else True
        except Exception:
            log.exception("health check failed")
            return False

    def _do_drain_reload() -> None:
        t0 = time.monotonic()
        ok = 1
        try:
            server.pause_accept()
            _quiesce(cfg.drain_deadline_s)
            try:
                server.reload()
            except Exception:
                log.exception("drain-reload: reload failed; serving the "
                              "previous instance")
                ok = 0
            if not _healthy():
                ok = 0
            server.resume_accept()
        except Exception:
            # a worker that cannot re-open its listener is dead weight;
            # exit nonzero and let the supervisor respawn a fresh one
            log.exception("drain-reload failed fatally; exiting for respawn")
            os._exit(1)
        ctl.send(MSG_RELOADED, int((time.monotonic() - t0) * 1000), ok)

    def _do_drain_exit() -> None:
        t0 = time.monotonic()
        try:
            server.pause_accept()
            _quiesce(cfg.drain_deadline_s)
        except Exception:
            log.exception("drain-exit: pause failed; exiting anyway")
        ctl.send(MSG_DRAINED, int((time.monotonic() - t0) * 1000))
        stop.set()

    def _sig_thread(fn):
        # signal handlers run between bytecodes on the main thread; the
        # actual work happens off-thread so serving never blocks
        def handler(signum, frame):
            threading.Thread(target=fn, daemon=True).start()
        return handler

    signal.signal(signal.SIGHUP, _sig_thread(server.reload))
    signal.signal(signal.SIGUSR1, _sig_thread(_do_drain_reload))
    signal.signal(signal.SIGUSR2, _sig_thread(_do_drain_exit))
    signal.signal(signal.SIGTERM, lambda s, f: stop.set())
    signal.signal(signal.SIGINT, lambda s, f: stop.set())

    def _heartbeat_loop() -> None:
        while not stop.is_set():
            completed, bad = _query_totals(name)
            burn, _ = slo.current_burn(name, "/queries.json")
            ctl.send(MSG_HEARTBEAT, _serving_in_flight(), completed, bad,
                     int(burn * 1000))
            stop.wait(cfg.heartbeat_interval_s)

    ctl.send(MSG_READY, server.port, snapshot_srv.port)
    server.start()
    threading.Thread(target=_heartbeat_loop, daemon=True,
                     name="supervisor-heartbeat").start()
    stop.wait()
    snapshot_srv.close()
    server.shutdown()
    if is_default:
        from predictionio_tpu.storage.registry import Storage
        Storage.get().close()
    sys.stdout.flush()
    return 0


# ---------------------------------------------------------------------------
# Supervisor side

class _Slot:
    """One worker seat: current process, heartbeat view, breaker."""

    def __init__(self, idx: int, cfg: SupervisorConfig):
        self.idx = idx
        self.breaker = CircuitBreaker(cfg.breaker_threshold,
                                      cfg.breaker_reset_s)
        self.pid: Optional[int] = None
        self.spawn_index = -1
        self.ready = False
        self.port = 0
        self.snapshot_port = 0  # worker's loopback metrics-snapshot socket
        self.spawned_at = 0.0
        self.ready_at = 0.0
        self.next_spawn_at: Optional[float] = 0.0  # None = no spawn pending
        self.draining_out = False   # scale-down in progress
        self.rolling = False        # drain-reload in progress
        self.reload_evt: Optional[threading.Event] = None
        self.kill_at: Optional[float] = None  # SIGTERM → SIGKILL escalation
        self.kill_reason: Optional[str] = None
        # heartbeat view
        self.last_hb = 0.0
        self.in_flight = 0
        self.completed = 0
        self.bad = 0
        self.burn = 0.0
        self.progress_at = 0.0
        # (completed, bad) snapshots for the error-ratio window
        self.window: List[Tuple[int, int]] = []

    def reset_process_view(self) -> None:
        self.pid = None
        self.ready = False
        self.port = 0
        self.snapshot_port = 0
        self.rolling = False
        self.kill_at = None
        self.in_flight = 0
        self.completed = 0
        self.bad = 0
        self.burn = 0.0
        self.window = []
        if self.reload_evt is not None:
            self.reload_evt.set()  # don't stall a roll on a dead worker


class Supervisor:
    """Owns the pool: reservation socket, fork/reap, heartbeats, the
    control tick (self-heal, autoscale, rolling deploys), and the
    control endpoint. `run()` blocks until shutdown and returns the
    `pio deploy` exit code."""

    def __init__(self, config, n_workers: int,
                 cfg: Optional[SupervisorConfig] = None):
        self.config = config
        self.cfg = cfg or SupervisorConfig.from_env()
        if self.cfg.min_workers <= 0:
            self.cfg.min_workers = n_workers
        if self.cfg.max_workers <= 0:
            self.cfg.max_workers = max(n_workers, self.cfg.min_workers)
        self.n_workers = max(n_workers, 1)
        self._lock = threading.Lock()
        self._slots: List[_Slot] = []
        self._by_pid: Dict[int, _Slot] = {}
        self._slot_seq = 0
        self._spawn_counter = 0
        self._worker_faults = parse_worker_faults(self.cfg.worker_faults)
        self._rng = random.Random()
        self._shutting_down = False
        self._ever_ready = False
        self._roll_requested = False
        self._rolling = False
        self._done = threading.Event()
        self._ready_evt = threading.Event()
        self._exit_code = 0
        self._up_ticks = 0
        self._down_since: Optional[float] = None
        self._reservation: Optional[socket.socket] = None
        self._read_fd = -1
        self._write_fd = -1
        self._control: Optional[HttpService] = None
        # set in run(): smoothed series for the autoscaler; until then
        # _autoscale falls back to instantaneous heartbeat readings
        self._history = None
        # per-worker serving queue budget, for the utilization signal
        try:
            self._queue_budget = max(
                1, int(float(os.environ.get("PIO_SERVING_MAX_QUEUE", 256))))
        except ValueError:
            self._queue_budget = 256

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> int:
        if not hasattr(socket, "SO_REUSEPORT"):
            print("--workers needs SO_REUSEPORT (Linux); this platform "
                  "lacks it", file=sys.stderr)
            return 1

        # port reservation: bound with SO_REUSEPORT but NEVER listening, so
        # the kernel excludes it from load balancing while guaranteeing the
        # port stays ours across worker respawns and paused accepts
        self._reservation = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._reservation.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        try:
            self._reservation.bind((self.config.ip, self.config.port))
        except OSError as e:
            print(f"Cannot bind {self.config.ip}:{self.config.port}: "
                  f"{e.strerror or e}", file=sys.stderr)
            return 1
        self.config.port = self._reservation.getsockname()[1]

        self._read_fd, self._write_fd = os.pipe()

        for _ in range(self.n_workers):
            self._add_slot()

        reader = threading.Thread(target=self._reader_loop, daemon=True,
                                  name="supervisor-reader")
        reader.start()

        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, self._on_term)
        signal.signal(signal.SIGHUP, self._on_hup)

        # smoothed autoscaling signals + /debug/history.json on the
        # control endpoint; None when PIO_METRICS_HISTORY=0
        self._history = metrics_history.ensure_started()
        # the control endpoint's /metrics serves the merged FLEET view,
        # not the supervisor's own registry
        telemetry_middleware.set_metrics_renderer(
            "supervisor", self._render_fleet_metrics)
        # …and its /debug/profile.json serves the fleet-merged flamegraph
        telemetry_middleware.set_profile_renderer(
            "supervisor", self._render_fleet_profile)
        # …and its /debug/lineage routes serve the fleet-merged timelines
        telemetry_middleware.set_lineage_renderer(
            "supervisor", self._render_fleet_lineage)
        # …and its /debug/jit.json serves the fleet-merged device view
        telemetry_middleware.set_device_renderer(
            "supervisor", self._render_fleet_device)
        # …and its /debug/tenants.json serves the fleet-merged per-app view
        telemetry_middleware.set_tenants_renderer(
            "supervisor", self._render_fleet_tenants)

        if self.cfg.control_port is not None:
            try:
                self._control = HttpService(
                    self.cfg.control_ip, self.cfg.control_port,
                    self._control_handler(), server_name="supervisor")
                self._control.start()
                print(f"Supervisor control endpoint on "
                      f"{self.cfg.control_ip}:{self._control.port}",
                      flush=True)
            except OSError as e:
                log.warning("control endpoint disabled: %s", e)
                self._control = None

        tick = threading.Thread(target=self._tick_loop, daemon=True,
                                name="supervisor-tick")
        tick.start()

        try:
            while True:
                try:
                    pid, status = os.wait()
                except ChildProcessError:
                    if self._done.wait(0.05):
                        break
                    continue
                except InterruptedError:
                    continue
                self._on_death(pid, status)
                if self._done.is_set() and not self._by_pid:
                    break
        finally:
            self._done.set()
            for fd in (self._write_fd, self._read_fd):
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._reservation.close()
            telemetry_middleware.set_metrics_renderer("supervisor", None)
            telemetry_middleware.set_profile_renderer("supervisor", None)
            telemetry_middleware.set_lineage_renderer("supervisor", None)
            telemetry_middleware.set_device_renderer("supervisor", None)
            telemetry_middleware.set_tenants_renderer("supervisor", None)
            if self._control is not None:
                try:
                    self._control.shutdown()
                except Exception:
                    pass
        return self._exit_code

    def _add_slot(self) -> _Slot:
        slot = _Slot(self._slot_seq, self.cfg)
        # single-writer: run() seeds the initial slots before the tick
        # thread starts (Thread.start is the ordering edge); afterwards
        # only _tick_loop's scale-up path allocates
        self._slot_seq += 1  # pio-lint: disable=race-shared-state
        with self._lock:
            self._slots.append(slot)
        return slot

    # -- signals -----------------------------------------------------------

    def _on_term(self, signum, frame):
        self._shutting_down = True
        self._broadcast(signal.SIGTERM)
        if not self._by_pid:
            self._done.set()

    def _on_hup(self, signum, frame):
        self._roll_requested = True

    def _broadcast(self, signum: int) -> None:
        for pid in list(self._by_pid):
            try:
                os.kill(pid, signum)
            except ProcessLookupError:
                pass

    # -- fork / reap -------------------------------------------------------

    def _spawn(self, slot: _Slot) -> None:
        spawn_index = self._spawn_counter
        self._spawn_counter += 1
        fault_spec = self._worker_faults.get(spawn_index)
        attempt = slot.breaker.failures + 1
        # parseable spawn receipt: the chaos gate asserts backoff gaps and
        # bounded attempt counts from these timestamps
        print(f"supervisor: spawn slot={slot.idx} attempt={attempt} "
              f"spawn_index={spawn_index} t={time.monotonic():.3f}",
              flush=True)
        pid = os.fork()
        if pid == 0:
            # child: the fork inherits the supervisor's handlers — reset
            # them FIRST, or a SIGTERM landing during the slow model load
            # would re-broadcast instead of dying. SIGHUP/SIGUSR1/SIGUSR2
            # are IGNORED (not SIG_DFL) until the server is up: a routine
            # roll racing this worker's multi-second model load must not
            # kill it — it loads the newest instance anyway; _worker_main
            # installs the real handlers once the server is built.
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, signal.SIG_DFL)
            for sig in (signal.SIGHUP, signal.SIGUSR1, signal.SIGUSR2):
                signal.signal(sig, signal.SIG_IGN)
            if fault_spec is not None:
                os.environ["PIO_FAULTS"] = fault_spec
            # stable fleet identity: metric series merge under slot<N>,
            # not the pid that changes on every respawn
            os.environ["PIO_METRICS_WORKER_LABEL"] = f"slot{slot.idx}"
            os.close(self._read_fd)
            self._reservation.close()
            if self._control is not None:
                # don't hold the control listener open in workers
                try:
                    self._control.httpd.socket.close()
                except OSError:
                    pass
            code = 1
            try:
                code = _worker_main(self.config, os.getppid(),
                                    self._write_fd, self.cfg)
            finally:
                os._exit(code)
        now = time.monotonic()
        slot.pid = pid
        slot.spawn_index = spawn_index
        slot.ready = False
        slot.spawned_at = now
        slot.last_hb = now
        slot.progress_at = now
        slot.next_spawn_at = None
        with self._lock:
            self._by_pid[pid] = slot
        POOL_SPAWNED.inc()
        self._update_gauges()

    def _on_death(self, pid: int, status: int) -> None:
        with self._lock:
            slot = self._by_pid.get(pid)
        if slot is None:
            return
        if not slot.ready:
            # readiness arrives via the pipe's reader THREAD while deaths
            # are reaped synchronously here: a worker that wrote its ready
            # mark and died moments later must not be misread as a startup
            # failure — give the reader a beat to drain the mark
            time.sleep(0.2)
        with self._lock:
            self._by_pid.pop(pid, None)
        rc = (os.waitstatus_to_exitcode(status)
              if hasattr(os, "waitstatus_to_exitcode") else status)
        was_ready = slot.ready
        now = time.monotonic()

        if self._shutting_down:
            slot.reset_process_view()
            self._update_gauges()
            if not self._by_pid:
                self._done.set()
            return

        if slot.draining_out:
            # intentional scale-down exit — not a failure
            log.info("worker %d drained out (scale-down, rc=%s)", pid, rc)
            slot.reset_process_view()
            with self._lock:
                if slot in self._slots:
                    self._slots.remove(slot)
            SUP_BREAKER_STATE.labels(slot=str(slot.idx)).set(0)
            self._update_gauges()
            return

        reason = slot.kill_reason or ("crash" if was_ready else "startup")
        slot.kill_reason = None
        if was_ready:
            log.warning("worker %d died (%s) — respawning [%s]",
                        pid, rc, reason)
            POOL_RESPAWNS.inc()
        else:
            log.error("worker %d failed at startup (%s)", pid, rc)
            POOL_STARTUP_FAILURES.inc()
        SUP_RESTARTS.labels(reason=reason).inc()

        rapid = (not was_ready) or (now - slot.ready_at < self.cfg.rapid_fail_s)
        slot.breaker.record_failure(now, rapid)
        slot.reset_process_view()
        if slot.breaker.failures >= self.cfg.breaker_threshold:
            slot.next_spawn_at = slot.breaker.open_until
            print(f"supervisor: breaker open slot={slot.idx} "
                  f"failures={slot.breaker.failures} "
                  f"retry_in={slot.breaker.open_until - now:.1f}s "
                  f"t={now:.3f}", flush=True)
        else:
            delay = backoff_s(slot.breaker.failures, self.cfg.backoff_base_s,
                              self.cfg.backoff_cap_s, self._rng)
            slot.next_spawn_at = now + delay
            print(f"supervisor: respawn slot={slot.idx} "
                  f"failures={slot.breaker.failures} in={delay:.2f}s "
                  f"t={now:.3f}", flush=True)
        self._update_gauges()

    # -- pipe reader -------------------------------------------------------

    def _reader_loop(self) -> None:
        while True:
            try:
                buf = os.read(self._read_fd, MSG_SIZE)
            except OSError:
                return
            if len(buf) != MSG_SIZE:
                return  # EOF / teardown
            kind, pid, a, b, c, d = unpack_msg(buf)
            with self._lock:
                slot = self._by_pid.get(pid)
            if slot is None:
                continue
            now = time.monotonic()
            if kind == MSG_READY:
                slot.ready = True
                slot.port = a
                slot.snapshot_port = b
                slot.ready_at = now
                slot.last_hb = now
                slot.progress_at = now
                slot.breaker.record_success()
                SUP_BREAKER_STATE.labels(slot=str(slot.idx)).set(0)
                self._ever_ready = True
                self._update_gauges()
                if not self._ready_evt.is_set():
                    self._ready_evt.set()
                    # announced from here (not the reap loop, which must
                    # keep reaping — a pool whose workers all fail at
                    # startup would otherwise block on a readiness that
                    # never comes)
                    print(f"Engine instance deployed on "
                          f"{self.config.ip}:{self.config.port} "
                          f"(workers: {self.n_workers})", flush=True)
            elif kind == MSG_HEARTBEAT:
                slot.last_hb = now
                if b != slot.completed or a == 0:
                    slot.progress_at = now
                slot.in_flight, slot.completed, slot.bad = a, b, c
                slot.burn = d / 1000.0
            elif kind == MSG_RELOADED:
                SUP_DRAIN_SECONDS.observe(a / 1000.0)
                if not b:
                    log.warning("worker %d finished drain-reload unhealthy",
                                pid)
                if slot.reload_evt is not None:
                    slot.reload_evt.set()
            elif kind == MSG_DRAINED:
                SUP_DRAIN_SECONDS.observe(a / 1000.0)

    # -- control tick ------------------------------------------------------

    def _tick_loop(self) -> None:
        while not self._done.is_set():
            try:
                if not self._shutting_down:
                    self._spawn_due()
                    self._check_health()
                    self._maybe_roll()
                    self._autoscale()
                self._decide_exit()
            except Exception:
                log.exception("supervisor tick failed")
            self._done.wait(self.cfg.poll_interval_s)

    def _spawn_due(self) -> None:
        now = time.monotonic()
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            if (slot.pid is None and slot.next_spawn_at is not None
                    and now >= slot.next_spawn_at
                    and slot.breaker.allows_spawn(now)):
                if slot.breaker.half_open:
                    SUP_BREAKER_STATE.labels(slot=str(slot.idx)).set(2)
                self._spawn(slot)

    def _check_health(self) -> None:
        cfg = self.cfg
        now = time.monotonic()
        with self._lock:
            slots = list(self._slots)
        for slot in slots:
            pid = slot.pid
            if pid is None:
                continue
            if slot.kill_at is not None:
                if now >= slot.kill_at:
                    # graceful drain overstayed its deadline
                    log.warning("worker %d ignored its drain deadline — "
                                "SIGKILL", pid)
                    self._kill(pid, signal.SIGKILL)
                    slot.kill_at = None
                continue
            if not slot.ready or slot.draining_out or slot.rolling:
                continue
            hb_age = now - slot.last_hb
            stalled = (slot.in_flight > 0
                       and now - slot.progress_at > cfg.hang_timeout_s)
            if hb_age > cfg.heartbeat_timeout_s or stalled:
                why = ("heartbeat silent %.1fs" % hb_age
                       if hb_age > cfg.heartbeat_timeout_s else
                       "in-flight %d stalled %.1fs"
                       % (slot.in_flight, now - slot.progress_at))
                log.warning("worker %d hung (%s) — SIGKILL", pid, why)
                slot.kill_reason = "hang"
                self._kill(pid, signal.SIGKILL)
                continue
            # error-ratio over a short trailing window (erroring worker)
            slot.window.append((slot.completed, slot.bad))
            max_len = max(2, int(cfg.error_window_s / cfg.poll_interval_s))
            if len(slot.window) > max_len:
                slot.window = slot.window[-max_len:]
            d_total = slot.completed - slot.window[0][0]
            d_bad = slot.bad - slot.window[0][1]
            if (d_total >= cfg.error_min_requests
                    and d_bad / d_total >= cfg.error_restart_ratio):
                log.warning("worker %d erroring (%d/%d bad in window) — "
                            "restarting", pid, d_bad, d_total)
                slot.kill_reason = "error_rate"
                self._restart_gracefully(slot, now)
                continue
            # burn-rate rule (slow worker: latency burn pages long before
            # availability does — a delay:500 worker answers only 200s)
            if (slot.burn >= cfg.burn_restart
                    and slot.completed >= cfg.error_min_requests
                    and now - slot.ready_at > cfg.burn_grace_s):
                log.warning("worker %d burning SLO budget (5m burn %.1f) — "
                            "restarting", pid, slot.burn)
                slot.kill_reason = "slo_burn"
                self._restart_gracefully(slot, now)

    def _restart_gracefully(self, slot: _Slot, now: float) -> None:
        self._kill(slot.pid, signal.SIGTERM)
        slot.kill_at = now + self.cfg.drain_deadline_s + 2.0

    def _kill(self, pid: Optional[int], signum: int) -> None:
        if pid is None:
            return
        try:
            os.kill(pid, signum)
        except ProcessLookupError:
            pass

    # -- rolling deploy ----------------------------------------------------

    def _maybe_roll(self) -> None:
        if self._roll_requested:
            self._roll_requested = False
            if not self._rolling:
                self._rolling = True
                threading.Thread(target=self._roll, daemon=True,
                                 name="supervisor-roll").start()

    def _roll(self) -> None:
        try:
            SUP_ROLLING.inc()
            print("supervisor: rolling reload started", flush=True)
            with self._lock:
                slots = list(self._slots)
            for slot in slots:
                if self._shutting_down or self._done.is_set():
                    break
                pid = slot.pid
                if pid is None or not slot.ready or slot.draining_out:
                    continue  # a fresh spawn loads the newest instance anyway
                slot.rolling = True
                slot.reload_evt = threading.Event()
                try:
                    os.kill(pid, signal.SIGUSR1)
                except ProcessLookupError:
                    slot.rolling = False
                    continue
                ok = slot.reload_evt.wait(self.cfg.drain_deadline_s + 10.0)
                slot.rolling = False
                slot.reload_evt = None
                if not ok:
                    log.warning("worker %d never acked drain-reload", pid)
            print("supervisor: rolling reload complete", flush=True)
        finally:
            self._rolling = False

    # -- autoscaling -------------------------------------------------------

    def _autoscale(self) -> None:
        if self._rolling:
            return
        cfg = self.cfg
        now = time.monotonic()
        with self._lock:
            slots = list(self._slots)
        ready = [s for s in slots if s.ready and s.pid is not None
                 and not s.draining_out]
        if not ready:
            return
        util = (sum(s.in_flight for s in ready) / len(ready)
                / self._queue_budget)
        avg_burn = sum(s.burn for s in ready) / len(ready)
        # publish the instantaneous signals so the history sampler can
        # record them; decisions below read the SMOOTHED series back, so
        # one heartbeat spike (or one idle beat) no longer whipsaws the
        # pool. Falls back to the point reads until history warms up.
        SUP_POOL_UTIL.set(util)
        SUP_POOL_BURN.set(avg_burn)
        up_util, up_burn = self._smoothed(cfg.scale_up_window_s,
                                          util, avg_burn)
        down_util, down_burn = self._smoothed(cfg.scale_down_window_s,
                                              util, avg_burn)

        if (len(slots) < cfg.max_workers
                and (up_util >= cfg.scale_up_util
                     or up_burn >= cfg.scale_up_burn)):
            self._up_ticks += 1
            if self._up_ticks >= cfg.scale_stable_ticks:
                self._up_ticks = 0
                slot = self._add_slot()
                slot.next_spawn_at = now
                SUP_SCALE_EVENTS.labels(direction="up").inc()
                print(f"supervisor: scale up → {len(slots) + 1} slots "
                      f"(util={up_util:.2f} burn={up_burn:.1f})", flush=True)
        else:
            self._up_ticks = 0

        can_shrink = (len([s for s in slots if not s.draining_out])
                      > cfg.min_workers)
        if (can_shrink and down_util <= cfg.scale_down_util
                and down_burn < 1.0):
            if self._down_since is None:
                self._down_since = now
            elif now - self._down_since >= cfg.scale_down_stable_s:
                self._down_since = None
                victim = ready[-1]
                victim.draining_out = True
                victim.kill_at = now + cfg.drain_deadline_s + 5.0
                SUP_SCALE_EVENTS.labels(direction="down").inc()
                print(f"supervisor: scale down → draining worker "
                      f"{victim.pid} (slot {victim.idx})", flush=True)
                self._kill(victim.pid, signal.SIGUSR2)
        else:
            self._down_since = None

    def _smoothed(self, window_s: float, util_now: float,
                  burn_now: float) -> Tuple[float, float]:
        """Windowed means of the pool signals from the metrics history;
        the instantaneous readings stand in until the sampler has data
        (or when history is disabled)."""
        hist = self._history
        if hist is None:
            return util_now, burn_now
        util = hist.mean("supervisor_pool_utilization", window_s=window_s)
        burn = hist.mean("supervisor_pool_burn_avg", window_s=window_s)
        return (util_now if util is None else util,
                burn_now if burn is None else burn)

    # -- fleet metrics -----------------------------------------------------

    def _worker_snapshots(self) -> List[dict]:
        """Registry snapshots from every ready worker's loopback socket.
        A worker that dies mid-fetch is simply absent from this round's
        merge — the fleet view degrades, never errors."""
        with self._lock:
            targets = [(f"slot{s.idx}", s.snapshot_port) for s in self._slots
                       if s.ready and s.pid is not None and s.snapshot_port]
        snaps = []
        for label, port in targets:
            try:
                snaps.append(aggregate.fetch_snapshot(port))
            except (OSError, ValueError):
                log.debug("metrics snapshot from %s (port %d) failed",
                          label, port)
        return snaps

    def _render_fleet_metrics(self) -> str:
        """The supervisor control endpoint's /metrics body: this process's
        registry merged with every ready worker's — counters sum exactly,
        gauges stay per-worker."""
        snaps = [aggregate.snapshot_registry(worker="supervisor")]
        snaps.extend(self._worker_snapshots())
        return aggregate.render_merged(aggregate.merge_snapshots(snaps))

    def _render_fleet_profile(self, route=None) -> tuple:
        """The control endpoint's /debug/profile.json: every worker's
        collapsed-stack export (riding the same snapshot fetch as the
        metric merge) plus the supervisor's own, summed exactly by
        profiler.merge_profiles — per-worker sample counts and the fleet
        total come from the SAME snapshot set, so
        ``samples == sum(workers.values())`` is checkable from one
        fetch."""
        from predictionio_tpu.telemetry import profiler
        parts = [("supervisor", profiler.export_state())]
        for snap in self._worker_snapshots():
            parts.append((str(snap.get("worker", "?")),
                          snap.get("profile")))
        return profiler.filter_merged(profiler.merge_profiles(parts), route)

    def _render_fleet_device(self) -> tuple:
        """The control endpoint's /debug/jit.json: every worker's device
        attribution export (riding the same snapshot fetch as the metric
        merge) plus the supervisor's own, merged by device.merge_device —
        device-microseconds sum exactly and the per-worker totals ship in
        the same payload, so ``total_us == sum(workers.values())`` is
        checkable from one fetch."""
        from predictionio_tpu.telemetry import device
        parts = [("supervisor", device.export_state())]
        for snap in self._worker_snapshots():
            parts.append((str(snap.get("worker", "?")),
                          snap.get("device")))
        return 200, device.merge_device(parts)

    def _render_fleet_tenants(self) -> tuple:
        """The control endpoint's /debug/tenants.json: every worker's
        tenant-meter export (riding the same snapshot fetch as the metric
        merge) plus the supervisor's own, merged by tenant.merge_tenants —
        which ASSERTS sum-exactness (sum over tenant labels, including the
        unattributed "-" bucket, equals the untagged totals) before the
        per-app top-K view is built."""
        from predictionio_tpu.telemetry import tenant
        parts = [("supervisor", tenant.export_state())]
        for snap in self._worker_snapshots():
            parts.append((str(snap.get("worker", "?")),
                          snap.get("tenant")))
        return 200, tenant.payload(merged=tenant.merge_tenants(parts))

    def _render_fleet_lineage(self, trace_id=None, limit: int = 100) -> tuple:
        """The control endpoint's /debug/lineage routes: every worker's
        lineage export (riding the same snapshot fetch as the metric
        merge) plus the supervisor's own, merged by lineage.merge_lineage
        — stage counts sum exactly and the per-worker totals ship in the
        same payload, so ``sum(stages.values()) ==
        sum(workers.values())`` is checkable from one fetch."""
        from predictionio_tpu.telemetry import lineage
        parts = [("supervisor", lineage.export_state())]
        for snap in self._worker_snapshots():
            parts.append((str(snap.get("worker", "?")),
                          snap.get("lineage")))
        merged = lineage.merge_lineage(parts, limit=limit)
        if trace_id is None:
            return 200, merged
        entry = lineage.find_in_merged(merged, trace_id)
        if entry is None:
            return telemetry_middleware.error_payload(
                404, "trace not in the fleet lineage view",
                trace_id=trace_id, evicted=False)
        return 200, entry

    def fleet_summary(self) -> dict:
        """Per-worker and fleet-total request counters for /status.json —
        the cross-check that the merged scrape is sum-exact."""
        snaps = self._worker_snapshots()
        per_worker = [{
            "worker": s.get("worker"),
            "pid": s.get("pid"),
            "httpRequests": aggregate.counter_totals(
                s, "http_requests_total"),
            "queries": aggregate.counter_totals(
                s, "http_requests_total",
                where={"route": "/queries.json"}),
        } for s in snaps]
        return {
            "workers": per_worker,
            "totals": {
                "httpRequests": sum(w["httpRequests"] for w in per_worker),
                "queries": sum(w["queries"] for w in per_worker),
            },
        }

    # -- exit policy -------------------------------------------------------

    def _decide_exit(self) -> None:
        now = time.monotonic()
        if self._shutting_down:
            if not self._by_pid:
                self._done.set()
            return
        if self._ever_ready:
            return
        with self._lock:
            slots = list(self._slots)
        if slots and all(s.pid is None and s.breaker.state(now) ==
                         CircuitBreaker.OPEN for s in slots):
            # nothing ever served and every slot crash-looped into its
            # breaker: config/model error — fail the pool fast rather
            # than sit dark behind a reserved port
            log.error("no worker ever became ready and every slot's "
                      "circuit breaker is open — failing the pool")
            print("supervisor: pool startup failed (all circuit breakers "
                  "open)", flush=True)
            self._exit_code = 1
            self._shutting_down = True
            self._done.set()

    # -- introspection -----------------------------------------------------

    def _update_gauges(self) -> None:
        with self._lock:
            slots = list(self._slots)
        live = sum(1 for s in slots if s.pid is not None)
        ready = sum(1 for s in slots if s.ready and s.pid is not None)
        POOL_WORKERS.set(live)
        SUP_WORKERS.labels(state="target").set(len(slots))
        SUP_WORKERS.labels(state="live").set(live)
        SUP_WORKERS.labels(state="ready").set(ready)
        now = time.monotonic()
        for s in slots:
            SUP_BREAKER_STATE.labels(slot=str(s.idx)).set(
                s.breaker.state(now))

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            slots = list(self._slots)
        return {
            "target": len(slots),
            "min": self.cfg.min_workers,
            "max": self.cfg.max_workers,
            "live": sum(1 for s in slots if s.pid is not None),
            "ready": sum(1 for s in slots if s.ready and s.pid is not None),
            "rolling": self._rolling,
            "shuttingDown": self._shutting_down,
            "port": self.config.port,
            "workers": [{
                "slot": s.idx,
                "pid": s.pid,
                "ready": s.ready,
                "port": s.port,
                "metricsSnapshotPort": s.snapshot_port or None,
                "inFlight": s.in_flight,
                "completed": s.completed,
                "bad": s.bad,
                "burn5m": round(s.burn, 3),
                "drainingOut": s.draining_out,
                "rolling": s.rolling,
                "failures": s.breaker.failures,
                "breaker": ("open" if s.breaker.state(now) == 1 else
                            "half-open" if s.breaker.state(now) == 2 else
                            "closed"),
                "heartbeatAgeS": (round(now - s.last_hb, 2)
                                  if s.pid is not None else None),
            } for s in slots],
        }

    def _control_handler(self):
        sup = self

        class ControlHandler(JsonRequestHandler):
            server_version = "pio-tpu-supervisor/0.1"

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path in ("/", "/status.json"):
                    payload = sup.status()
                    if "fleet=1" in query.split("&"):
                        payload["fleet"] = sup.fleet_summary()
                    return self.send_json(200, payload)
                return self.send_json(404, {"message": "Not Found"})

        return ControlHandler


def run_worker_pool(config, n_workers: int) -> int:
    """Supervise an N-worker SO_REUSEPORT pool (`pio deploy --workers N`).
    Returns the process exit code. Mutates `config.port` to the resolved
    concrete port when called with port 0."""
    return Supervisor(config, n_workers).run()
