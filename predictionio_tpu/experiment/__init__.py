"""Experimentation plane: many trained engine variants behind one route.

PredictionIO's lineage is A/B-testable engines; this package is that
capability rebuilt on the subsystems already here. One `VariantRouter`
sits where a single ServingPlane used to, in front of one
admission-gated plane PER trained engine variant:

- **sticky mode** — a deterministic digest of the user id picks the
  variant (bandit-free A/B with stable assignment: the same user maps
  to the same variant across worker restarts, pool resizes, and rolling
  deploys, because the digest — unlike Python's per-process-randomized
  `hash()` — depends on nothing but the bytes of the id).
- **bandit mode** — Thompson sampling over per-variant Beta posteriors.
  Feedback arrives as `$reward` events through the normal group-commit
  ingest funnel (ingest/writer.py); a `RewardTailer` polls the durable
  event store and updates the posteriors, so every serving worker —
  whichever process ingested the reward — converges on the same split.

Per-variant `experiment_*` telemetry (traffic share, posterior mean,
reward counts, request outcomes) and per-variant SLO objectives
(`/queries.json@<variant>`) ride the existing registry; per-variant
result-cache keys (serving/result_cache.py) keep cached answers from
leaking across variants. Configuration is the `PIO_EXPERIMENT_*` env
family (workflow/create_server.py turns it on), so pre-fork pool
workers inherit one consistent experiment posture across fork/exec —
same story as PIO_SERVING_* / PIO_INGEST_*.

See docs/experimentation.md for the operator guide and bandit math.
"""

from predictionio_tpu.experiment.bandit import (  # noqa: F401
    ThompsonBandit,
    sticky_variant,
)
from predictionio_tpu.experiment.rewards import RewardTailer  # noqa: F401
from predictionio_tpu.experiment.router import (  # noqa: F401
    ExperimentConfig,
    VariantRouter,
)
