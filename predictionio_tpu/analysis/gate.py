"""Analysis gate — CI wrapper over the pio-lint engine.

Run via ``python quality.py --analysis-gate``. Fails on any finding not
grandfathered in ``conf/analysis-baseline.json`` (whose every entry
must carry a reviewed ``reason``) and not inline-suppressed. No
imports of the scanned code, no jax — pure AST.
"""

from __future__ import annotations

import os
import sys

from predictionio_tpu.analysis import engine


def run_gate() -> int:
    project = engine.Project(engine.default_root(),
                             subdirs=engine.DEFAULT_SUBDIRS)
    findings = engine.run_rules(project)
    baseline_path = os.path.join(engine.default_root(),
                                 engine.DEFAULT_BASELINE)
    problems = []
    try:
        baseline = engine.load_baseline(baseline_path)
    except (engine.BaselineError, ValueError) as e:
        baseline = {}
        problems.append(f"baseline: {e}")
    new, grandfathered, _stale = engine.partition(findings, baseline)
    problems.extend(f.render() for f in new)
    for p in problems:
        print(p, file=sys.stderr)
    print(f"analysis gate: {'FAIL' if problems else 'OK'} "
          f"({len(problems)} problem(s), {len(grandfathered)} baselined, "
          f"{len(project.modules())} module(s) scanned)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(run_gate())
