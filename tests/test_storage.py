"""Storage backend + registry + event-store façade tests — mirrors the
reference's LEventsSpec / metadata repo specs (SURVEY.md §4.1)."""

from datetime import datetime, timezone

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.data.store import EventStore
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)


def ts(h, m=0):
    return datetime(2026, 1, 1, h, m, 0, tzinfo=timezone.utc)


def ev(name, eid="u1", t=None, **kw):
    return Event(event=name, entity_type="user", entity_id=eid,
                 event_time=t or ts(0), **kw)


class TestApps:
    def test_crud(self, memory_storage):
        apps = memory_storage.meta_apps()
        app_id = apps.insert(App(id=0, name="MyApp", description="d"))
        assert app_id is not None
        assert apps.get(app_id).name == "MyApp"
        assert apps.get_by_name("MyApp").id == app_id
        assert apps.insert(App(id=0, name="MyApp")) is None  # duplicate name
        assert apps.update(App(id=app_id, name="Renamed"))
        assert apps.get_by_name("Renamed") is not None
        assert [a.name for a in apps.get_all()] == ["Renamed"]
        assert apps.delete(app_id)
        assert apps.get(app_id) is None


class TestAccessKeysAndChannels:
    def test_access_keys(self, memory_storage):
        keys = memory_storage.meta_access_keys()
        k = AccessKey.generate(app_id=1, events=["rate"])
        keys.insert(k)
        got = keys.get(k.key)
        assert got.app_id == 1 and got.events == ["rate"]
        assert len(keys.get_by_app_id(1)) == 1
        assert keys.delete(k.key)
        assert keys.get(k.key) is None

    def test_channels(self, memory_storage):
        channels = memory_storage.meta_channels()
        cid = channels.insert(Channel(id=0, name="ch1", app_id=1))
        assert cid is not None
        assert channels.get(cid).name == "ch1"
        # duplicate per app rejected
        assert channels.insert(Channel(id=0, name="ch1", app_id=1)) is None
        # invalid name rejected (too long)
        assert channels.insert(Channel(id=0, name="x" * 20, app_id=1)) is None
        assert [c.name for c in channels.get_by_app_id(1)] == ["ch1"]


class TestEngineInstances:
    def mk(self, status="RUNNING", t=None):
        t = t or ts(1)
        return EngineInstance(
            id="", status=status, start_time=t, end_time=t,
            engine_id="eng", engine_version="1", engine_variant="engine.json",
            engine_factory="mod.Factory",
        )

    def test_insert_get_update(self, memory_storage):
        eis = memory_storage.meta_engine_instances()
        iid = eis.insert(self.mk())
        inst = eis.get(iid)
        assert inst.status == "RUNNING"
        inst.status = "COMPLETED"
        eis.update(inst)
        assert eis.get(iid).status == "COMPLETED"

    def test_latest_completed(self, memory_storage):
        eis = memory_storage.meta_engine_instances()
        eis.insert(self.mk("COMPLETED", ts(1)))
        latest = self.mk("COMPLETED", ts(2))
        eis.insert(latest)
        eis.insert(self.mk("RUNNING", ts(3)))
        got = eis.get_latest_completed("eng", "1", "engine.json")
        assert got.id == latest.id
        assert eis.get_latest_completed("other", "1", "engine.json") is None


class TestEvaluationInstancesAndModels:
    def test_eval_instances(self, memory_storage):
        evs = memory_storage.meta_evaluation_instances()
        inst = EvaluationInstance(
            id="", status="EVALRUNNING", start_time=ts(1), end_time=ts(1),
            evaluation_class="ev.Cls", engine_params_generator_class="gen.Cls",
        )
        iid = evs.insert(inst)
        inst.status = "EVALCOMPLETED"
        inst.evaluator_results = "MAP@10: 0.1"
        evs.update(inst)
        completed = evs.get_completed()
        assert [i.id for i in completed] == [iid]
        assert completed[0].evaluator_results == "MAP@10: 0.1"

    def test_models_blob(self, memory_storage):
        models = memory_storage.model_data_models()
        models.insert(Model(id="i1", models=b"\x00\x01bytes"))
        assert models.get("i1").models == b"\x00\x01bytes"
        models.insert(Model(id="i1", models=b"replaced"))
        assert models.get("i1").models == b"replaced"
        assert models.delete("i1")
        assert models.get("i1") is None


class TestLEvents:
    def test_insert_get_delete(self, memory_storage):
        le = memory_storage.l_events()
        e = ev("rate", properties=DataMap({"rating": 4.0}))
        eid = le.insert(e, app_id=1)
        got = le.get(eid, app_id=1)
        assert got.properties.to_dict() == {"rating": 4.0}
        assert le.get(eid, app_id=2) is None  # app isolation
        assert le.delete(eid, app_id=1)
        assert le.get(eid, app_id=1) is None

    def test_find_filters(self, memory_storage):
        le = memory_storage.l_events()
        le.insert(ev("rate", "u1", ts(1)), app_id=1)
        le.insert(ev("buy", "u1", ts(2)), app_id=1)
        le.insert(ev("rate", "u2", ts(3)), app_id=1)
        le.insert(ev("rate", "u9", ts(1)), app_id=2)

        assert len(le.find(app_id=1)) == 3
        assert len(le.find(app_id=1, event_names=["rate"])) == 2
        assert len(le.find(app_id=1, entity_id="u1")) == 2
        assert len(le.find(app_id=1, start_time=ts(2))) == 2
        assert len(le.find(app_id=1, until_time=ts(2))) == 1
        # time-ordered + reversed + limit
        times = [e.event_time for e in le.find(app_id=1)]
        assert times == sorted(times)
        rev = le.find(app_id=1, reversed=True, limit=1)
        assert rev[0].event_time == ts(3)

    def test_channel_isolation(self, memory_storage):
        le = memory_storage.l_events()
        le.insert(ev("rate", "u1", ts(1)), app_id=1, channel_id=None)
        le.insert(ev("rate", "u2", ts(2)), app_id=1, channel_id=7)
        assert [e.entity_id for e in le.find(app_id=1)] == ["u1"]
        assert [e.entity_id for e in le.find(app_id=1, channel_id=7)] == ["u2"]


class TestEventStoreFacade:
    def setup_app(self, storage, name="App1"):
        app_id = storage.meta_apps().insert(App(id=0, name=name))
        return app_id

    def test_find_by_app_name(self, memory_storage):
        app_id = self.setup_app(memory_storage)
        memory_storage.l_events().insert(ev("rate"), app_id=app_id)
        store = EventStore(memory_storage)
        assert len(store.find("App1")) == 1
        import pytest
        with pytest.raises(ValueError):
            store.find("NoSuchApp")

    def test_aggregate_properties(self, memory_storage):
        app_id = self.setup_app(memory_storage)
        le = memory_storage.l_events()
        le.insert(Event(event="$set", entity_type="user", entity_id="u1",
                        properties=DataMap({"a": 1}), event_time=ts(1)), app_id=app_id)
        le.insert(Event(event="$set", entity_type="user", entity_id="u1",
                        properties=DataMap({"b": 2}), event_time=ts(2)), app_id=app_id)
        le.insert(Event(event="$set", entity_type="item", entity_id="i1",
                        properties=DataMap({"c": 3}), event_time=ts(1)), app_id=app_id)
        store = EventStore(memory_storage)
        props = store.aggregate_properties("App1", "user")
        assert props["u1"].to_dict() == {"a": 1, "b": 2}
        assert "i1" not in props
        # required-keys filter
        assert store.aggregate_properties("App1", "user", required=["missing"]) == {}

    def test_sqlite_file_backend(self, tmp_path):
        from predictionio_tpu.storage.registry import SourceConfig, Storage, StorageConfig
        src = SourceConfig(name="F", type="sqlite", path=str(tmp_path / "pio.db"))
        storage = Storage(StorageConfig(metadata=src, modeldata=src, eventdata=src))
        app_id = storage.meta_apps().insert(App(id=0, name="FileApp"))
        storage.l_events().insert(ev("rate"), app_id=app_id)
        assert len(list(storage.l_events().find(app_id=app_id))) == 1
        assert all(storage.verify_all_data_objects().values())
        storage.close()


class TestReviewRegressions:
    """Regressions from the first code review."""

    def test_subsecond_event_time_ordering(self, memory_storage):
        from datetime import timedelta
        le = memory_storage.l_events()
        base = ts(1)
        # event at +0.5s stored between whole-second events
        le.insert(ev("a", "u1", base), app_id=1)
        le.insert(ev("b", "u1", base + timedelta(microseconds=500000)), app_id=1)
        le.insert(ev("c", "u1", base + timedelta(seconds=1)), app_id=1)
        names = [e.event for e in le.find(app_id=1)]
        assert names == ["a", "b", "c"]
        # range filter at whole-second boundary must include the .5s event
        got = le.find(app_id=1, start_time=base, until_time=base + timedelta(seconds=1))
        assert [e.event for e in got] == ["a", "b"]

    def test_get_delete_channel_scoped(self, memory_storage):
        le = memory_storage.l_events()
        eid = le.insert(ev("rate", "u1", ts(1)), app_id=1, channel_id=7)
        assert le.get(eid, app_id=1) is None  # default channel must not see it
        assert not le.delete(eid, app_id=1)
        assert le.get(eid, app_id=1, channel_id=7) is not None
        assert le.delete(eid, app_id=1, channel_id=7)

    def test_access_key_duplicate_insert_returns_none(self, memory_storage):
        keys = memory_storage.meta_access_keys()
        k = AccessKey(key="fixed", app_id=1)
        assert keys.insert(k) == "fixed"
        assert keys.insert(AccessKey(key="fixed", app_id=2)) is None


class TestBatchInsert:
    def test_insert_batch_single_transaction(self, memory_storage):
        events = memory_storage.l_events()
        batch = [ev("rate", eid=f"u{i}", t=ts(i % 24)) for i in range(250)]
        ids = events.insert_batch(batch, app_id=1)
        assert len(ids) == 250 and len(set(ids)) == 250
        found = events.find(app_id=1)
        assert len(found) == 250
        # events carry their assigned ids back
        assert all(e.event_id for e in batch)


class TestThreadConnReaping:
    def test_dead_thread_connections_are_reaped(self, tmp_path):
        """Per-thread sqlite connections must not outlive their threads:
        a long-lived server spawns a handler thread per client
        connection, and before round 5 every such thread's connection
        (db + wal fds) stayed open forever via _all_conns' strong ref —
        the fd leak the 10-minute soak drill caught (~2 fds per
        /reload). Dead threads' conns are closed when the next
        connection is created."""
        import threading

        from predictionio_tpu.storage.sqlite import SQLiteBackend

        b = SQLiteBackend(str(tmp_path / "reap.db"))
        b.apps().insert(App(id=None, name="ReapApp"))

        def read():
            assert b.apps().get_by_name("ReapApp") is not None

        for _ in range(20):
            t = threading.Thread(target=read)
            t.start()
            t.join()
        # one fresh connect triggers the sweep of all 20 dead owners
        read_main = threading.Thread(target=read)
        read_main.start()
        read_main.join()
        with b._conns_lock:
            live = len(b._all_conns)
        assert live <= 3, f"{live} connections retained for dead threads"
        b.close()


class TestLockedDatabaseRetry:
    """The "database is locked" regression (round 6): two per-thread WAL
    connections collide on the write lock. PIO_SQLITE_BUSY_TIMEOUT_MS=0
    turns off sqlite's own busy handler so the collision surfaces
    instantly, and the `sqlite.pre_commit=delay:` fault holds a real
    writer's transaction open long enough to stage the overlap. The
    undecorated write path (`insert.__wrapped__`) must reproduce the raw
    OperationalError; the _retry_locked-wrapped path must ride the same
    window out."""

    def test_locked_error_reproduced_then_retried_away(self, tmp_path,
                                                       monkeypatch):
        import sqlite3
        import threading
        import time

        from predictionio_tpu.storage.registry import (
            SourceConfig, Storage, StorageConfig,
        )
        from predictionio_tpu.storage.sqlite import SQLiteLEvents
        from predictionio_tpu.utils import faults

        monkeypatch.setenv("PIO_SQLITE_BUSY_TIMEOUT_MS", "0")
        src = SourceConfig(name="L", type="sqlite",
                           path=str(tmp_path / "locked.db"))
        storage = Storage(StorageConfig(metadata=src, modeldata=src,
                                        eventdata=src))
        le = storage.l_events()
        try:
            # the holder's commit sleeps 200 ms at the fault site with
            # its write transaction still open — a real writer holding
            # the WAL write lock, not a mock
            monkeypatch.setenv("PIO_FAULTS", "sqlite.pre_commit=delay:200")
            faults._parse()

            def hold(started):
                started.set()
                le.insert(ev("hold"), app_id=1)

            def stage_collision():
                started = threading.Event()
                t = threading.Thread(target=hold, args=(started,))
                t.start()
                started.wait(5)
                time.sleep(0.08)  # holder is now inside its commit sleep
                return t

            # repro: the undecorated insert surfaces the raw error
            # (fresh event per attempt — ids are assigned in-place)
            locked = None
            deadline = time.monotonic() + 10
            while locked is None and time.monotonic() < deadline:
                t = stage_collision()
                try:
                    SQLiteLEvents.insert.__wrapped__(le, ev("bare"), 1)
                except sqlite3.OperationalError as e:
                    locked = e
                t.join(10)
            assert locked is not None and "locked" in str(locked).lower(), (
                "undecorated insert never hit the staged lock collision")

            # fix: the decorated path retries through the same window
            t = stage_collision()
            assert le.insert(ev("retried"), app_id=1)
            t.join(10)
            events = {e.event for e in le.find(app_id=1)}
            assert {"hold", "retried"} <= events
        finally:
            monkeypatch.delenv("PIO_FAULTS", raising=False)
            faults._parse()
            storage.close()
