"""ALS bucket cache (VERDICT r2 #5): the host bucketize result is reused
across trains under a fingerprint of the training data + bucketizer
inputs, skipped on any change, and survives corruption."""

import logging

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, als_train


def _data(seed=0, nnz=800, n_u=40, n_i=30):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_u, nnz).astype(np.int32),
            rng.integers(0, n_i, nnz).astype(np.int32),
            rng.uniform(1, 5, nnz).astype(np.float32), n_u, n_i)


CFG = ALSConfig(rank=6, iterations=2, reg=0.05, seed=0, solver="chol",
                split_cap=16)


class TestBucketCache:
    def test_hit_after_miss_and_identical_factors(self, tmp_path, caplog):
        ui, ii, r, n_u, n_i = _data()
        cache = str(tmp_path / "cache")
        with caplog.at_level(logging.INFO, "predictionio_tpu.ops.als"):
            a = als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=cache)
            assert any("bucket cache miss" in m for m in caplog.messages)
            caplog.clear()
            b = als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=cache)
            assert any("bucket cache hit" in m for m in caplog.messages)
        np.testing.assert_array_equal(a.user_factors, b.user_factors)
        np.testing.assert_array_equal(a.item_factors, b.item_factors)

    @pytest.mark.parametrize("mutate", ["ratings", "split_cap", "growth"])
    def test_invalidation(self, tmp_path, caplog, mutate):
        import dataclasses

        ui, ii, r, n_u, n_i = _data()
        cache = str(tmp_path / "cache")
        als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=cache)
        cfg = CFG
        if mutate == "ratings":  # one new/changed event must invalidate
            r = r.copy()
            r[0] += 1.0
        elif mutate == "split_cap":
            cfg = dataclasses.replace(CFG, split_cap=24)
        else:
            cfg = dataclasses.replace(CFG, cap_growth=2.0)
        with caplog.at_level(logging.INFO, "predictionio_tpu.ops.als"):
            als_train(ui, ii, r, n_u, n_i, cfg, bucket_cache_dir=cache)
        assert any("bucket cache miss" in m for m in caplog.messages)
        assert not any("bucket cache hit" in m for m in caplog.messages)

    def test_corrupt_cache_rebuckets(self, tmp_path, caplog):
        ui, ii, r, n_u, n_i = _data()
        cache = tmp_path / "cache"
        ref = als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=str(cache))
        (entry,) = cache.glob("*.npz")
        entry.write_bytes(b"not an npz")
        with caplog.at_level(logging.WARNING, "predictionio_tpu.ops.als"):
            out = als_train(ui, ii, r, n_u, n_i, CFG,
                            bucket_cache_dir=str(cache))
        assert any("unreadable" in m for m in caplog.messages)
        np.testing.assert_array_equal(out.user_factors, ref.user_factors)

    def test_truncated_zip_rebuckets(self, tmp_path, caplog):
        """Corruption AFTER the zip magic (BadZipFile, not ValueError)
        must also fall back instead of crashing the train."""
        ui, ii, r, n_u, n_i = _data()
        cache = tmp_path / "cache"
        ref = als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=str(cache))
        (entry,) = cache.glob("*.npz")
        entry.write_bytes(entry.read_bytes()[:100])  # keeps PK magic
        with caplog.at_level(logging.WARNING, "predictionio_tpu.ops.als"):
            out = als_train(ui, ii, r, n_u, n_i, CFG,
                            bucket_cache_dir=str(cache))
        assert any("unreadable" in m for m in caplog.messages)
        np.testing.assert_array_equal(out.user_factors, ref.user_factors)

    def test_gc_keeps_newest(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PIO_BUCKET_CACHE_KEEP", "2")
        cache = tmp_path / "cache"
        for seed in range(4):
            ui, ii, r, n_u, n_i = _data(seed=seed)
            als_train(ui, ii, r, n_u, n_i, CFG, bucket_cache_dir=str(cache))
        assert len(list(cache.glob("*.npz"))) == 2

    def test_mesh_shape_invalidates(self, tmp_path, caplog):
        """row_multiple depends on the mesh axes; a cache built for one
        mesh must not feed a differently-aligned one."""
        import jax

        from predictionio_tpu.parallel.mesh import make_mesh

        ui, ii, r, n_u, n_i = _data()
        cache = str(tmp_path / "cache")
        m1 = make_mesh({"data": 1, "model": 1}, devices=jax.devices()[:1])
        als_train(ui, ii, r, n_u, n_i, CFG, mesh=m1, bucket_cache_dir=cache)
        m2 = make_mesh({"data": 4, "model": 2})
        with caplog.at_level(logging.INFO, "predictionio_tpu.ops.als"):
            out = als_train(ui, ii, r, n_u, n_i, CFG, mesh=m2,
                            bucket_cache_dir=cache)
        assert any("bucket cache miss" in m for m in caplog.messages)
        assert np.isfinite(out.user_factors).all()
