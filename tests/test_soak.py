"""Short-window soak mechanism drill (VERDICT r4 next #6).

The full receipt is `bench.py --soak --duration 600` (recorded in
BASELINE.md); the suite runs the same machinery — concurrent ingest +
serving + background retrain/reload with RSS/fd/thread probes and the
starvation/error gates — over a window short enough for CI. The
flatness assertions themselves execute either way (bench_soak raises on
any error, starvation, RSS growth past bar, or fd leak)."""

import pytest


@pytest.mark.e2e
def test_short_soak_mixed_load():
    import bench

    record = bench.bench_soak(duration_s=25.0, emit=False,
                              retrain_every_s=8.0)
    assert record["errors"] == 0
    assert record["counts"]["serve"] > 0
    assert record["counts"]["ingest"] > 0
    assert record["counts"]["retrain"] >= 1
    assert record["counts"]["reload"] >= 1
    assert record["rss_mb"]["growth_vs_warm"] <= 1.15
    assert record["fds"]["end"] <= record["fds"]["baseline"] + 15
