"""Pallas TPU kernel: batched SPD solve via vectorized Gauss-Jordan.

The other ALS hot op: after the Gram/RHS einsums, each bucket needs
x_r = A_r⁻¹ b_r for thousands of small (K×K, K = rank) SPD systems. XLA
lowers `jnp.linalg.cholesky` to a custom-call whose batched factorization
dominates rank-64 epochs (v5e profile, round 1: 873 ms of a 1.8 s 10-iter
loop on the 12 664-row bucket — ~66% of device time including the paired
triangular solves). A batched CG solver is worse still (1.5–2.8 s/epoch
vs 1.07 s): its matvecs re-read the [R, K, K] Gram from HBM every
iteration.

This kernel instead runs Gauss-Jordan elimination on the *augmented*
matrix [A | b], vectorized over the batch: a [R_tile, K, K+1] block of
systems is reduced with K data-independent steps of elementwise VPU work
(pivot row/column selection via one-hot iota masks, elimination as one
fused FMA+select pass), so throughput scales with the batch instead of
the sequential critical path of one factorization. When the elimination
finishes, A has become I and the augmented column holds x.

Mosaic lessons baked in (round-1 findings, kept so nobody re-learns them):
- dynamic slices/stores on the sublane/lane dims miscompile silently
  (compiled output diverged while interpret mode was exact) — all
  selection goes through one-hot masks, and the grid walks the outer
  (batch) dim only;
- `input_output_aliases` does NOT deliver the input inside the out block
  once the grid pipelines (>1 tile ⇒ NaNs) — the working copy is an
  explicit VMEM scratch instead.

Gauss-Jordan does ~2·K³ useful FLOPs per system (vs Cholesky's K³/3) but
they are perfectly batch-parallel VPU FMAs instead of a sequential
custom-call — measured 3.4× faster than the Cholesky path at rank 64 on
v5e (110 ms → 32 ms on a [12664, 64, 64] batch; BASELINE.md). No
pivoting: A = YᵀWY + λ(n)I is SPD with strictly
positive diagonal, the same assumption MLlib's dppsv Cholesky makes.
All-zero systems (bucket padding rows) short-circuit to x = 0 via the
pivot guard.

No reference counterpart: PredictionIO delegates these solves to Spark
MLlib's JNI BLAS («org.apache.spark.mllib.recommendation.ALS» →
CholeskyDecomposition.solve — SURVEY.md §2.5 [U]); this kernel is the
TPU-native equivalent of that native layer.
"""

from __future__ import annotations

import functools

# VMEM budget for blocks in flight: pipelined input blocks + the scratch
# working copy + x (≈4 augmented blocks of slack). Sets the batch tile.
_VMEM_BUDGET = 12 * 1024 * 1024
_LANES = 128
_MAX_RANK = 256


def _lane_pad(n: int) -> int:
    return -(-n // _LANES) * _LANES


def _row_tile(k: int) -> int:
    """Batch tile (multiple of 8, ≤128) sized so ~4 augmented blocks fit."""
    per_row = k * _lane_pad(k + 1) * 4
    t = _VMEM_BUDGET // (4 * per_row)
    return max(8, min(128, t // 8 * 8))


def gj_applicable(rank: int) -> bool:
    return rank <= _MAX_RANK


@functools.lru_cache(maxsize=32)
def _build_solver(k: int, r_tile: int, n_tiles: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kp = _lane_pad(k + 1)  # augmented + lane-padded column count

    def kernel(aug_ref, x_ref, scr):
        scr[:] = aug_ref[:]
        sub = jax.lax.broadcasted_iota(jnp.int32, (1, k, 1), 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, kp), 2)

        def step(j, _):
            a = scr[:]  # [R, K, KP]
            is_row = sub == j
            is_col = lane == j
            row = jnp.sum(jnp.where(is_row, a, 0.0), axis=1,
                          keepdims=True)  # [R, 1, KP] pivot row
            d = jnp.sum(jnp.where(is_col, row, 0.0), axis=2,
                        keepdims=True)  # [R, 1, 1] pivot
            # all-zero (padding) systems: guard the pivot so they solve
            # to x = 0 instead of poisoning the tile with inf/NaN
            d = jnp.where(jnp.abs(d) < 1e-30, 1.0, d)
            row = row / d
            col = jnp.sum(jnp.where(is_col, a, 0.0), axis=2,
                          keepdims=True)  # [R, K, 1] pivot column
            # row j eliminates every *other* row; storing the scaled
            # pivot row rides the same select pass
            col = jnp.where(is_row, 0.0, col)
            scr[:] = jnp.where(is_row, row, a - col * row)
            return 0

        jax.lax.fori_loop(0, k, step, 0, unroll=False)
        # x = the augmented column, folded back to [R, K] (K on lanes)
        is_b = lane == k
        x_ref[:] = jnp.sum(jnp.where(is_b, scr[:], 0.0), axis=2)

    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((r_tile, k, kp), lambda g: (g, 0, 0))],
        out_specs=pl.BlockSpec((r_tile, k), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((n_tiles * r_tile, k), jnp.float32),
        scratch_shapes=[pltpu.VMEM((r_tile, k, kp), jnp.float32)],
        interpret=interpret,
    )


def gj_solve(a, b, interpret: bool = False):
    """Solve x = A⁻¹ b for a batch of SPD systems.

    a: [R, K, K] f32 — SPD (λ-regularized normal equations); all-zero
       systems (bucket padding rows) yield x = 0.
    b: [R, K] f32
    returns x: [R, K] f32
    """
    import jax.numpy as jnp

    r, k, _ = a.shape
    r_tile = _row_tile(k)
    r_pad = -(-r // r_tile) * r_tile
    kp = _lane_pad(k + 1)
    aug = jnp.concatenate(
        [a.astype(jnp.float32), b.astype(jnp.float32)[..., None]], axis=-1)
    aug = jnp.pad(aug, ((0, r_pad - r), (0, 0), (0, kp - (k + 1))))
    x = _build_solver(k, r_tile, r_pad // r_tile, interpret)(aug)
    return x[:r]
