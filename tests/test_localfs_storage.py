"""LocalFS model store + pluggable backend registry (SURVEY.md §2.2
'Storage registry' env contract, 'LocalFS/HDFS/S3 model stores')."""

import os

import pytest

from predictionio_tpu.storage.base import Model
from predictionio_tpu.storage.localfs import LocalFSBackend, LocalFSModels
from predictionio_tpu.storage.registry import (
    BACKEND_TYPES,
    SourceConfig,
    Storage,
    StorageConfig,
    register_backend,
)


class TestLocalFSModels:
    def test_round_trip_and_delete(self, tmp_path):
        store = LocalFSModels(str(tmp_path))
        store.insert(Model(id="abc123", models=b"\x00\x01factors"))
        got = store.get("abc123")
        assert got is not None and got.models == b"\x00\x01factors"
        assert store.delete("abc123") is True
        assert store.get("abc123") is None
        assert store.delete("abc123") is False

    def test_overwrite(self, tmp_path):
        store = LocalFSModels(str(tmp_path))
        store.insert(Model(id="m", models=b"v1"))
        store.insert(Model(id="m", models=b"v2"))
        assert store.get("m").models == b"v2"

    def test_rejects_path_escape(self, tmp_path):
        store = LocalFSModels(str(tmp_path))
        for bad in ("../evil", "a/b", "a\\b", ""):
            with pytest.raises(ValueError):
                store.get(bad)

    def test_non_models_repos_fail_fast(self, tmp_path):
        backend = LocalFSBackend(str(tmp_path))
        with pytest.raises(NotImplementedError):
            backend.apps()
        with pytest.raises(NotImplementedError):
            backend.events()


class TestEnvWiring:
    def test_mixed_sources_from_env(self, tmp_path):
        """Reference-style deployment: metadata+events in sqlite, model
        blobs on the filesystem — via the PIO_STORAGE_* env contract."""
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "PGLIKE",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PGLIKE",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
            "PIO_STORAGE_SOURCES_PGLIKE_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_PGLIKE_PATH": str(tmp_path / "meta.db"),
            "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_LOCALFS_PATH": str(tmp_path / "models"),
        }
        storage = Storage(StorageConfig.from_env(env))
        try:
            storage.model_data_models().insert(Model(id="x1", models=b"blob"))
            assert os.path.exists(tmp_path / "models" / "x1.model")
            assert storage.model_data_models().get("x1").models == b"blob"
            # metadata landed in sqlite, not localfs
            from predictionio_tpu.storage.base import App

            storage.meta_apps().insert(App(id=0, name="EnvApp"))
            assert storage.meta_apps().get_by_name("EnvApp") is not None
            assert all(storage.verify_all_data_objects().values())
        finally:
            storage.close()

    def test_localfs_default_path_uses_basedir(self, tmp_path):
        env = {
            "PIO_FS_BASEDIR": str(tmp_path),
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LFS",
            "PIO_STORAGE_SOURCES_LFS_TYPE": "localfs",
        }
        cfg = StorageConfig.from_env(env)
        assert cfg.modeldata.path == str(tmp_path / "models")

    def test_unknown_type_rejected(self):
        env = {"PIO_STORAGE_SOURCES_PIO_DEFAULT_TYPE": "hbase"}
        with pytest.raises(ValueError, match="hbase"):
            StorageConfig.from_env(env)


class TestPluggableBackends:
    def test_register_custom_backend(self, tmp_path):
        calls = []

        def factory(source):
            calls.append(source.name)
            return LocalFSBackend(source.path)

        register_backend("mycloud", factory)
        try:
            env = {
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MC",
                "PIO_STORAGE_SOURCES_MC_TYPE": "mycloud",
                "PIO_STORAGE_SOURCES_MC_PATH": str(tmp_path),
            }
            storage = Storage(StorageConfig.from_env(env))
            storage.model_data_models().insert(Model(id="c", models=b"z"))
            assert calls == ["MC"]
            storage.close()
        finally:
            BACKEND_TYPES.pop("mycloud", None)


class TestTrainDeployOnLocalFS:
    def test_model_blob_lands_on_filesystem(self, tmp_path):
        """End-to-end: train stores the serialized model via localfs; the
        prediction server deploys from it."""
        from predictionio_tpu.sdk import EngineClient
        from predictionio_tpu.workflow.create_server import (
            PredictionServer,
            ServerConfig,
        )
        from tests.test_prediction_server import train_once
        from tests.test_recommendation_template import ingest_ratings

        env = {
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
            "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_LOCALFS_PATH": str(tmp_path / "models"),
            "PIO_STORAGE_SOURCES_PIO_DEFAULT_TYPE": "memory",
        }
        storage = Storage(StorageConfig.from_env(env))
        Storage.reset(storage)
        try:
            ingest_ratings(storage)
            instance = train_once(storage)
            blob_file = tmp_path / "models" / f"{instance.id}.model"
            assert blob_file.exists() and blob_file.stat().st_size > 0
            server = PredictionServer(
                ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                             engine_variant="rec-test"), storage)
            server.start()
            try:
                client = EngineClient(url=f"http://127.0.0.1:{server.port}")
                assert "itemScores" in client.send_query({"user": "u1", "num": 2})
            finally:
                server.shutdown()
        finally:
            storage.close()
            Storage.reset(None)
