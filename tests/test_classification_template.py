"""Classification template end-to-end: $set property events → labeled
points → NB / LogReg train → label queries (SURVEY.md §2.4 Classification
row; §7.2 step 7)."""

import numpy as np
import pytest

from predictionio_tpu.controller import WorkflowContext
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.storage.base import App
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_utils import (
    EngineVariant,
    extract_engine_params,
    get_engine,
)

FACTORY = "predictionio_tpu.templates.classification.ClassificationEngine"


def ingest_users(storage, app_name="ClsApp", n_per_class=20, seed=0):
    """Three separable classes in attr space: plan c has attrs ~ onehot(c)*4."""
    app_id = storage.meta_apps().insert(App(id=0, name=app_name))
    le = storage.l_events()
    rng = np.random.default_rng(seed)
    uid = 0
    for plan in (0.0, 1.0, 2.0):
        base = np.eye(3)[int(plan)] * 4.0
        for _ in range(n_per_class):
            attrs = np.maximum(0.0, base + rng.integers(0, 2, size=3))
            le.insert(
                Event(
                    event="$set", entity_type="user", entity_id=f"u{uid}",
                    properties=DataMap({
                        "attr0": float(attrs[0]),
                        "attr1": float(attrs[1]),
                        "attr2": float(attrs[2]),
                        "plan": plan,
                    }),
                ),
                app_id,
            )
            uid += 1


def variant_dict(app_name="ClsApp", algo="naive", algo_params=None):
    return {
        "id": "cls-test",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [{"name": algo, "params": algo_params or {}}],
    }


class TestClassificationEndToEnd:
    @pytest.mark.parametrize(
        "algo,params",
        [
            ("naive", {"lambda": 1.0}),
            ("logisticregression", {"iterations": 300, "stepSize": 0.3}),
        ],
    )
    def test_train_and_classify(self, memory_storage, algo, params):
        ingest_users(memory_storage)
        variant = EngineVariant.from_dict(variant_dict(algo=algo, algo_params=params))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage, seed=0)
        instance = CoreWorkflow.run_train(engine, ep, variant, ctx)
        assert instance.status == "COMPLETED"

        blob = memory_storage.model_data_models().get(instance.id).models
        models = engine.deserialize_models(blob, instance.id, ep)
        # each class prototype should classify back to its own plan
        for plan in (0.0, 1.0, 2.0):
            proto = (np.eye(3)[int(plan)] * 4.0).tolist()
            q = {"attr0": proto[0], "attr1": proto[1], "attr2": proto[2]}
            assert engine.predict(ep, models, q) == {"label": plan}

    def test_attribute_order_is_training_order(self, memory_storage):
        """Non-lexicographic attribute config must still vectorize queries
        in training column order (regression: sorted(query) permuted the
        features)."""
        ingest_users(memory_storage)
        variant = EngineVariant.from_dict({
            "id": "cls-order",
            "engineFactory": FACTORY,
            "datasource": {"params": {
                "appName": "ClsApp",
                "attributes": ["attr2", "attr0", "attr1"],
            }},
            "algorithms": [{"name": "naive", "params": {"lambda": 1.0}}],
        })
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        for plan in (0.0, 1.0, 2.0):
            proto = np.eye(3)[int(plan)] * 4.0
            q = {"attr0": proto[0], "attr1": proto[1], "attr2": proto[2]}
            assert engine.predict(ep, models, q) == {"label": plan}
        with pytest.raises(ValueError, match="missing attribute"):
            engine.predict(ep, models, {"attr0": 1.0, "attr1": 2.0})

    def test_query_features_list_form(self, memory_storage):
        ingest_users(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        r = engine.predict(ep, models, {"features": [4.0, 0.0, 0.0]})
        assert r == {"label": 0.0}

    def test_bad_feature_count_raises(self, memory_storage):
        ingest_users(memory_storage)
        variant = EngineVariant.from_dict(variant_dict())
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        models = engine.train(ctx, ep)
        with pytest.raises(ValueError, match="features"):
            engine.predict(ep, models, {"features": [1.0, 2.0]})

    def test_empty_app_fails_sanity_check(self, memory_storage):
        memory_storage.meta_apps().insert(App(id=0, name="EmptyCls"))
        variant = EngineVariant.from_dict(variant_dict("EmptyCls"))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        ctx = WorkflowContext(storage=memory_storage)
        with pytest.raises(ValueError, match="no labeled points"):
            CoreWorkflow.run_train(engine, ep, variant, ctx)

    def test_evaluation_accuracy(self, memory_storage):
        ingest_users(memory_storage)
        variant = EngineVariant.from_dict({
            "id": "cls-eval",
            "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "ClsApp", "evalK": 3}},
            "algorithms": [{"name": "naive", "params": {"lambda": 1.0}}],
        })
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        from predictionio_tpu.controller import AverageMetric
        from predictionio_tpu.controller.evaluation import Evaluation, MetricEvaluator

        class Accuracy(AverageMetric):
            def calculate(self, q, p, a):
                return 1.0 if p["label"] == a["label"] else 0.0

        class ClsEval(Evaluation):
            pass

        ClsEval.engine = engine
        ClsEval.metric = Accuracy()
        ctx = WorkflowContext(storage=memory_storage, seed=0)
        result = MetricEvaluator.evaluate(ctx, ClsEval(), [ep])
        assert result.best.scores["Accuracy"] >= 0.9

    def test_template_engine_json_parses(self):
        import os

        from predictionio_tpu.workflow.workflow_utils import read_engine_json

        path = os.path.join(
            os.path.dirname(__file__), "..", "predictionio_tpu", "templates",
            "classification", "engine.json")
        variant = read_engine_json(path)
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        assert ep.algorithm_params_list[0][0] == "naive"
        assert ep.algorithm_params_list[0][1].lambda_ == 1.0


class TestClassifyOps:
    def test_nb_matches_hand_computation(self):
        from predictionio_tpu.ops.classify import naive_bayes_train

        x = np.array([[1.0, 0.0], [2.0, 0.0], [0.0, 1.0], [0.0, 3.0]],
                     dtype=np.float32)
        y = np.array([0, 0, 1, 1], dtype=np.int32)
        m = naive_bayes_train(x, y, n_classes=2, smoothing=1.0)
        # priors: (2+1)/(4+2) each → log(0.5)
        np.testing.assert_allclose(m.log_prior, np.log([0.5, 0.5]), rtol=1e-5)
        # class 0 feature sums [3, 0], total 3: theta = [(3+1)/(3+2), (0+1)/(3+2)]
        np.testing.assert_allclose(
            np.exp(m.log_theta[0]), [4 / 5, 1 / 5], rtol=1e-5)
        np.testing.assert_allclose(
            np.exp(m.log_theta[1]), [1 / 6, 5 / 6], rtol=1e-5)

    def test_nb_rejects_negative_features(self):
        from predictionio_tpu.ops.classify import naive_bayes_train

        with pytest.raises(ValueError, match="non-negative"):
            naive_bayes_train(
                np.array([[-1.0]], dtype=np.float32),
                np.array([0], dtype=np.int32), n_classes=1)

    def test_nb_on_non_divisor_mesh_axis(self):
        """Padding must reach a common multiple of 8 and the data-axis size
        (regression: max(8, axis) broke P("data") placement on axis=6)."""
        import jax

        from predictionio_tpu.ops.classify import naive_bayes_train
        from predictionio_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": 6, "model": 1}, devices=jax.devices()[:6])
        rng = np.random.default_rng(0)
        x = rng.random((10, 3)).astype(np.float32)
        y = (np.arange(10) % 2).astype(np.int32)
        m = naive_bayes_train(x, y, n_classes=2, mesh=mesh)
        assert m.log_theta.shape == (2, 3)

    def test_logreg_separable_converges(self):
        from predictionio_tpu.ops.classify import logreg_train

        rng = np.random.default_rng(0)
        x0 = rng.normal(-2.0, 0.5, size=(40, 2))
        x1 = rng.normal(2.0, 0.5, size=(40, 2))
        x = np.vstack([x0, x1]).astype(np.float32)
        y = np.array([0] * 40 + [1] * 40, dtype=np.int32)
        m = logreg_train(x, y, n_classes=2, iterations=200, learning_rate=0.2)
        pred = np.argmax(m.logits(x), axis=-1)
        assert (pred == y).mean() == 1.0
        assert m.loss_history[-1] < m.loss_history[0]
