"""Sequence-parallel attention: ring attention + Ulysses (all-to-all).

The reference has no sequence dimension (SURVEY.md §5 'Long-context':
PredictionIO predates transformers; its only big-tensor shard is MLlib's
block-partitioned ALS interaction matrix). The rebuild still ships
long-context sequence parallelism as first-class infrastructure, because
a TPU-native framework's scale story is shaped by it:

- `ring_attention`: queries/keys/values sharded over the mesh sequence
  axis; K/V blocks rotate around the ring via `ppermute` while each step
  folds one block into a numerically-stable online softmax (the
  flash/ring-attention recurrence). Peak memory per device is O(S/n · d)
  and the ICI traffic overlaps with the per-block matmuls.
- `ulysses_attention`: `all_to_all` re-shards seq → heads, computes
  full-sequence attention locally per head group, and all_to_alls back —
  cheaper collective volume when heads % n_shards == 0.

Both are exact (not approximations) and match `dense_attention` to float
tolerance; causal masking uses global positions so it is shard-layout
invariant. Shapes: [batch, heads, seq, head_dim], seq sharded.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import DATA_AXIS

_NEG_INF = -1e30  # large-negative instead of -inf: keeps fully-masked rows
# (causal ring blocks entirely in the future) NaN-free after softmax


def dense_attention(q, k, v, causal: bool = False):
    """Reference single-device attention. q,k,v: [B, H, S, D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _online_block(o, m, l, q, k_blk, v_blk, q_pos, kv_pos, causal):
    """Fold one K/V block into the running (o, m, l) softmax state."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) / math.sqrt(q.shape[-1])
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Sq, Skv]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Sq]
    m_new = jnp.maximum(m, m_blk)
    # rescale old accumulators, then add this block's contribution
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
    return o_new, m_new, l_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = DATA_AXIS,
                   causal: bool = False):
    """Exact attention with seq sharded over `axis`; K/V ring-rotate.

    q, k, v: [B, H, S, D] jax arrays (global view); S % mesh.shape[axis]
    == 0. Returns [B, H, S, D] sharded like q.
    """
    n = mesh.shape[axis]
    seq = q.shape[2]
    if seq % n != 0:
        raise ValueError(f"seq {seq} not divisible by {axis}={n}")
    blk = seq // n
    spec = P(None, None, axis, None)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec)
    def run(q_blk, k_blk, v_blk):
        idx = jax.lax.axis_index(axis)
        q_pos = idx * blk + jnp.arange(blk)
        o = jnp.zeros_like(q_blk)
        m = jnp.full(q_blk.shape[:-1], _NEG_INF, dtype=q_blk.dtype)
        l = jnp.zeros(q_blk.shape[:-1], dtype=q_blk.dtype)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_cur, v_cur = k_blk, v_blk
        for step in range(n):  # static ring walk, unrolled under jit
            src = (idx - step) % n  # whose block we currently hold
            kv_pos = src * blk + jnp.arange(blk)
            o, m, l = _online_block(o, m, l, q_blk, k_cur, v_cur,
                                    q_pos, kv_pos, causal)
            if step + 1 < n:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return o / jnp.maximum(l, 1e-30)[..., None]

    return run(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = DATA_AXIS,
                      causal: bool = False):
    """Exact attention via all-to-all head/seq re-sharding (DeepSpeed-
    Ulysses style). Requires H % n == 0 and S % n == 0."""
    n = mesh.shape[axis]
    b, h, seq, d = q.shape
    if h % n != 0:
        raise ValueError(f"heads {h} not divisible by {axis}={n}")
    if seq % n != 0:
        raise ValueError(f"seq {seq} not divisible by {axis}={n}")
    spec = P(None, None, axis, None)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec)
    def run(q_blk, k_blk, v_blk):
        # [B, H, S/n, D] → all_to_all → [B, H/n, S, D]: full sequence,
        # head-group local
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        out = dense_attention(qh, kh, vh, causal=causal)
        return to_seq(out)

    return run(q, k, v)


def sequence_sharded_attention(q, k, v, mesh: Mesh, axis: str = DATA_AXIS,
                               causal: bool = False,
                               method: Optional[str] = None):
    """Pick the sequence-parallel strategy: 'ring', 'ulysses', or None =
    ulysses when heads divide evenly (lower collective volume), else
    ring."""
    n = mesh.shape[axis]
    if method is None:
        method = "ulysses" if q.shape[1] % n == 0 else "ring"
    if method == "ring":
        return ring_attention(q, k, v, mesh, axis, causal)
    if method == "ulysses":
        return ulysses_attention(q, k, v, mesh, axis, causal)
    raise ValueError(f"Unknown method {method!r} (ring | ulysses)")
