"""Plugin SPI: event-server input blockers/sniffers, engine-server output
blockers/sniffers, env discovery (SURVEY.md §5 plugin hooks)."""

import pytest

from predictionio_tpu.data.api import EventServer, EventServerConfig
from predictionio_tpu.plugins import (
    EngineServerPlugin,
    EventServerPlugin,
    PluginRegistry,
    PluginRejection,
    load_plugins_from_env,
)
from predictionio_tpu.sdk import EventClient, PredictionIOError
from predictionio_tpu.storage.base import AccessKey, App


class RejectBots(EventServerPlugin):
    plugin_name = "reject-bots"
    plugin_type = EventServerPlugin.INPUT_BLOCKER

    def process(self, event, app_id, channel_id):
        if event.get("entityId", "").startswith("bot-"):
            raise PluginRejection("bots are not welcome")


class CountingSniffer(EventServerPlugin):
    plugin_type = EventServerPlugin.INPUT_SNIFFER

    def __init__(self):
        self.seen = []

    def process(self, event, app_id, channel_id):
        self.seen.append(event["event"])


class CrashySniffer(EventServerPlugin):
    plugin_type = EventServerPlugin.INPUT_SNIFFER

    def process(self, event, app_id, channel_id):
        raise RuntimeError("boom")


class CapResults(EngineServerPlugin):
    plugin_type = EngineServerPlugin.OUTPUT_BLOCKER

    def process(self, query, prediction, instance_id):
        scores = prediction.get("itemScores", [])
        return {"itemScores": scores[:1]}


class PredictionSniffer(EngineServerPlugin):
    plugin_type = EngineServerPlugin.OUTPUT_SNIFFER

    def __init__(self):
        self.count = 0

    def process(self, query, prediction, instance_id):
        self.count += 1
        return "ignored-return"


@pytest.fixture()
def served(memory_storage):
    app_id = memory_storage.meta_apps().insert(App(id=0, name="PlugApp"))
    key = AccessKey.generate(app_id)
    memory_storage.meta_access_keys().insert(key)
    registry = PluginRegistry()
    sniffer = CountingSniffer()
    registry.register(RejectBots())
    registry.register(sniffer)
    registry.register(CrashySniffer())
    srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0),
                      memory_storage, plugins=registry)
    srv.start()
    yield EventClient(access_key=key.key,
                      url=f"http://127.0.0.1:{srv.port}"), sniffer
    srv.shutdown()


class TestEventServerPlugins:
    def test_blocker_rejects_with_403(self, served):
        client, _ = served
        with pytest.raises(PredictionIOError) as ei:
            client.set_user("bot-1")
        assert ei.value.status == 403 and "bots" in ei.value.message

    def test_sniffer_sees_accepted_events(self, served):
        client, sniffer = served
        client.set_user("human-1")
        client.record_user_action_on_item("view", "human-1", "i1")
        assert sniffer.seen == ["$set", "view"]

    def test_crashy_sniffer_does_not_break_ingest(self, served):
        client, _ = served
        eid = client.set_user("human-2")  # CrashySniffer raised, but logged
        assert client.get_event(eid)["entityId"] == "human-2"

    def test_batch_blocker_per_event_status(self, served):
        client, _ = served
        results = client.create_batch_events([
            {"event": "$set", "entityType": "user", "entityId": "bot-9"},
            {"event": "$set", "entityType": "user", "entityId": "ok"},
        ])
        assert [r["status"] for r in results] == [403, 201]


class TestEngineServerPlugins:
    def test_output_blocker_and_sniffer(self, memory_storage):
        from predictionio_tpu.workflow.create_server import (
            PredictionServer,
            ServerConfig,
        )
        from predictionio_tpu.sdk import EngineClient
        from tests.test_prediction_server import train_once
        from tests.test_recommendation_template import ingest_ratings

        ingest_ratings(memory_storage)
        train_once(memory_storage)
        registry = PluginRegistry()
        sniffer = PredictionSniffer()
        registry.register(CapResults())
        registry.register(sniffer)
        server = PredictionServer(
            ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                         engine_variant="rec-test"),
            memory_storage, plugins=registry)
        server.start()
        try:
            client = EngineClient(url=f"http://127.0.0.1:{server.port}")
            result = client.send_query({"user": "u1", "num": 5})
            assert len(result["itemScores"]) <= 1  # capped by blocker
            assert sniffer.count == 1  # sniffer ran, return value ignored
        finally:
            server.shutdown()


class TestDiscovery:
    def test_load_from_env_string(self):
        registry = load_plugins_from_env(
            env="tests.test_plugins:RejectBots, tests.test_plugins:CapResults")
        assert len(registry.event_plugins) == 1
        assert len(registry.engine_plugins) == 1

    def test_bad_spec_logged_not_raised(self):
        registry = load_plugins_from_env(env="no.such.module:Nope")
        assert registry.event_plugins == [] and registry.engine_plugins == []

    def test_register_rejects_non_plugin(self):
        with pytest.raises(TypeError):
            PluginRegistry().register(object())
