"""Train the TPU ALS and the MLlib-faithful CPU reference on identical
data; report held-out RMSE / MAP@10 side by side (VERDICT r1 #1).

The metric code here is shared numpy applied to both implementations'
factor matrices — what must be independent is the *training* math, and it
is (quality/mllib_als.py shares no code with ops/als.py). Cold-start
semantics match MLlib's `coldStartStrategy="drop"`: test entries whose
user or item has no training data are dropped from both metrics,
identically for both implementations.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from predictionio_tpu.ops.ranking import average_precision_at_k
from predictionio_tpu.quality import datasets
from predictionio_tpu.quality.mllib_als import mllib_als_train


def rmse_heldout(uf, itf, split: datasets.RatingSplit) -> float:
    """Held-out RMSE with cold (train-unseen) users/items dropped."""
    seen_u = np.zeros(split.n_users, bool)
    seen_u[split.train_u] = True
    seen_i = np.zeros(split.n_items, bool)
    seen_i[split.train_i] = True
    keep = seen_u[split.test_u] & seen_i[split.test_i]
    u, i, r = split.test_u[keep], split.test_i[keep], split.test_r[keep]
    pred = np.einsum("ij,ij->i", uf[u].astype(np.float64),
                     itf[i].astype(np.float64))
    return float(np.sqrt(np.mean((pred - r) ** 2)))


def map_at_k_heldout(uf, itf, split: datasets.RatingSplit, k: int = 10,
                     max_users: Optional[int] = None,
                     chunk: int = 2048) -> float:
    """MAP@k against held-out positives, train items excluded from the
    candidate ranking (the standard implicit-ALS protocol and what the
    reference's Recommendation template evaluation measures [U])."""
    test_users = np.unique(split.test_u)
    if max_users is not None and len(test_users) > max_users:
        rng = np.random.default_rng(12345)
        test_users = rng.choice(test_users, max_users, replace=False)
        test_users.sort()
    # CSR views of train/test per user
    def by_user(u_arr, i_arr):
        order = np.argsort(u_arr, kind="stable")
        counts = np.bincount(u_arr, minlength=split.n_users)
        indptr = np.concatenate(([0], np.cumsum(counts)))
        return indptr, i_arr[order]

    tr_ptr, tr_items = by_user(split.train_u, split.train_i)
    te_ptr, te_items = by_user(split.test_u, split.test_i)

    uf64 = uf.astype(np.float64)
    itf64 = itf.astype(np.float64)
    ap_sum, n_ap = 0.0, 0
    for s in range(0, len(test_users), chunk):
        users = test_users[s : s + chunk]
        scores = uf64[users] @ itf64.T  # [chunk, n_items]
        for row, u in enumerate(users):
            scores[row, tr_items[tr_ptr[u] : tr_ptr[u + 1]]] = -np.inf
        top = np.argpartition(-scores, k, axis=1)[:, :k]
        ord_ = np.take_along_axis(scores, top, axis=1).argsort(axis=1)[:, ::-1]
        top = np.take_along_axis(top, ord_, axis=1)
        for row, u in enumerate(users):
            actual = te_items[te_ptr[u] : te_ptr[u + 1]]
            if actual.size == 0:
                continue
            ap_sum += average_precision_at_k(
                top[row].tolist(), set(actual.tolist()), k)
            n_ap += 1
    return ap_sum / max(n_ap, 1)


def run_parity(
    mode: str = "explicit",
    scale: str = "100k",
    rank: int = 10,
    iterations: int = 10,
    reg: float = 0.1,
    alpha: float = 40.0,
    seed: int = 0,
    map_k: int = 10,
    map_max_users: Optional[int] = 20_000,
    ref_iterations: Optional[int] = None,
    als_kwargs: Optional[dict] = None,
) -> dict:
    """Returns {"ours": {...}, "ref": {...}, "delta": {...}, ...}."""
    implicit = mode == "implicit"
    split = (datasets.synth_implicit(scale, seed=seed) if implicit
             else datasets.synth_explicit(scale, seed=seed))

    from predictionio_tpu.ops.als import ALSConfig, als_train

    cfg = ALSConfig(rank=rank, iterations=iterations, reg=reg,
                    weighted_reg=True, implicit=implicit,
                    alpha=alpha if implicit else 1.0, seed=seed,
                    **(als_kwargs or {}))
    t0 = time.perf_counter()
    ours = als_train(split.train_u, split.train_i, split.train_r,
                     split.n_users, split.n_items, cfg)
    ours_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    ref = mllib_als_train(split.train_u, split.train_i, split.train_r,
                          split.n_users, split.n_items, rank=rank,
                          iterations=ref_iterations or iterations, reg=reg,
                          implicit=implicit, alpha=alpha, seed=seed)
    ref_wall = time.perf_counter() - t0

    out = {
        "mode": mode, "scale": scale, "rank": rank,
        "iterations": iterations, "reg": reg,
        "n_train": split.n_train, "n_test": split.n_test,
        "ours": {"wall_s": round(ours_wall, 2),
                 "epoch_s": (round(float(np.median(ours.epoch_times)), 4)
                             if ours.epoch_times else None)},
        "ref": {"wall_s": round(ref_wall, 2),
                "epoch_s": round(float(np.median(ref.epoch_times)), 4)},
    }
    if implicit:
        out["alpha"] = alpha
        for name, uf, itf in (("ours", ours.user_factors, ours.item_factors),
                              ("ref", ref.user_factors, ref.item_factors)):
            out[name]["map%d" % map_k] = round(
                map_at_k_heldout(uf, itf, split, map_k, map_max_users), 4)
        key = "map%d" % map_k
    else:
        for name, uf, itf in (("ours", ours.user_factors, ours.item_factors),
                              ("ref", ref.user_factors, ref.item_factors)):
            out[name]["rmse"] = round(rmse_heldout(uf, itf, split), 4)
        key = "rmse"
    out["delta"] = round(out["ours"][key] - out["ref"][key], 4)
    out["metric"] = key
    return out
