"""Plugin SPI for the event server and the prediction server.

Parity with the reference's service-provider hooks («data/.../api/
EventServerPlugin.scala» and the engine-server plugin SPI, SURVEY.md §5
'Metrics / logging' [U]): custom sinks/gates discovered at server start
and invoked on the hot paths.

Two plugin families, each with the reference's two roles:

- `EventServerPlugin` — called on every accepted ingest.
  * INPUT_BLOCKER: may veto an event by raising `PluginRejection`
    (client sees 403 with the plugin's message).
  * INPUT_SNIFFER: observes; exceptions are logged, never surfaced.
- `EngineServerPlugin` — called on every query.
  * OUTPUT_BLOCKER: may transform the prediction (returns the result to
    serve) or veto with `PluginRejection`.
  * OUTPUT_SNIFFER: observes (query, prediction); failures logged.

Discovery: explicit `register(...)` in code, or the `PIO_PLUGINS` env
var — a comma-separated list of `module:ClassName` loaded by
`load_plugins_from_env()` at server construction (the rebuild's stand-in
for the reference's classpath scan).
"""

from __future__ import annotations

import abc
import importlib
import logging
import os
from typing import Any, Optional

log = logging.getLogger(__name__)

EVENT_SERVER_PLUGINS_ENV = "PIO_PLUGINS"


class PluginRejection(Exception):
    """Raised by a blocker plugin to veto an event or a prediction."""


class EventServerPlugin(abc.ABC):
    INPUT_BLOCKER = "inputblocker"
    INPUT_SNIFFER = "inputsniffer"

    plugin_name: str = ""
    plugin_type: str = INPUT_SNIFFER

    @abc.abstractmethod
    def process(self, event: dict, app_id: int,
                channel_id: Optional[int]) -> None:
        """Inspect one incoming event (wire-format dict). Blockers raise
        `PluginRejection` to refuse it."""


class EngineServerPlugin(abc.ABC):
    OUTPUT_BLOCKER = "outputblocker"
    OUTPUT_SNIFFER = "outputsniffer"

    plugin_name: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    @abc.abstractmethod
    def process(self, query: dict, prediction: Any,
                instance_id: str) -> Any:
        """Inspect one (query, prediction). Blockers return the (possibly
        transformed) prediction to serve, or raise `PluginRejection`;
        sniffer return values are ignored."""


class PluginRegistry:
    """Holds the plugins wired into one server instance."""

    def __init__(self):
        self.event_plugins: list[EventServerPlugin] = []
        self.engine_plugins: list[EngineServerPlugin] = []

    def register(self, plugin) -> None:
        if isinstance(plugin, EventServerPlugin):
            self.event_plugins.append(plugin)
        elif isinstance(plugin, EngineServerPlugin):
            self.engine_plugins.append(plugin)
        else:
            raise TypeError(
                f"{type(plugin).__name__} is neither an EventServerPlugin "
                "nor an EngineServerPlugin")
        log.info("plugins: registered %s (%s)",
                 plugin.plugin_name or type(plugin).__name__,
                 plugin.plugin_type)

    # -- hot-path hooks ----------------------------------------------------

    def on_event(self, event: dict, app_id: int,
                 channel_id: Optional[int]) -> None:
        """Run event plugins. Propagates `PluginRejection` from blockers;
        swallows (logs) everything else."""
        for p in self.event_plugins:
            try:
                p.process(event, app_id, channel_id)
            except PluginRejection:
                if p.plugin_type == EventServerPlugin.INPUT_BLOCKER:
                    raise
                log.warning("plugins: sniffer %s raised PluginRejection "
                            "(ignored; not a blocker)",
                            type(p).__name__)
            except Exception:
                log.exception("plugins: %s failed on event", type(p).__name__)

    def on_prediction(self, query: dict, prediction: Any,
                      instance_id: str) -> Any:
        """Run engine plugins; blockers may replace the prediction."""
        for p in self.engine_plugins:
            try:
                out = p.process(query, prediction, instance_id)
                if p.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER:
                    prediction = out
            except PluginRejection:
                if p.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER:
                    raise
                log.warning("plugins: sniffer %s raised PluginRejection "
                            "(ignored; not a blocker)", type(p).__name__)
            except Exception:
                log.exception("plugins: %s failed on prediction",
                              type(p).__name__)
        return prediction


def load_plugins_from_env(registry: Optional[PluginRegistry] = None,
                          env: Optional[str] = None) -> PluginRegistry:
    """Instantiate plugins named in `PIO_PLUGINS` (module:Class,...)."""
    registry = registry or PluginRegistry()
    spec = env if env is not None else os.environ.get(
        EVENT_SERVER_PLUGINS_ENV, "")
    for item in filter(None, (s.strip() for s in spec.split(","))):
        module_name, _, cls_name = item.partition(":")
        try:
            cls = getattr(importlib.import_module(module_name), cls_name)
            registry.register(cls())
        except Exception:
            log.exception("plugins: cannot load %r", item)
    return registry
