"""Fixture: the sessionrec scorer shape — a session history's
len() passed straight into a jit-wrapped scorer (flagged: every new
history length is a fresh trace) next to the disciplined spelling that
rounds the length through the serving plane's seq-tier helper first
(legal: the executable space stays bounded by the ladder)."""


def metered_jit(fn, label=""):
    return fn


def _score(params, seq, length):
    return seq


score = metered_jit(_score, label="fixture.sessionrec.score")


def bad_session_call(params, history):
    return score(params, history, len(history))


def good_session_call(params, history):
    length = _pad_seq_tier(len(history))
    return score(params, history, length)


def _pad_seq_tier(n):
    return max(8, 1 << (n - 1).bit_length())
