"""Market-basket co-occurrence mining, TPU-first.

Compute path for the Complementary Purchase template (upstream gallery
template «template-scala-parallel-complementarypurchase» [U] — its Spark
job self-joins basket RDDs to count itemset co-occurrence). The TPU
formulation: baskets become one-hot rows and co-occurrence is a Gram
matrix on the MXU —

    B ∈ {0,1}^[n_baskets, n_items]   (built on device by scatter from COO)
    C = BᵀB                          (C[i,j] = #baskets containing both)

B is never materialized whole: baskets stream through in row chunks under
`lax.fori_loop`, each chunk contributing one [n_items, n_items] matmul
(bf16 inputs, f32 accumulation — counts are exact integers well inside
bf16·bf16→f32 range per chunk). The diagonal carries item supports.

Association scores from C (n = total baskets):
    support(i,j)    = C[i,j] / n
    confidence(i→j) = C[i,j] / C[i,i]
    lift(i→j)       = C[i,j]·n / (C[i,i]·C[j,j])

The dense [n_items, n_items] Gram bounds the catalog this path serves
(`max_dense_items`, default 8192 ≈ 256 MB f32); larger catalogs use the
numpy sparse-pair fallback (same math, hash-map counts on host).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class BasketRules:
    """Pairwise rules i → j, pre-filtered and top-k'd per antecedent."""

    cond_items: np.ndarray  # [R] int32 — antecedent item row
    cons_items: np.ndarray  # [R, k] int32 — consequent rows, -1 padded
    scores: np.ndarray  # [R, k] float32 — ranking score (lift or conf)
    support: np.ndarray  # [R, k] float32
    confidence: np.ndarray  # [R, k] float32
    lift: np.ndarray  # [R, k] float32
    n_baskets: int = 0

    def lookup(self, cond_row: int) -> Optional[int]:
        """Index into the rule table for an antecedent row, or None."""
        i = np.searchsorted(self.cond_items, cond_row)
        if i < len(self.cond_items) and self.cond_items[i] == cond_row:
            return int(i)
        return None


def _dedup_and_cap(basket_idx, item_idx, n_baskets: int,
                   max_basket_items: int, caller: str):
    """Shared pre-pass for BOTH count paths: dedup (basket, item) pairs
    (incidence is 0/1 — repeat purchases must not count twice OR crowd
    real items out of the cap), then truncate oversized baskets to
    `max_basket_items` distinct items (lowest item ids — deterministic)
    with a warning."""
    basket_idx = np.asarray(basket_idx, np.int64)
    item_idx = np.asarray(item_idx, np.int64)
    n_items_span = int(item_idx.max(initial=-1)) + 1
    pair = np.unique(basket_idx * max(n_items_span, 1) + item_idx)
    b_sorted = (pair // max(n_items_span, 1)).astype(np.int32)
    i_sorted = (pair % max(n_items_span, 1)).astype(np.int32)
    counts = np.bincount(b_sorted, minlength=n_baskets)
    if counts.max(initial=0) > max_basket_items:
        import logging

        logging.getLogger(__name__).warning(
            "%s: truncating %d basket(s) larger than %d distinct items",
            caller, int((counts > max_basket_items).sum()),
            max_basket_items)
        starts_full = np.concatenate(([0], np.cumsum(counts)))
        rank = np.arange(len(b_sorted)) - starts_full[b_sorted]
        keep = rank < max_basket_items
        b_sorted = b_sorted[keep]
        i_sorted = i_sorted[keep]
    return b_sorted, i_sorted


def cooccurrence_matrix(
    basket_idx: np.ndarray,
    item_idx: np.ndarray,
    n_baskets: int,
    n_items: int,
    chunk: int = 1024,
    max_basket_items: int = 512,
) -> np.ndarray:
    """C[i, j] = number of baskets containing both i and j (diagonal =
    per-item support counts). Chunked one-hot + MXU Gram on device.

    `max_basket_items` truncates pathological baskets (a crawler "basket"
    with 100k purchases would otherwise set the rectangular chunk walk's
    padded width for EVERY chunk — r2 review): oversized baskets keep N
    DISTINCT items (duplicates are deduped before the cap, so repeat
    purchases never crowd out real items), with a warning. Association
    rules from bot-sized baskets are noise, not signal.
    """
    import jax
    import jax.numpy as jnp

    if len(basket_idx) == 0:
        return np.zeros((n_items, n_items), np.float32)
    b_sorted, i_sorted = _dedup_and_cap(basket_idx, item_idx, n_baskets,
                                        max_basket_items,
                                        "cooccurrence_matrix")
    counts = np.bincount(b_sorted, minlength=n_baskets)
    starts = np.concatenate(([0], np.cumsum(counts)))

    n_chunks = -(-n_baskets // chunk)
    # pad entries to a rectangular [n_chunks, max_entries] walk: simpler
    # and XLA-friendly — each chunk gets (entry_rows, entry_cols) slices
    max_e = 0
    for c in range(n_chunks):
        lo = starts[c * chunk]
        hi = starts[min((c + 1) * chunk, n_baskets)]
        max_e = max(max_e, hi - lo)
    rows = np.zeros((n_chunks, max_e), np.int32)
    cols = np.zeros((n_chunks, max_e), np.int32)
    valid = np.zeros((n_chunks, max_e), np.float32)
    for c in range(n_chunks):
        lo = starts[c * chunk]
        hi = starts[min((c + 1) * chunk, n_baskets)]
        e = hi - lo
        rows[c, :e] = b_sorted[lo:hi] - c * chunk  # chunk-local basket row
        cols[c, :e] = i_sorted[lo:hi]
        valid[c, :e] = 1.0

    rows_d = jnp.asarray(rows)
    cols_d = jnp.asarray(cols)
    valid_d = jnp.asarray(valid)

    def body(c, acc):
        # one-hot incidence for this chunk's baskets; padding entries
        # scatter to row `chunk` (dropped) so they contribute nothing
        r = jnp.where(valid_d[c] > 0, rows_d[c], chunk)
        m = jnp.zeros((chunk + 1, n_items), jnp.float32)
        # max: duplicate (basket, item) pairs must stay 0/1, not count 2
        m = m.at[r, cols_d[c]].max(valid_d[c])
        m = m[:chunk].astype(jnp.bfloat16)
        return acc + jax.lax.dot_general(
            m, m, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    from predictionio_tpu.utils.profiling import metered_jit

    def run():
        acc0 = jnp.zeros((n_items, n_items), jnp.float32)
        return jax.lax.fori_loop(0, n_chunks, body, acc0)

    return np.asarray(metered_jit(run, label="basket.cooccurrence")())


def cooccurrence_matrix_host(
    basket_idx: np.ndarray,
    item_idx: np.ndarray,
    n_baskets: int,
    n_items: int,
    max_basket_items: int = 512,
) -> dict:
    """Sparse host fallback for catalogs too large for the dense Gram:
    {(i, j): count} for i < j plus {i: support} — same math, and the SAME
    basket cap as the dense path (an unbounded bot basket would otherwise
    enumerate O(n²) pairs here — r2 review)."""
    from collections import Counter, defaultdict

    if len(basket_idx):
        basket_idx, item_idx = _dedup_and_cap(
            basket_idx, item_idx, n_baskets, max_basket_items,
            "cooccurrence_matrix_host")
    per_basket: dict = defaultdict(set)
    for b, i in zip(basket_idx, item_idx):
        per_basket[int(b)].add(int(i))
    support: Counter = Counter()
    pairs: Counter = Counter()
    for items in per_basket.values():
        s = sorted(items)
        support.update(s)
        for a_i in range(len(s)):
            for b_i in range(a_i + 1, len(s)):
                pairs[(s[a_i], s[b_i])] += 1
    return {"support": support, "pairs": pairs}


def mine_rules(
    basket_idx: np.ndarray,
    item_idx: np.ndarray,
    n_baskets: int,
    n_items: int,
    min_support: float = 0.0,
    min_confidence: float = 0.0,
    min_lift: float = 1.0,
    top_k: int = 10,
    score: str = "lift",
    max_dense_items: int = 8192,
    max_basket_items: int = 512,
) -> BasketRules:
    """Pairwise association rules i → j, thresholded and top-k'd.

    `score` ("lift" | "confidence") ranks each antecedent's consequents.
    min_support applies to the PAIR's support (fraction of baskets),
    matching the upstream template's minSupport semantics [U].
    """
    if score not in ("lift", "confidence"):
        raise ValueError(f"score must be 'lift' or 'confidence': {score!r}")
    n = max(n_baskets, 1)
    if n_items <= max_dense_items:
        C = cooccurrence_matrix(basket_idx, item_idx, n_baskets, n_items,
                                max_basket_items=max_basket_items)
    else:
        sp = cooccurrence_matrix_host(basket_idx, item_idx, n_baskets,
                                      n_items,
                                      max_basket_items=max_basket_items)
        return _rules_from_sparse(sp, n, n_items, min_support,
                                  min_confidence, min_lift, top_k, score)

    # row-wise pass: materializing full [n_items, n_items] supp/conf/lift
    # planes alongside C would peak ~7× the documented Gram budget (r2
    # review); per-condition rows keep the peak at C + O(n_items)
    diag = np.diag(C).copy()
    # candidate condition rows: any co-occurrence beyond the diagonal
    nz_per_row = np.count_nonzero(C, axis=1)
    candidates = np.nonzero(nz_per_row - (diag > 0) > 0)[0]

    k = min(top_k, n_items)
    ids = np.arange(n_items)
    cond_list, rows_out = [], []
    for i in candidates:
        cn = C[i].copy()
        cn[i] = 0.0
        supp = cn / n
        with np.errstate(divide="ignore", invalid="ignore"):
            conf = cn / diag[i] if diag[i] > 0 else np.zeros_like(cn)
            lift = np.where(diag > 0, cn * n / (diag[i] * diag), 0.0) \
                if diag[i] > 0 else np.zeros_like(cn)
        # cn > 0: a rule requires actual co-occurrence (self-pairs and
        # never-together pairs must not surface when thresholds are 0 —
        # the sparse fallback only ever sees real pairs)
        ok = ((cn > 0) & (supp >= min_support) & (conf >= min_confidence)
              & (lift >= min_lift))
        if not ok.any():
            continue
        rank = np.where(ok, lift if score == "lift" else conf, -np.inf)
        # deterministic order: score desc, item id asc (ties at the top-k
        # boundary must resolve identically to the sparse fallback)
        top = np.lexsort((ids, -rank))[:k]
        top = top[rank[top] > -np.inf]
        cond_list.append(i)
        rows_out.append((top, rank[top], supp[top], conf[top], lift[top]))

    cond_rows = np.asarray(cond_list, np.int32)
    cons = np.full((len(cond_rows), k), -1, np.int32)
    sc = np.zeros((len(cond_rows), k), np.float32)
    s_out = np.zeros((len(cond_rows), k), np.float32)
    c_out = np.zeros((len(cond_rows), k), np.float32)
    l_out = np.zeros((len(cond_rows), k), np.float32)
    for out_i, (top, r_v, s_v, c_v, l_v) in enumerate(rows_out):
        cons[out_i, : len(top)] = top
        sc[out_i, : len(top)] = r_v
        s_out[out_i, : len(top)] = s_v
        c_out[out_i, : len(top)] = c_v
        l_out[out_i, : len(top)] = l_v
    return BasketRules(cond_rows, cons, sc, s_out, c_out, l_out, n_baskets)


def _rules_from_sparse(sp: dict, n: int, n_items: int, min_support: float,
                       min_confidence: float, min_lift: float, top_k: int,
                       score: str) -> BasketRules:
    support = sp["support"]
    per_cond: dict = {}
    for (a, b), cnt in sp["pairs"].items():
        for i, j in ((a, b), (b, a)):
            s = cnt / n
            conf = cnt / support[i] if support[i] else 0.0
            lift = (cnt * n / (support[i] * support[j])
                    if support[i] and support[j] else 0.0)
            if s >= min_support and conf >= min_confidence and lift >= min_lift:
                per_cond.setdefault(i, []).append(
                    (lift if score == "lift" else conf, j, s, conf, lift))
    cond_rows = np.asarray(sorted(per_cond), np.int32)
    k = top_k
    cons = np.full((len(cond_rows), k), -1, np.int32)
    sc = np.zeros((len(cond_rows), k), np.float32)
    s_out = np.zeros((len(cond_rows), k), np.float32)
    c_out = np.zeros((len(cond_rows), k), np.float32)
    l_out = np.zeros((len(cond_rows), k), np.float32)
    for out_i, i in enumerate(cond_rows):
        # same deterministic order as the dense path: score desc, id asc
        entries = sorted(per_cond[int(i)],
                         key=lambda e: (-e[0], e[1]))[:k]
        for e_i, (rank_v, j, s, conf, lift) in enumerate(entries):
            cons[out_i, e_i] = j
            sc[out_i, e_i] = rank_v
            s_out[out_i, e_i] = s
            c_out[out_i, e_i] = conf
            l_out[out_i, e_i] = lift
    return BasketRules(cond_rows, cons, sc, s_out, c_out, l_out, n)


def sessionize(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    times: np.ndarray,
    window_s: float,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Events → baskets: a user's purchases closer than `window_s` apart
    share a basket (the upstream template's basketWindow [U]). Returns
    (basket_idx, item_idx, n_baskets), vectorized numpy."""
    if len(user_idx) == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32), 0)
    order = np.lexsort((np.asarray(times), np.asarray(user_idx)))
    u = np.asarray(user_idx)[order]
    i = np.asarray(item_idx)[order]
    t = np.asarray(times, np.float64)[order]
    new_user = np.concatenate(([True], u[1:] != u[:-1]))
    gap = np.concatenate(([True], (t[1:] - t[:-1]) > window_s))
    new_basket = new_user | gap
    basket = np.cumsum(new_basket) - 1
    return basket.astype(np.int32), i.astype(np.int32), int(basket[-1]) + 1
