"""The analysis engine: module loading, the rule registry, findings,
inline suppressions, and the baseline workflow.

Design:

- A :class:`Project` lazily parses every ``*.py`` under its scan roots
  once (AST + source + per-line suppression map) and caches the result;
  every rule shares the cache, so a full lint is one parse pass.
- A rule is a function ``(project) -> iterable[Finding]`` registered
  with the :func:`rule` decorator. Rules never import the code they
  scan.
- Findings carry ``file:line``, severity, rule id, a stable ``symbol``
  anchor and a fix hint. The baseline is keyed on
  ``rule:file:symbol-or-line`` so grandfathered findings survive
  unrelated line drift.
- ``# pio-lint: disable=<rule>[,<rule>...]`` on (or standalone
  immediately above) the flagged line suppresses it; suppressions are
  for reviewed false positives and should carry a justification
  comment. ``conf/analysis-baseline.json`` grandfathers pre-existing
  findings so CI fails only on regressions; every entry must carry a
  ``reason``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(r"#\s*pio-lint:\s*disable=([\w\-,\s]+)")

DEFAULT_SUBDIRS = ("predictionio_tpu",)
DEFAULT_BASELINE = os.path.join("conf", "analysis-baseline.json")


def default_root() -> str:
    """The repo root (two levels above this file's package dir)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


# -- findings ---------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    file: str          # path relative to the project root, '/'-separated
    line: int          # 1-based; 0 for whole-project findings
    message: str
    severity: str = "error"     # "error" | "warning"
    symbol: str = ""            # stable anchor (function/attr name)
    hint: str = ""              # how to fix it

    @property
    def key(self) -> str:
        """Baseline key — stable across unrelated line drift."""
        anchor = self.symbol or str(self.line)
        return f"{self.rule}:{self.file}:{anchor}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "severity": self.severity, "message": self.message,
                "symbol": self.symbol, "hint": self.hint, "key": self.key}

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else (self.file or "-")
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.hint:
            out += f" (fix: {self.hint})"
        return out


# -- modules ----------------------------------------------------------------


class Module:
    """One parsed source file: AST, function index, suppression map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(source, filename=rel)
        except SyntaxError as e:
            self.tree = None
            self.error = str(e)
        self._suppressions: Optional[Dict[int, set]] = None

    @property
    def suppressions(self) -> Dict[int, set]:
        """line → set of disabled rule ids. A trailing comment applies
        to its own line; a standalone comment line applies to itself
        AND the following line."""
        if self._suppressions is None:
            supp: Dict[int, set] = {}
            lines = self.source.splitlines()
            try:
                tokens = tokenize.generate_tokens(
                    io.StringIO(self.source).readline)
                for tok in tokens:
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _SUPPRESS_RE.search(tok.string)
                    if not m:
                        continue
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    row = tok.start[0]
                    supp.setdefault(row, set()).update(rules)
                    before = lines[row - 1][:tok.start[1]]
                    if not before.strip():     # standalone comment line
                        supp.setdefault(row + 1, set()).update(rules)
            except tokenize.TokenizeError:
                pass
            self._suppressions = supp
        return self._suppressions

    def suppressed(self, rule_id: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule_id in rules or "all" in rules)


class Project:
    """A set of parsed modules under `root`, plus text access to the
    rest of the tree (tests/, docs/, tools/) for coverage rules."""

    def __init__(self, root: str,
                 subdirs: Optional[Sequence[str]] = None):
        self.root = os.path.abspath(root)
        self.subdirs = tuple(subdirs) if subdirs else None
        self._modules: Optional[List[Module]] = None

    def _scan_roots(self) -> List[str]:
        if not self.subdirs:
            return [self.root]
        return [os.path.join(self.root, d) for d in self.subdirs]

    def modules(self) -> List[Module]:
        if self._modules is None:
            mods: List[Module] = []
            for scan_root in self._scan_roots():
                for dirpath, dirnames, filenames in os.walk(scan_root):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d not in ("__pycache__", ".git"))
                    for fn in sorted(filenames):
                        if not fn.endswith(".py"):
                            continue
                        path = os.path.join(dirpath, fn)
                        rel = os.path.relpath(path, self.root)
                        try:
                            with open(path, encoding="utf-8") as f:
                                mods.append(Module(path, rel, f.read()))
                        except OSError:
                            continue
            self._modules = mods
        return self._modules

    def module(self, rel_suffix: str) -> Optional[Module]:
        """The module whose rel path ends with `rel_suffix`."""
        suffix = rel_suffix.replace(os.sep, "/")
        for m in self.modules():
            if m.rel == suffix or m.rel.endswith("/" + suffix):
                return m
        return None

    def text_files(self, subdir: str,
                   suffixes: Tuple[str, ...]) -> List[Tuple[str, str]]:
        """[(rel, text)] for files under root/subdir with a suffix —
        reference corpora (tests, docs, tools) outside the scan roots."""
        base = os.path.join(self.root, subdir)
        out: List[Tuple[str, str]] = []
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__", ".git"))
            for fn in sorted(filenames):
                if not fn.endswith(suffixes):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, encoding="utf-8") as f:
                        out.append((os.path.relpath(path, self.root)
                                    .replace(os.sep, "/"), f.read()))
                except OSError:
                    continue
        return out


# -- rule registry ----------------------------------------------------------


@dataclasses.dataclass
class Rule:
    id: str
    doc: str
    fn: Callable[[Project], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}


def rule(rule_id: str, doc: str):
    """Register a rule function ``(project) -> iterable[Finding]``."""

    def deco(fn):
        _RULES[rule_id] = Rule(rule_id, doc, fn)
        return fn

    return deco


def load_default_rules() -> None:
    """Import the rule packs (registration happens at import)."""
    from predictionio_tpu.analysis import (  # noqa: F401
        concurrency,
        coverage,
        eventloop,
        gates,
        labels,
        lockgraph,
        shapes,
    )


def all_rules() -> Dict[str, Rule]:
    load_default_rules()
    return dict(_RULES)


def run_rules(project: Project,
              rule_ids: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run rules over the project, drop inline-suppressed findings,
    return the rest sorted by (file, line, rule)."""
    rules = all_rules()
    if rule_ids is not None:
        unknown = [r for r in rule_ids if r not in rules]
        if unknown:
            raise KeyError(f"unknown rule(s): {unknown} "
                           f"(known: {sorted(rules)})")
        selected = [rules[r] for r in rule_ids]
    else:
        selected = [rules[r] for r in sorted(rules)]
    by_rel = {m.rel: m for m in project.modules()}
    out: List[Finding] = []
    for r in selected:
        for f in r.fn(project):
            mod = by_rel.get(f.file)
            if mod is not None and f.line and mod.suppressed(f.rule, f.line):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return out


# -- baseline ---------------------------------------------------------------


class BaselineError(ValueError):
    """Malformed baseline file (missing reason, bad shape)."""


def load_baseline(path: str) -> Dict[str, str]:
    """key → reason. Every entry must be a reviewed, commented one:
    a missing/empty ``reason`` is an error, not a default."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", data if isinstance(data, list) else [])
    out: Dict[str, str] = {}
    for e in entries:
        if not isinstance(e, dict) or not e.get("key"):
            raise BaselineError(f"baseline entry missing 'key': {e!r}")
        if not str(e.get("reason", "")).strip():
            raise BaselineError(
                f"baseline entry {e['key']!r} has no reason — baseline "
                f"entries must be reviewed and commented")
        out[e["key"]] = e["reason"]
    return out


def partition(findings: Sequence[Finding], baseline: Dict[str, str]
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, baselined, stale_baseline_keys)."""
    new: List[Finding] = []
    grandfathered: List[Finding] = []
    seen = set()
    for f in findings:
        if f.key in baseline:
            grandfathered.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - seen)
    return new, grandfathered, stale
