"""ServingPlane: admission control + micro-batching + graceful degradation.

This is the single object the HTTP layer talks to. Per request:

    result, degraded = plane.handle_query(query, headers)

which is admit → (batched or direct) dispatch → release, with the
degraded-mode hook tried when admission sheds. The HTTP handler maps the
two exceptions that can escape — ShedLoad → 429, DeadlineExceeded → 503,
both with Retry-After — and everything else stays the 400 it always was.

Degradation fires ONLY on saturation (ShedLoad): a cheap fallback answer
(e.g. the popularity model, which needs no per-user work) beats a 429
when the engine offers one. Deadline misses do NOT degrade — the client
declared the answer worthless after the deadline, so any answer, however
cheap, is wasted bytes.

Configuration resolves from PIO_SERVING_* environment variables
(`ServingConfig.from_env`) so the pre-fork worker pool — where each
worker builds its own PredictionServer in a fresh process — picks up one
consistent serving posture without plumbing flags through exec.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import time
from typing import Callable, List, Optional, Tuple

from predictionio_tpu.serving.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineExceeded,
    ShedLoad,
    deadline_from_headers,
)
from predictionio_tpu.serving.batcher import BatcherConfig, MicroBatcher
from predictionio_tpu.serving.result_cache import MISS, ResultCache, cache_from_env
from predictionio_tpu.telemetry import spans, tenant
from predictionio_tpu.telemetry.registry import REGISTRY
from predictionio_tpu.utils import faults

log = logging.getLogger(__name__)

# planes with no app binding still meter (under "-") but skip the
# contextvar set/reset on the hot path
_NO_TENANT = contextlib.nullcontext()

DEGRADED = REGISTRY.counter(
    "serving_degraded_total",
    "Predict requests answered by the degraded-mode fallback under shed")

_TRUTHY = {"1", "true", "yes", "on"}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("ignoring unparseable %s=%r", name, raw)
        return default


@dataclasses.dataclass
class ServingConfig:
    # micro-batching on/off; admission control is NOT optional — with
    # batching off, requests still admit/release around a direct dispatch
    batching: bool = True
    admission: AdmissionConfig = dataclasses.field(default_factory=AdmissionConfig)
    batcher: BatcherConfig = dataclasses.field(default_factory=BatcherConfig)

    @classmethod
    def from_env(cls) -> "ServingConfig":
        """Resolve from PIO_SERVING_* (every knob optional):

        PIO_SERVING_BATCHING=0|1, PIO_SERVING_MAX_BATCH,
        PIO_SERVING_MAX_WAIT_MS, PIO_SERVING_MAX_QUEUE,
        PIO_SERVING_DEFAULT_DEADLINE_MS, PIO_SERVING_RETRY_AFTER_S."""
        cfg = cls()
        raw = os.environ.get("PIO_SERVING_BATCHING")
        if raw is not None:
            cfg.batching = raw.strip().lower() in _TRUTHY
        cfg.batcher.max_batch = int(
            _env_float("PIO_SERVING_MAX_BATCH", cfg.batcher.max_batch))
        cfg.batcher.max_wait_ms = _env_float(
            "PIO_SERVING_MAX_WAIT_MS", cfg.batcher.max_wait_ms)
        cfg.admission.max_queue = int(
            _env_float("PIO_SERVING_MAX_QUEUE", cfg.admission.max_queue))
        cfg.admission.default_deadline_ms = _env_float(
            "PIO_SERVING_DEFAULT_DEADLINE_MS",
            cfg.admission.default_deadline_ms)
        cfg.admission.retry_after_s = _env_float(
            "PIO_SERVING_RETRY_AFTER_S", cfg.admission.retry_after_s)
        return cfg


class ServingPlane:
    """Admission-gated (optionally batched) dispatch for one engine
    instance.

    `dispatch_fn(queries: list) -> list[results]` — the batched predict
    path (Engine.predict_batch bound to the served state).
    `degraded_fn(query) -> result` — optional cheap fallback used when
    admission sheds; raise/return None to decline.
    `variant` — the engine variant this plane serves; scopes the result
    cache's keys so answers never leak across variants when several
    planes live behind one route (experiment/router.py).
    `app` — the app id this engine/variant is bound to (the serving-side
    tenant root, resolved once at server construction); every query is
    handled under this tenant binding so downstream device dispatches
    attribute to it, and metered as tenant_requests_total by outcome
    (cache_hit vs ok gives the per-tenant result-cache slice hit rate)."""

    def __init__(self,
                 dispatch_fn: Callable[[List], List],
                 degraded_fn: Optional[Callable] = None,
                 config: Optional[ServingConfig] = None,
                 name: str = "predictionserver",
                 result_cache: Optional[ResultCache] = None,
                 variant: str = "",
                 app: str = ""):
        self.config = config or ServingConfig()
        self.variant = variant
        self.app = str(app) if app else ""

        # Optional per-user result cache (OFF unless PIO_HTTP_RESULT_CACHE
        # opts in, or one is passed explicitly). Kept read-your-writes by
        # the ingest write plane: every durable commit publishes its
        # entity ids on the invalidation bus and this cache drops that
        # user's entries (serving/result_cache.py has the full posture).
        self.result_cache = (result_cache if result_cache is not None
                             else cache_from_env())
        if self.result_cache is not None:
            from predictionio_tpu.ingest.invalidation import BUS

            cache, own_variant = self.result_cache, variant

            def _invalidate(entity_ids, msg_variant=None):
                # a variant-scoped commit (a $reward credit) can only
                # stale this plane's entries if it names this variant
                if msg_variant is None or msg_variant == own_variant:
                    cache.invalidate_entities(entity_ids,
                                              variant=msg_variant)

            self._invalidate = _invalidate
            BUS.subscribe(self._invalidate)

        # `serving.pre_dispatch` fault site: after admission, before the
        # model runs — the chaos gate arms delay:/error modes here to turn
        # a live worker slow or erroring without killing it. One site in
        # the plane covers every serving surface (batched and direct).
        # The tenant re-bind matters on the batched path: the batcher's
        # worker thread never saw the request thread's contextvar, and a
        # plane's batcher only ever carries this plane's (single) app.
        def _faultable_dispatch(queries: List) -> List:
            faults.inject("serving.pre_dispatch")
            if self.app:
                with tenant.bound(self.app, "variant"):
                    return dispatch_fn(queries)
            return dispatch_fn(queries)

        self.dispatch_fn = _faultable_dispatch
        self.degraded_fn = degraded_fn
        self.admission = AdmissionController(self.config.admission)
        self.batcher: Optional[MicroBatcher] = None
        if self.config.batching:
            # the admitted count is the batcher's fill signal: a forming
            # batch stops waiting the moment it holds every admitted
            # request (see batcher module docstring)
            self.batcher = MicroBatcher(
                self.dispatch_fn, config=self.config.batcher, name=name,
                pending_fn=lambda: self.admission.admitted)

    def handle_query(self, query, headers=None) -> Tuple[object, bool]:
        """Admit, dispatch, release. Returns (result, degraded_flag).

        Raises ShedLoad (→ 429) when saturated and no degraded answer
        exists; DeadlineExceeded (→ 503) when the request's deadline
        expired before a result was produced.

        Runs under the plane's tenant binding: the queue/dispatch spans,
        the device clock's dispatch accounting, and the per-request
        metering below all attribute to `self.app`."""
        t0 = time.monotonic()
        with tenant.bound(self.app, "variant") if self.app else _NO_TENANT:
            try:
                result, degraded, outcome = self._handle_query(query, headers)
            except ShedLoad:
                self._meter("shed", 429, t0)
                raise
            except DeadlineExceeded:
                self._meter("deadline", 503, t0)
                raise
            except Exception:
                self._meter("error", 500, t0)
                raise
        self._meter(outcome, 200, t0)
        return result, degraded

    def _meter(self, outcome: str, status: int, t0: float) -> None:
        tenant.record_request("predictionserver", outcome,
                              app=self.app or None, status=status,
                              duration_s=time.monotonic() - t0)

    def _handle_query(self, query, headers) -> Tuple[object, bool, str]:
        cache = self.result_cache
        if cache is not None:
            with spans.span("serving.result_cache"):
                hit = cache.get(query, self.variant)
            if hit is not MISS:
                return hit, False, "cache_hit"
        deadline = deadline_from_headers(headers, self.config.admission)
        try:
            with spans.span("serving.admission"):
                self.admission.admit(deadline)
        except ShedLoad:
            degraded = self._try_degraded(query)
            if degraded is not None:
                return degraded, True, "degraded"
            raise
        try:
            if self.batcher is not None:
                result = self.batcher.submit(query, deadline)
            else:
                with spans.span("serving.dispatch"):
                    result = self.dispatch_fn([query])[0]
        finally:
            self.admission.release()
        if cache is not None:
            # full-quality results only: a degraded answer must never
            # outlive the saturation that produced it
            cache.put(query, result, self.variant)
        return result, False, "ok"

    def _try_degraded(self, query):
        if self.degraded_fn is None:
            return None
        try:
            result = self.degraded_fn(query)
        except Exception:  # noqa: BLE001 — degraded path must never mask the shed
            log.exception("degraded-mode fallback failed; shedding instead")
            return None
        if result is not None:
            DEGRADED.inc()
        return result

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()
        if self.result_cache is not None:
            from predictionio_tpu.ingest.invalidation import BUS

            BUS.unsubscribe(self._invalidate)
            self.result_cache.clear()
