"""Cached JSON codecs for the HTTP hot path.

`json.dumps(obj, separators=...)` constructs a fresh JSONEncoder on
every call, and `json.loads(b"...")` runs byte-order-mark detection
before it ever reaches the C scanner — both measurable taxes at the
per-request rate the serving/ingest planes run at (ROADMAP item 3: the
r05 ladder went flat on shared-core CPU, not on the model). This module
binds one compact C encoder and one C decoder at import and exposes:

- `dumps_bytes(obj)` / `loads(data)` — the cached generic codec pair.
  Every hot-path handler must use these instead of bare `json.dumps` /
  `json.loads` (enforced by `quality.py --hotpath-gate`).
- envelope encoders — preserialized byte fragments for the fixed parts
  of high-volume responses (`{"eventId": ...}` on event ingest,
  `{"itemScores": [...]}` on predictions), so the fixed bytes are never
  re-encoded. Fragment paths count as encoder-cache hits; anything that
  falls back to the generic encoder counts as a miss, so the hit ratio
  is observable (`http_encoder_cache_*` on /metrics).
- `message_body(status, message)` — a bounded cache of fully rendered
  `{"message": ...}` bodies for the small vocabulary of shed/error
  replies (429/503/404/health), interned so repeated sheds cost a dict
  lookup, not an encode.

Compact separators change response *whitespace* relative to the old
`json.dumps` default — JSON-insignificant, and both transports (event
loop and threaded fallback) encode through here, so A/B parity stays
bitwise.
"""

from __future__ import annotations

import json
import math
import re
from typing import Optional

from predictionio_tpu.telemetry.registry import REGISTRY

ENCODER_HITS = REGISTRY.counter(
    "http_encoder_cache_hits_total",
    "Hot-path responses encoded via a preserialized envelope fragment "
    "or an interned static body")
ENCODER_MISSES = REGISTRY.counter(
    "http_encoder_cache_misses_total",
    "Hot-path responses that fell back to the generic cached encoder")

_HITS = ENCODER_HITS.labels()
_MISSES = ENCODER_MISSES.labels()

# One compact C encoder / one C decoder for the whole process, bound once.
_ENCODER = json.JSONEncoder(separators=(",", ":"))
_encode = _ENCODER.encode
_DECODER = json.JSONDecoder()
_decode = _DECODER.decode


def dumps_bytes(obj) -> bytes:
    """Compact-encode to UTF-8 bytes via the process-bound C encoder."""
    return _encode(obj).encode("utf-8")


def dumps(obj) -> str:
    return _encode(obj)


def loads(data):
    """Decode JSON from bytes or str, skipping json.loads' per-call
    BOM/encoding detection for the overwhelmingly common UTF-8 case.
    Raises json.JSONDecodeError / UnicodeDecodeError (a ValueError) on
    bad input — same contract the route handlers already map to 400."""
    if isinstance(data, (bytes, bytearray)):
        data = data.decode("utf-8")
    return _decode(data)


# -- envelope fragments ------------------------------------------------------

# JSON string characters that need no escaping: everything printable-ASCII
# except the two JSON-special characters. Event ids are uuid hex and item
# ids are catalog keys, so this matches essentially always; anything else
# falls back to the generic encoder (correctness over the fast path).
_PLAIN_STR = re.compile(r'^[ !#-\[\]-~]*$')

_EVENT_ID_PRE = b'{"eventId":"'
_EVENT_ID_POST = b'"}'


def event_id_response(event_id: str) -> bytes:
    """`{"eventId": "..."}` — the 201 body of every single-event ingest."""
    if _PLAIN_STR.match(event_id):
        _HITS.inc()
        return _EVENT_ID_PRE + event_id.encode("ascii") + _EVENT_ID_POST
    _MISSES.inc()
    return dumps_bytes({"eventId": event_id})


_ITEM_PRE = '{"item":"'
_ITEM_MID = '","score":'
_SCORES_PRE = b'{"itemScores":['
_SCORES_POST = b']}'
_EMPTY_SCORES = b'{"itemScores":[]}'


def _fragment_item_scores(scores: list) -> Optional[bytes]:
    """Fast path for the dominant prediction shape
    `{"itemScores": [{"item": str, "score": float}, ...]}`. Floats are
    rendered with `repr`, which is exactly what the C encoder emits for
    finite floats; any shape surprise returns None and the caller falls
    back to the generic encoder."""
    parts = []
    for s in scores:
        if type(s) is not dict or len(s) != 2:
            return None
        item = s.get("item")
        score = s.get("score")
        if type(item) is not str or not _PLAIN_STR.match(item):
            return None
        if type(score) is float:
            if not math.isfinite(score):
                return None
            score_txt = repr(score)
        elif type(score) is int and type(score) is not bool:
            score_txt = str(score)
        else:
            return None
        parts.append(_ITEM_PRE + item + _ITEM_MID + score_txt + "}")
    return _SCORES_PRE + ",".join(parts).encode("ascii") + _SCORES_POST


def prediction_response(result) -> bytes:
    """Encode one prediction result, fragment-assembling the fixed
    envelope when the result is the standard item-scores shape."""
    if type(result) is dict and len(result) == 1:
        scores = result.get("itemScores")
        if type(scores) is list:
            if not scores:
                _HITS.inc()
                return _EMPTY_SCORES
            body = _fragment_item_scores(scores)
            if body is not None:
                _HITS.inc()
                return body
    _MISSES.inc()
    return dumps_bytes(result)


# -- interned small bodies ---------------------------------------------------

# {"message": ...} replies (shed, not-found, health) repeat a small
# vocabulary of strings; intern the rendered bytes. Bounded: admission
# messages embed the in-flight count, so the key space is a few hundred
# at most, but cap it anyway so a hostile message stream cannot grow it.
_MESSAGE_CACHE: dict = {}
_MESSAGE_CACHE_MAX = 512


def message_body(message: str) -> bytes:
    body = _MESSAGE_CACHE.get(message)
    if body is not None:
        _HITS.inc()
        return body
    body = dumps_bytes({"message": message})
    if len(_MESSAGE_CACHE) < _MESSAGE_CACHE_MAX:
        _MESSAGE_CACHE[message] = body
    _MISSES.inc()
    return body
