"""Grid-batched eval (ops/als_grid + Engine.eval_grid): N hyperparameter
points as one device program, numerically matching sequential trains —
SURVEY.md §2.6 strategy 4's TPU-native form (VERDICT r3 #1)."""

import dataclasses

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, als_train
from predictionio_tpu.ops.als_grid import als_train_grid, grid_compatible


def coo(n=20000, n_u=300, n_i=200, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_u, n).astype(np.int32),
            rng.integers(0, n_i, n).astype(np.int32),
            rng.uniform(1, 5, n).astype(np.float32), n_u, n_i)


def rel_err(a, b):
    return np.abs(a - b).max() / max(np.abs(b).max(), 1e-9)


class TestGridCompatible:
    BASE = ALSConfig(rank=8, iterations=3, reg=0.1)

    def test_variable_fields_ok(self):
        cfgs = [dataclasses.replace(self.BASE, reg=r, alpha=a, seed=s)
                for r, a, s in ((0.01, 1.0, 0), (0.1, 2.0, 1))]
        assert grid_compatible(cfgs) is None

    def test_mixed_iterations_ok(self):
        """r5: an iterations sweep — the cheapest and most-used grid
        axis — batches via the traced per-cell horizon instead of
        degrading to sequential trains."""
        cfgs = [dataclasses.replace(self.BASE, iterations=n)
                for n in (2, 5, 3)]
        assert grid_compatible(cfgs) is None

    @pytest.mark.parametrize("field,value", [
        ("rank", 16), ("implicit", True), ("split_cap", 64),
        ("cap_growth", 2.0), ("compute_dtype", "bfloat16"),
        ("weighted_reg", False),
    ])
    def test_static_mismatch_rejected(self, field, value):
        cfgs = [self.BASE, dataclasses.replace(self.BASE, **{field: value})]
        reason = grid_compatible(cfgs)
        assert reason is not None and field in reason

    def test_cg_rejected(self):
        cfgs = [dataclasses.replace(self.BASE, solver="cg")] * 2
        assert "cg" in grid_compatible(cfgs)

    def test_empty_rejected(self):
        assert grid_compatible([]) is not None

    def test_grid_groups_partitions_mixed_grid(self):
        from predictionio_tpu.ops.als_grid import grid_groups

        cfgs = [dataclasses.replace(self.BASE, rank=r, reg=lam)
                for r in (8, 16) for lam in (0.01, 0.1)]
        cfgs.append(dataclasses.replace(self.BASE, solver="cg"))
        groups = grid_groups(cfgs)
        assert sorted(map(sorted, groups)) == [[0, 1], [2, 3], [4]]


class TestGridMatchesSequential:
    def test_explicit_with_hot_row_segments(self):
        """λ grid over data with rows past split_cap: the segment
        scatter-add/combine path must match sequential too."""
        u, i, v, n_u, n_i = coo()
        base = ALSConfig(rank=16, iterations=3, seed=7, split_cap=64)
        cfgs = [dataclasses.replace(base, reg=r) for r in (0.01, 0.1, 1.0)]
        grid = als_train_grid(u, i, v, n_u, n_i, cfgs, compute_rmse=True)
        assert len(grid) == 3
        for cfg, gr in zip(cfgs, grid):
            seq = als_train(u, i, v, n_u, n_i, cfg, compute_rmse=True)
            assert rel_err(gr.user_factors, seq.user_factors) < 1e-4
            assert rel_err(gr.item_factors, seq.item_factors) < 1e-4
            assert gr.rmse_history == pytest.approx(seq.rmse_history,
                                                    rel=1e-4)
        # different λ must actually produce different factors (the grid
        # axis isn't broadcasting one solution)
        assert rel_err(grid[0].user_factors, grid[2].user_factors) > 1e-3

    def test_implicit_alpha_and_seed_grid(self):
        u, i, v, n_u, n_i = coo(n=8000, n_u=150, n_i=100, seed=1)
        base = ALSConfig(rank=12, iterations=3, implicit=True, reg=0.05,
                         split_cap=0)
        cfgs = [dataclasses.replace(base, alpha=a, seed=s)
                for a, s in ((1.0, 0), (10.0, 1), (40.0, 2))]
        grid = als_train_grid(u, i, v, n_u, n_i, cfgs)
        for cfg, gr in zip(cfgs, grid):
            seq = als_train(u, i, v, n_u, n_i, cfg)
            assert rel_err(gr.user_factors, seq.user_factors) < 1e-4

    def test_mixed_iterations_match_sequential_per_cell(self):
        """r4-weak-#3 closed: cells with DIFFERENT iteration counts in
        one grid program — each must equal its own sequential train
        (the traced horizon freezes a finished cell's factors), and the
        rmse history must be each cell's own length."""
        u, i, v, n_u, n_i = coo()
        base = ALSConfig(rank=8, iterations=0, seed=5, split_cap=64)
        cfgs = [dataclasses.replace(base, iterations=n, reg=r)
                for n, r in ((2, 0.1), (5, 0.1), (3, 0.02))]
        grid = als_train_grid(u, i, v, n_u, n_i, cfgs, compute_rmse=True)
        for cfg, gr in zip(cfgs, grid):
            seq = als_train(u, i, v, n_u, n_i, cfg, compute_rmse=True)
            assert rel_err(gr.user_factors, seq.user_factors) < 1e-4
            assert rel_err(gr.item_factors, seq.item_factors) < 1e-4
            assert len(gr.rmse_history) == cfg.iterations
            assert gr.rmse_history == pytest.approx(seq.rmse_history,
                                                    rel=1e-4)
            assert len(gr.epoch_times) == cfg.iterations
        # the 2-iter and 5-iter cells share λ: the horizon must make
        # them genuinely different, not clones of the longest run
        assert rel_err(grid[0].user_factors, grid[1].user_factors) > 1e-3

    def test_incompatible_grid_raises(self):
        u, i, v, n_u, n_i = coo(n=500, n_u=30, n_i=20)
        cfgs = [ALSConfig(rank=8), ALSConfig(rank=16)]
        with pytest.raises(ValueError, match="rank"):
            als_train_grid(u, i, v, n_u, n_i, cfgs)

    def test_sharded_data_mesh_matches_single_device(self):
        """The grid under the 8-device SPMD mesh (bucket rows sharded over
        `data`) matches the single-device result."""
        import jax
        from jax.sharding import Mesh

        from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        u, i, v, n_u, n_i = coo(n=6000, n_u=120, n_i=80, seed=2)
        base = ALSConfig(rank=8, iterations=2, seed=3, split_cap=0)
        cfgs = [dataclasses.replace(base, reg=r) for r in (0.05, 0.5)]
        devs = np.array(jax.devices()).reshape(-1, 1)
        mesh = Mesh(devs, (DATA_AXIS, MODEL_AXIS))
        single = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                      (DATA_AXIS, MODEL_AXIS))
        grid_m = als_train_grid(u, i, v, n_u, n_i, cfgs, mesh=mesh)
        grid_1 = als_train_grid(u, i, v, n_u, n_i, cfgs, mesh=single)
        for gm, g1 in zip(grid_m, grid_1):
            assert rel_err(gm.user_factors, g1.user_factors) < 1e-4

    def test_model_sharded_mesh_rejected(self):
        import jax
        from jax.sharding import Mesh

        from predictionio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        u, i, v, n_u, n_i = coo(n=500, n_u=30, n_i=20)
        devs = np.array(jax.devices()).reshape(-1, 2)
        mesh = Mesh(devs, (DATA_AXIS, MODEL_AXIS))
        with pytest.raises(ValueError, match="model"):
            als_train_grid(u, i, v, n_u, n_i, [ALSConfig(rank=8)] * 2,
                           mesh=mesh)


class TestEvalGridIntegration:
    """MetricEvaluator → Engine.eval_grid → ALSAlgorithm.train_grid."""

    def _setup(self, memory_storage, lambdas=(0.01, 0.05, 0.5)):
        from predictionio_tpu.controller import WorkflowContext
        from predictionio_tpu.workflow.workflow_utils import (
            EngineVariant, extract_engine_params, get_engine,
        )
        from tests.test_recommendation_template import (
            FACTORY, ingest_ratings,
        )

        ingest_ratings(memory_storage, n_users=16, n_items=10)
        engine = get_engine(FACTORY)
        eps = []
        for lam in lambdas:
            variant = EngineVariant.from_dict({
                "id": "rec-eval-grid",
                "engineFactory": FACTORY,
                "datasource": {"params": {"appName": "RecApp", "evalK": 3}},
                "algorithms": [{"name": "als", "params": {
                    "rank": 4, "numIterations": 6, "lambda": lam,
                    "seed": 1}}],
            })
            eps.append(extract_engine_params(engine, variant))
        ctx = WorkflowContext(storage=memory_storage, seed=0)
        return engine, eps, ctx

    def _evaluation(self, engine):
        from predictionio_tpu.controller import OptionAverageMetric
        from predictionio_tpu.controller.evaluation import Evaluation
        from predictionio_tpu.ops.ranking import average_precision_at_k

        class MAPat10(OptionAverageMetric):
            def calculate(self, q, p, a):
                predicted = np.asarray(
                    [s["item"] for s in p["itemScores"]], dtype=object)
                return average_precision_at_k(predicted, set(a["items"]), 10)

        class RecEval(Evaluation):
            pass

        RecEval.engine = engine
        RecEval.metric = MAPat10()
        return RecEval()

    def test_grid_scores_match_sequential(self, memory_storage, monkeypatch):
        """The whole point: MetricEvaluator over a λ grid produces the
        same per-point scores whether the grid path or the sequential
        reference loop runs."""
        from predictionio_tpu.controller.engine import Engine
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.ops import als_grid

        engine, eps, ctx = self._setup(memory_storage)

        calls = {"grid": 0}
        real = als_grid.als_train_grid

        def spy(*a, **k):
            calls["grid"] += 1
            return real(*a, **k)

        monkeypatch.setattr(als_grid, "als_train_grid", spy)
        grid_result = MetricEvaluator.evaluate(ctx, self._evaluation(engine),
                                               eps)
        assert calls["grid"] == 3  # once per fold, not per (fold × cell)

        monkeypatch.setattr(Engine, "eval_grid",
                            lambda self, ctx, eps: None)
        seq_result = MetricEvaluator.evaluate(ctx, self._evaluation(engine),
                                              eps)
        for g, s in zip(grid_result.all_results, seq_result.all_results):
            assert g.scores["MAPat10"] == pytest.approx(
                s.scores["MAPat10"], rel=1e-4, abs=1e-6)
        assert (grid_result.all_results.index(grid_result.best)
                == seq_result.all_results.index(seq_result.best))

    def test_unbatchable_grid_still_shares_folds(self, memory_storage,
                                                 monkeypatch):
        """Grid cells with differing rank: train_grid declines, eval_grid
        still evaluates them (sequential trains, shared fold read)."""
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.ops import als_grid

        engine, eps, ctx = self._setup(memory_storage, lambdas=(0.01, 0.05))
        eps[1].algorithm_params_list[0][1].rank = 6  # break batchability

        monkeypatch.setattr(
            als_grid, "als_train_grid",
            lambda *a, **k: pytest.fail("train_grid must decline"))
        result = MetricEvaluator.evaluate(ctx, self._evaluation(engine), eps)
        assert len(result.all_results) == 2
        for r in result.all_results:
            assert 0.0 <= r.scores["MAPat10"] <= 1.0

    def test_mixed_rank_lambda_grid_batches_per_rank(self, memory_storage,
                                                     monkeypatch):
        """The stock template shape (rank×λ grid): one grid program per
        rank group, not one per cell."""
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.ops import als_grid

        engine, eps, ctx = self._setup(memory_storage,
                                       lambdas=(0.01, 0.05, 0.01, 0.05))
        for ep in eps[2:]:
            ep.algorithm_params_list[0][1].rank = 6

        grid_sizes = []
        real = als_grid.als_train_grid

        def spy(*a, **k):
            grid_sizes.append(len(k.get("cfgs") or a[5]))
            return real(*a, **k)

        monkeypatch.setattr(als_grid, "als_train_grid", spy)
        result = MetricEvaluator.evaluate(ctx, self._evaluation(engine), eps)
        # 3 folds × 2 rank groups, each batching its 2 λ cells
        assert grid_sizes == [2] * 6
        assert len(result.all_results) == 4

    def test_check_asserts_declines_grid(self, memory_storage, monkeypatch):
        """--check-asserts must run the checked sequential trains, not the
        (checkify-less) grid program."""
        from predictionio_tpu.ops import als_grid
        from predictionio_tpu.utils import checks

        engine, eps, ctx = self._setup(memory_storage, lambdas=(0.01, 0.05))
        monkeypatch.setattr(checks, "enabled", lambda: True)
        monkeypatch.setattr(
            als_grid, "als_train_grid",
            lambda *a, **k: pytest.fail("grid must decline under checks"))
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithm,
        )

        _, prep, algos, _ = engine.components(eps[0])
        instances = [engine.components(ep)[2][0][1] for ep in eps]
        td = engine.components(eps[0])[0].read_training(ctx)
        pd = prep.prepare(ctx, td)
        assert ALSAlgorithm.train_grid(ctx, pd, instances) is None

    def test_device_model_similar_products_and_single_query(self):
        """Device-resident grid-eval models must survive every ALSModel
        read path: batch, single-query, and the in-place-mutating
        similar_products."""
        import jax.numpy as jnp

        from predictionio_tpu.data.bimap import BiMap
        from predictionio_tpu.models.als_model import ALSModel, SeenItems

        rng = np.random.default_rng(0)
        uf = rng.normal(size=(6, 4)).astype(np.float32)
        vf = rng.normal(size=(5, 4)).astype(np.float32)
        host = ALSModel(
            user_factors=uf, item_factors=vf,
            user_ids=BiMap.string_int([f"u{i}" for i in range(6)]),
            item_ids=BiMap.string_int([f"i{i}" for i in range(5)]),
            seen=SeenItems(np.zeros(1, np.int32), np.zeros(1, np.int32), 6),
        )
        dev = ALSModel(
            user_factors=jnp.asarray(uf), item_factors=jnp.asarray(vf),
            user_ids=host.user_ids, item_ids=host.item_ids, seen=host.seen,
        )
        assert dev.similar_products(["i1"], 3) == pytest.approx(
            host.similar_products(["i1"], 3))
        for h, d in zip(host.recommend_products("u2", 3),
                        dev.recommend_products("u2", 3)):
            assert h[0] == d[0] and h[1] == pytest.approx(d[1], rel=1e-5)
        hb = host.recommend_products_batch([f"u{i}" for i in range(6)], 3)
        db = dev.recommend_products_batch([f"u{i}" for i in range(6)], 3)
        for hrow, drow in zip(hb, db):
            assert [i for i, _ in hrow] == [i for i, _ in drow]

    def test_heterogeneous_datasource_falls_back(self, memory_storage):
        """eval_grid returns None when the grid varies the data source
        params; the sequential path must still produce results."""
        from predictionio_tpu.controller.evaluation import MetricEvaluator

        engine, eps, ctx = self._setup(memory_storage, lambdas=(0.01, 0.05))
        eps[1].data_source_params.evalK = 2
        assert engine.eval_grid(ctx, eps) is None
        result = MetricEvaluator.evaluate(ctx, self._evaluation(engine), eps)
        assert len(result.all_results) == 2
