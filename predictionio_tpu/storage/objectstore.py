"""S3-compatible object-store model-blob backend (the reference's S3/HDFS
remote model stores).

Parity with «storage/s3/.../S3Models.scala» and the HDFS models role
(SURVEY.md §2.2 'LocalFS / HDFS / S3 model stores' [U]): model blobs live
in a remote object store so every host in a multi-host deployment (train
writes on rank 0, serve reads anywhere) sees the same bytes without a
shared POSIX filesystem.

The client speaks the S3 REST subset the Models repository needs —
PUT/GET/DELETE object, path-style addressing — over plain http.client,
with optional AWS Signature V4 request signing, so it works against real
S3, MinIO, GCS interop, or the bundled emulation server
(`storage/objectstore_server.py`, this image has no external services).

Registry wiring (type "s3"):

    PIO_STORAGE_SOURCES_S3_TYPE=s3
    PIO_STORAGE_SOURCES_S3_PATH=s3://bucket/prefix?endpoint=http://host:9001
    # optional auth (SigV4): &access_key=AK&secret_key=SK&region=us-east-1

Like localfs, this source backs `models()` only; metadata/events belong in
a SQL source.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import logging
import threading
import urllib.parse
from typing import Optional

from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import Model

log = logging.getLogger(__name__)


# ---------------------------------------------------------------- SigV4 --


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(
    method: str,
    host: str,
    path: str,
    headers: dict,
    payload_sha256: str,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    service: str = "s3",
    now: Optional[datetime.datetime] = None,
) -> dict:
    """AWS Signature Version 4 for a path-style S3 request. Returns the
    headers to add (`x-amz-date`, `x-amz-content-sha256`, `Authorization`).
    Public spec (docs.aws.amazon.com/general/latest/gr/sigv4_signing.html);
    implemented from the spec, shared by the client and the emulation
    server's verifier so the signing path is tested end-to-end."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    all_headers = dict(headers)
    all_headers["host"] = host
    all_headers["x-amz-date"] = amz_date
    all_headers["x-amz-content-sha256"] = payload_sha256

    ci = all_headers_ci(all_headers)
    signed_names = sorted(ci)
    canonical_headers = "".join(
        f"{k}:{str(ci[k]).strip()}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method.upper(),
        path,  # callers pass the path AS SENT (already percent-encoded);
        # re-quoting here would double-encode and break real S3/MinIO
        "",  # canonical query (none used by this client)
        canonical_headers,
        signed_headers,
        payload_sha256,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256_hex(canonical_request.encode()),
    ])
    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha256,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"),
    }


def all_headers_ci(headers: dict) -> dict:
    """Lower-cased-key view of a header dict."""
    return {k.lower(): v for k, v in headers.items()}


# ---------------------------------------------------------------- client --


class ObjectStoreError(RuntimeError):
    def __init__(self, status: int, body: bytes, op: str, key: str):
        super().__init__(
            f"object store {op} {key!r} failed: HTTP {status} "
            f"{body[:200]!r}")
        self.status = status


class S3Client:
    """Minimal path-style S3 REST client over persistent http.client
    connections (one per thread; the serving path may fetch models from
    several request threads)."""

    def __init__(self, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1", timeout: float = 30.0):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise ValueError(f"bad object-store endpoint {endpoint!r}; "
                             "expected http(s)://host[:port]")
        self._scheme = u.scheme
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        # the signed Host must be byte-identical to what http.client sends:
        # it omits the scheme's default port (so an explicit :80/:443 must
        # not leak into the signature) and re-brackets IPv6 literals
        default_port = 443 if u.scheme == "https" else 80
        host = u.hostname or ""
        if ":" in host:  # IPv6 literal — http.client sends it bracketed
            host = f"[{host}]"
        self._host_header = (host if u.port in (None, default_port)
                             else f"{host}:{u.port}")
        self.bucket = bucket
        self._auth = (access_key, secret_key) if access_key else None
        self._region = region
        self._timeout = timeout
        self._local = threading.local()

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(self._host, self._port, timeout=self._timeout)
            self._local.conn = conn
        return conn

    def _request(self, method: str, key: str, body: bytes = b"") -> tuple:
        path = "/" + urllib.parse.quote(
            f"{self.bucket}/{key}".strip("/"), safe="/~")
        headers: dict = {"Content-Length": str(len(body))}
        payload_hash = _sha256_hex(body)
        if self._auth:
            headers.update(sign_v4(
                method, self._host_header, path, {}, payload_hash,
                self._auth[0], self._auth[1], self._region))
        else:
            headers["x-amz-content-sha256"] = payload_hash
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request(method, path, body, headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, data
            except (http.client.HTTPException, ConnectionError, OSError):
                # stale keep-alive or dropped transport: rebuild the
                # connection once. PUT/DELETE on an object store are
                # idempotent, so a blind retry is safe (unlike event POSTs)
                conn.close()
                self._local.conn = None
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def put_object(self, key: str, data: bytes) -> None:
        status, body = self._request("PUT", key, data)
        if status not in (200, 201):
            raise ObjectStoreError(status, body, "PUT", key)

    def get_object(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise ObjectStoreError(status, body, "GET", key)
        return body

    def delete_object(self, key: str) -> bool:
        status, body = self._request("DELETE", key)
        if status in (200, 204):
            return True
        if status == 404:
            return False
        raise ObjectStoreError(status, body, "DELETE", key)


# ---------------------------------------------------------------- models --


class S3Models(base.Models):
    """Model blobs as objects: `<prefix>/<model_id>.model`. Object-store
    PUTs are atomic (no torn reads of a half-uploaded object — the object
    appears only on completion), giving the same crash-safety the localfs
    backend gets from temp-file + os.replace."""

    def __init__(self, client: S3Client, prefix: str = ""):
        self._client = client
        self._prefix = prefix.strip("/")

    def _key(self, model_id: str) -> str:
        if (not model_id or any(c in model_id for c in "/\\\0?#%")
                or ".." in model_id):
            raise ValueError(f"Invalid model id {model_id!r}")
        name = f"{model_id}.model"
        return f"{self._prefix}/{name}" if self._prefix else name

    def insert(self, model: Model) -> None:
        self._client.put_object(self._key(model.id), bytes(model.models))

    def get(self, model_id: str) -> Optional[Model]:
        data = self._client.get_object(self._key(model_id))
        return None if data is None else Model(id=model_id, models=data)

    def delete(self, model_id: str) -> bool:
        return self._client.delete_object(self._key(model_id))


class S3Backend(base.StorageBackend):
    """Models-only storage source (type "s3").

    PATH syntax:
        s3://bucket[/prefix]?endpoint=http://host:port
            [&access_key=AK&secret_key=SK&region=us-east-1]
    """

    def __init__(self, path: str):
        u = urllib.parse.urlsplit(path)
        if u.scheme != "s3" or not u.netloc:
            raise ValueError(
                f"bad s3 source PATH {path!r}; expected "
                "s3://bucket[/prefix]?endpoint=http://host:port")
        opts = dict(urllib.parse.parse_qsl(u.query))
        endpoint = opts.pop("endpoint", "")
        if not endpoint:
            raise ValueError(
                f"s3 source PATH {path!r} needs ?endpoint=http://host:port "
                "(real AWS, MinIO, or the bundled objectstore server)")
        client = S3Client(
            endpoint, bucket=u.netloc,
            access_key=opts.pop("access_key", ""),
            secret_key=opts.pop("secret_key", ""),
            region=opts.pop("region", "us-east-1"))
        if opts:
            log.warning("s3 source: ignoring unknown option(s) %s",
                        ", ".join(sorted(opts)))
        self._models = S3Models(client, prefix=u.path)

    def _unsupported(self, repo: str):
        raise NotImplementedError(
            f"The s3 backend only provides model blobs; wire {repo} to a "
            "sqlite/postgres source (PIO_STORAGE_REPOSITORIES_*_SOURCE).")

    def apps(self):
        self._unsupported("apps")

    def access_keys(self):
        self._unsupported("access_keys")

    def channels(self):
        self._unsupported("channels")

    def engine_instances(self):
        self._unsupported("engine_instances")

    def evaluation_instances(self):
        self._unsupported("evaluation_instances")

    def models(self) -> S3Models:
        return self._models

    def events(self):
        self._unsupported("events")
