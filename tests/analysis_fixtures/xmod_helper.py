"""Middle hop of the cross-module blocking fixture — no blocking call
of its own, just the bridge from the route module to the db module."""

from xmod_db import fetch_rows


def load_report(table):
    rows = fetch_rows(table)
    return {"rows": rows}
