"""W2V SGNS roofline: where does 5.6M pairs/s sit vs the gather/scatter
op ceiling? (VERDICT r3 weak #3 — apply the ALS roofline methodology to
the Word2Vec step, closing SURVEY.md §2.5's "Pallas negative-sampling
kernel" mandate with either a kernel or a measured refutation.)

Per SGNS step at B pairs, N negatives, K dims the step MUST touch
B·(N+2) embedding rows twice — gather (read) and scatter-add (write);
that row traffic is irreducible for the algorithm (every sampled row's
value feeds the loss; every sampled row receives a gradient). So the
question "can a Pallas kernel beat the XLA step?" reduces to "does the
XLA step already run at the hardware's row-op rate?" — measured here by
timing stripped-down variants of the same scan:

  full        the real step (gathers + math + scatters)
  gather-only same gathers + math, gradients summed instead of scattered
  scatter-only constant rows scattered to the same indices, no gathers
  pure-gather a bare table[idx] sum, the op-rate ceiling probe
  sorted-gather same with per-step sorted indices (ALS measured ~20×
              from monotonic row ids in its fused gather+Gram pipeline
              — does a bare gather see any of that here?)

Run on the TPU: python benchmarks/w2v_roofline.py [--quick]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--batch", type=int, default=16_384)
    ap.add_argument("--negatives", type=int, default=5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    V, K, B, N = args.vocab, args.dim, args.batch, args.negatives
    steps = 30 if args.quick else 100
    reps = 2 if args.quick else 3
    rows_per_pair = N + 2

    key = jax.random.key(0)
    emb_in = jax.random.normal(key, (V, K), jnp.float32) * 0.01
    emb_out = jax.random.normal(key, (V, K), jnp.float32) * 0.01
    n_pairs = 1_000_000
    pairs = jax.random.randint(key, (n_pairs, 2), 0, V, jnp.int32)

    def sgns_math(c, pos, ngs, inv_b):
        pos_score = jnp.sum(c * pos, axis=-1)
        neg_score = jnp.einsum("bk,bnk->bn", c, ngs)
        g_pos = (jax.nn.sigmoid(pos_score) - 1.0) * inv_b
        g_neg = jax.nn.sigmoid(neg_score) * inv_b
        g_c = g_pos[:, None] * pos + jnp.einsum("bn,bnk->bk", g_neg, ngs)
        g_ctx = g_pos[:, None] * c
        g_ngs = g_neg[..., None] * c[:, None, :]
        return g_c, g_ctx, g_ngs

    def variant_full(carry, key):
        emb_in, emb_out = carry
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (B,), 0, n_pairs)
        batch = pairs[idx]
        center, ctx = batch[:, 0], batch[:, 1]
        neg = jax.random.randint(k2, (B, N), 0, V)
        g_c, g_ctx, g_ngs = sgns_math(emb_in[center], emb_out[ctx],
                                      emb_out[neg], 1.0 / B)
        emb_in = emb_in.at[center].add(-0.05 * g_c)
        emb_out = emb_out.at[ctx].add(-0.05 * g_ctx)
        emb_out = emb_out.at[neg.reshape(-1)].add(
            -0.05 * g_ngs.reshape(-1, K))
        return (emb_in, emb_out), 0.0

    def variant_gather_only(carry, key):
        emb_in, emb_out = carry
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (B,), 0, n_pairs)
        batch = pairs[idx]
        center, ctx = batch[:, 0], batch[:, 1]
        neg = jax.random.randint(k2, (B, N), 0, V)
        g_c, g_ctx, g_ngs = sgns_math(emb_in[center], emb_out[ctx],
                                      emb_out[neg], 1.0 / B)
        # consume gradients without row writes (keeps the gathers +
        # math live under DCE; one scalar accumulate instead)
        s = g_c.sum() + g_ctx.sum() + g_ngs.sum()
        return (emb_in + s * 0.0, emb_out), 0.0

    def variant_scatter_only(carry, key):
        emb_in, emb_out = carry
        k1, k2 = jax.random.split(key)
        center = jax.random.randint(k1, (B,), 0, V)
        ctx = jax.random.randint(k1, (B,), 0, V)
        neg = jax.random.randint(k2, (B, N), 0, V)
        row = jnp.full((B, K), 1e-6, jnp.float32)
        rows_n = jnp.full((B * N, K), 1e-6, jnp.float32)
        emb_in = emb_in.at[center].add(row)
        emb_out = emb_out.at[ctx].add(row)
        emb_out = emb_out.at[neg.reshape(-1)].add(rows_n)
        return (emb_in, emb_out), 0.0

    def variant_pure_gather(carry, key):
        emb_in, emb_out = carry
        k2 = jax.random.fold_in(key, 1)
        neg = jax.random.randint(k2, (B * rows_per_pair,), 0, V)
        s = emb_out[neg].sum()
        return (emb_in + s * 0.0, emb_out), 0.0

    def variant_sorted_gather(carry, key):
        emb_in, emb_out = carry
        k2 = jax.random.fold_in(key, 1)
        neg = jnp.sort(jax.random.randint(k2, (B * rows_per_pair,), 0, V))
        s = emb_out[neg].sum()
        return (emb_in + s * 0.0, emb_out), 0.0

    def run(variant):
        @jax.jit
        def loop(emb_in, emb_out, key):
            keys = jax.random.split(key, steps)
            (ei, eo), _ = jax.lax.scan(variant, (emb_in, emb_out), keys)
            return ei, eo

        loop(emb_in, emb_out, key)  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            ei, eo = loop(emb_in, emb_out, key)
            float(ei[0, 0])  # execution fence (axon tunnel)
            best = min(best, time.perf_counter() - t0)
        return best / steps

    results = {}
    for name, fn in [("full", variant_full),
                     ("gather_only", variant_gather_only),
                     ("scatter_only", variant_scatter_only),
                     ("pure_gather", variant_pure_gather),
                     ("sorted_gather", variant_sorted_gather)]:
        step_s = run(fn)
        results[name] = step_s
        rows = B * rows_per_pair
        print(f"{name:14s} {step_s*1e3:7.3f} ms/step  "
              f"{B/step_s/1e6:6.2f} M pairs/s  "
              f"{rows/step_s/1e6:7.1f} M rows/s", flush=True)

    # ceiling statement: the full step must gather AND scatter
    # rows_per_pair rows per pair; with measured per-row op costs
    # t_g (pure gather) and t_s (scatter-only), the op-bound floor is
    pg = results["pure_gather"] / (B * rows_per_pair)   # s per gathered row
    so = results["scatter_only"] / (B * rows_per_pair)  # s per scattered row
    floor_step = (pg + so) * B * rows_per_pair
    print(f"\nop-bound floor (gather+scatter at measured rates): "
          f"{floor_step*1e3:.3f} ms/step = "
          f"{B/floor_step/1e6:.2f} M pairs/s")
    print(f"full step is {results['full']/floor_step:.2f}x the floor; "
          f"sorted gather is {results['pure_gather']/results['sorted_gather']:.2f}x "
          f"the unsorted gather")


if __name__ == "__main__":
    main()
