#!/usr/bin/env python
"""Quality-parity CLI: TPU ALS vs MLlib-faithful CPU reference on
identical data (VERDICT r1 #1; north-star's "at matching MAP@10" half).

    python quality.py --mode explicit --scale 2m --rank 64 --iters 10
    python quality.py --mode implicit --scale 2m --rank 64 --alpha 40

Prints one JSON line per run. `--cpu` forces the TPU path onto the CPU
backend (virtual mesh) for hardware-free runs.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--telemetry-gate", action="store_true",
                   help="run the observability CI gate (no jax, no data): "
                        "fails if any in-package HTTP surface bypasses the "
                        "telemetry middleware, if an admitted "
                        "/queries.json or /events.json request produces a "
                        "flight-recorder timeline without its admission "
                        "and dispatch/commit spans, if the alert_* "
                        "families fail to render under a watchdog, or if "
                        "a 4-worker pool drill's supervisor /metrics "
                        "counter totals differ from the sum of the "
                        "per-worker registries (fleet-aggregation drill, "
                        "history sampling held under the 5% overhead "
                        "bar), if the always-on stack sampler is not "
                        "live with /debug/profile.json non-empty under "
                        "load at ≤5% p95 overhead (profiler drill), if "
                        "the jit-cache inventory at /debug/jit.json is "
                        "empty or inconsistent under load, misses the "
                        "retrace blame for a shape outside the warmed "
                        "bucket ladder, drops route attribution, or the "
                        "device clock exceeds the 5% overhead bar "
                        "(device drill), or if the fleet-merged "
                        "flamegraph's sample count / device-microsecond "
                        "total differs from the exact per-worker sum / "
                        "misattributes the seeded burn route")
    p.add_argument("--serving-gate", action="store_true",
                   help="run the serving CI gate (no jax, no data): fails "
                        "if any predict route bypasses admission control / "
                        "the serving plane")
    p.add_argument("--ingest-gate", action="store_true",
                   help="run the ingest CI gate (no jax, no data): fails "
                        "if any event-server write route bypasses the "
                        "group-commit write plane, or if an overloaded "
                        "server answers anything but 200/201/429")
    p.add_argument("--chaos-gate", action="store_true",
                   help="run the supervisor chaos CI gate (no jax, no "
                        "data): boots a supervised stub worker pool and "
                        "drills hard-kill, slow-worker (delay:500) and "
                        "erroring-worker recovery plus crash-loop circuit "
                        "breaking; fails unless capacity self-heals with "
                        "bounded restarts")
    p.add_argument("--hotpath-gate", action="store_true",
                   help="run the HTTP hot-path CI gate (no jax, no data): "
                        "fails if a hot-route handler (or anything it "
                        "calls in-module) uses bare json.dumps/json.loads "
                        "instead of utils.fastjson, or if a committed "
                        "ingest write fails to invalidate the per-user "
                        "serving result cache before the ack "
                        "(read-your-writes drill)")
    p.add_argument("--experiment-gate", action="store_true",
                   help="run the experimentation-plane CI gate (no jax, no "
                        "data): fails unless the sticky user→variant "
                        "mapping is identical across interpreters with "
                        "different PYTHONHASHSEEDs, the result cache "
                        "isolates variants, the Thompson bandit fed "
                        "$reward events through the real ingest funnel "
                        "converges ≥80% of traffic onto the better arm, "
                        "and the experiment_* telemetry renders")
    p.add_argument("--analysis-gate", action="store_true",
                   help="run the concurrency-analysis CI gate, two "
                        "halves: (1) a lock-sanitizer drill — "
                        "cross-plane concurrent workload under "
                        "instrumented locks (PIO_LOCKSAN machinery) "
                        "asserting no dynamic lock-order cycle and that "
                        "every observed edge matches the static lock "
                        "graph or a reviewed conf/lockorder-baseline.json "
                        "entry; (2) the pio-lint engine's full rule set "
                        "(no imports of the scanned code) — "
                        "interprocedural event-loop blocking-call rule, "
                        "whole-program lock-order deadlock detection, "
                        "race detector, jit shape discipline, coverage "
                        "rules, and the migrated serving/ingest/hotpath "
                        "static gates — failing on any finding not "
                        "inline-suppressed or grandfathered in "
                        "conf/analysis-baseline.json, with the "
                        "pio-lint --json artifact written to "
                        "$PIO_LINT_ARTIFACT for CI diffing")
    p.add_argument("--online-gate", action="store_true",
                   help="run the online-learning CI gate (jax on the local "
                        "backend, in-memory data): trains a small engine, "
                        "then drills freshness (burst of rating events for "
                        "existing and never-seen users must reach the "
                        "served model with p95 event→servable ≤ 5 s), "
                        "crash recovery (a fault between fold-in and "
                        "watermark advance must replay to bit-identical "
                        "factors with zero events lost), full-retrain "
                        "parity (folded rows bitwise-match their own "
                        "half-epoch; plane-wide drift bounded), the "
                        "session model family (a sessionrec engine's "
                        "fresh view events servable within the same 5 s "
                        "bar; crash replay rebuilds bit-identical "
                        "session windows/embeddings/scores), and the "
                        "online_* telemetry render")
    p.add_argument("--mode", choices=["explicit", "implicit"],
                   default="explicit")
    p.add_argument("--scale", choices=["100k", "2m", "20m"], default="100k")
    p.add_argument("--rank", type=int, default=10)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--reg", type=float, default=0.1)
    p.add_argument("--alpha", type=float, default=40.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ref-iters", type=int, default=None,
                   help="cap the CPU reference's iterations (it is slow at "
                        "20m scale); metrics stay comparable once converged")
    p.add_argument("--map-max-users", type=int, default=20_000)
    p.add_argument("--cpu", action="store_true",
                   help="run the TPU path on the CPU backend")
    args = p.parse_args()

    if args.telemetry_gate:
        from predictionio_tpu.telemetry.gate import run_gate

        return run_gate()

    if args.serving_gate:
        from predictionio_tpu.serving.gate import run_gate

        return run_gate()

    if args.ingest_gate:
        from predictionio_tpu.ingest.gate import run_gate

        return run_gate()

    if args.chaos_gate:
        from predictionio_tpu.runtime.gate import run_gate

        return run_gate()

    if args.hotpath_gate:
        from predictionio_tpu.utils.hotpath_gate import run_gate

        return run_gate()

    if args.experiment_gate:
        from predictionio_tpu.experiment.gate import run_gate

        return run_gate()

    if args.analysis_gate:
        from predictionio_tpu.analysis.gate import run_gate

        return run_gate()

    if args.online_gate:
        from predictionio_tpu.online.gate import run_gate

        return run_gate()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from predictionio_tpu.quality.parity import run_parity

    out = run_parity(mode=args.mode, scale=args.scale, rank=args.rank,
                     iterations=args.iters, reg=args.reg, alpha=args.alpha,
                     seed=args.seed, ref_iterations=args.ref_iters,
                     map_max_users=args.map_max_users)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main() or 0)
