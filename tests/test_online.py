"""Online-learning plane (round 11): tailer extraction, fold-in math,
hot delta-swap, and the event→servable loop end to end.

Covers the receipts `quality.py --online-gate` drills operationally:

- StoreTailer extraction — RewardTailer is a thin subclass that only
  supplies the $reward filter and posterior update; the watermark +
  overlap + dedup loop is inherited, with streaming (at-most-once per
  event) and batch (at-least-once, crash-replayed) delivery modes.
- Fold-in math — a single-row fold is bitwise one ALS half-epoch
  restricted to that row; cold-start ids append rows without disturbing
  existing codes; replaying a fold against fixed opposing factors is
  bit-identical (what makes at-least-once delivery safe).
- Delta-swap — per-user cache invalidation: a fold drops exactly the
  touched users' result-cache entries (cross-user survival), while a
  full /reload still drops the whole variant; a swap computed against a
  replaced state is refused (StaleState) instead of clobbering it.
- End to end — a never-seen user becomes servable after one poll; a
  crash between fold-in and watermark advance replays to bit-identical
  factors with zero events lost; the plane-wide parity check bounds
  drift against a fresh half-epoch.
"""

import contextlib
import threading
from datetime import datetime, timedelta, timezone
from types import SimpleNamespace

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.events import Event
from predictionio_tpu.experiment.rewards import RewardTailer
from predictionio_tpu.ingest.tailer import OVERLAP, StoreTailer
from predictionio_tpu.models.als_model import ALSModel
from predictionio_tpu.online import (
    DeltaSwapper,
    OnlineConfig,
    SeenOverlay,
    StaleState,
    fold_model,
    solve_rows,
)
from predictionio_tpu.online.foldin import extend_bimap
from predictionio_tpu.ops.als import ALSConfig
from predictionio_tpu.serving.plane import ServingConfig, ServingPlane
from predictionio_tpu.serving.result_cache import MISS, ResultCache
from predictionio_tpu.utils.faults import FaultInjected
from predictionio_tpu.workflow.create_server import (
    PredictionServer,
    ServerConfig,
)
from tests.test_experiment import train_variant
from tests.test_recommendation_template import ingest_ratings

T0 = datetime(2026, 3, 1, tzinfo=timezone.utc)


def _event(user, item, t, event="rate", rating=5.0):
    return Event(event=event, entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties=DataMap({"rating": rating}), event_time=t)


class _Recorder(StoreTailer):
    """Streaming-mode consumer that records what it was handed."""

    def __init__(self, storage, **kw):
        super().__init__(storage, **kw)
        self.applied = []

    def _apply(self, e) -> bool:
        self.applied.append(e.target_entity_id)
        return True


class TestStoreTailer:
    def test_reward_tailer_is_a_thin_subclass(self, memory_storage):
        assert issubclass(RewardTailer, StoreTailer)
        # the tail machinery is inherited, not re-implemented: the
        # subclass only supplies the filter and the apply hook
        for inherited in ("poll_once", "_collect", "_process", "_mark",
                          "start", "stop", "_run"):
            assert getattr(RewardTailer, inherited) is \
                getattr(StoreTailer, inherited)

        class _Bandit:
            def __init__(self):
                self.rewards = []

            def reward(self, variant, r):
                self.rewards.append((variant, r))
                return True

            def posterior_mean(self, variant):
                return 0.5

        bandit = _Bandit()
        rt = RewardTailer(memory_storage, bandit)
        assert rt.event_names == ["$reward"]
        assert rt.name == "reward-tailer"
        le = memory_storage.l_events()
        le.insert(Event(event="$reward", entity_type="user", entity_id="u1",
                        properties=DataMap({"variant": "a", "reward": 1.0}),
                        event_time=T0), 1)
        le.insert(_event("u1", "i1", T0), 1)  # filtered by event_names
        assert rt.poll_once() == 1
        assert bandit.rewards == [("a", 1.0)]

    def test_streaming_delivery_in_time_order(self, memory_storage):
        le = memory_storage.l_events()
        # inserted out of event-time order; delivery must sort
        le.insert(_event("u1", "i2", T0 + timedelta(seconds=2)), 1)
        le.insert(_event("u1", "i0", T0), 1)
        le.insert(_event("u1", "i1", T0 + timedelta(seconds=1)), 1)
        t = _Recorder(memory_storage)
        assert t.poll_once() == 3
        assert t.applied == ["i0", "i1", "i2"]
        assert t.poll_once() == 0  # dedup: nothing re-applied

    def test_overlap_catches_late_arrivals_without_redelivery(
            self, memory_storage):
        le = memory_storage.l_events()
        le.insert(_event("u1", "i0", T0), 1)
        t = _Recorder(memory_storage)
        assert t.poll_once() == 1
        # a group-commit straggler lands with an event_time BEHIND the
        # watermark but inside the overlap window: it must be delivered
        # exactly once, and i0 must not come back with it
        late = T0 - OVERLAP + timedelta(seconds=0.5)
        le.insert(_event("u1", "late", late), 1)
        assert t.poll_once() == 1
        assert t.applied == ["i0", "late"]

    def test_event_name_filter_and_max_batch(self, memory_storage):
        le = memory_storage.l_events()
        for i in range(3):
            le.insert(_event("u1", f"i{i}", T0 + timedelta(seconds=i)), 1)
        le.insert(_event("u1", "bought", T0, event="buy"), 1)
        t = _Recorder(memory_storage, event_names=["rate"], max_batch=2)
        assert t.poll_once() == 2  # capped
        assert t.poll_once() == 1  # the remainder, next pass
        assert t.applied == ["i0", "i1", "i2"]  # "buy" never delivered

    def test_streaming_is_at_most_once_per_event(self, memory_storage):
        """The original RewardTailer contract: each event is marked
        consumed BEFORE _apply runs, so a consumer that throws does not
        get the same event twice (a bandit reward must not double)."""
        class _Flaky(_Recorder):
            def _apply(self, e):
                if e.target_entity_id == "i1":
                    raise RuntimeError("consumer died mid-batch")
                return super()._apply(e)

        le = memory_storage.l_events()
        for i in range(3):
            le.insert(_event("u1", f"i{i}", T0 + timedelta(seconds=i)), 1)
        t = _Flaky(memory_storage)
        with pytest.raises(RuntimeError, match="mid-batch"):
            t.poll_once()
        # i0 applied, i1 marked-but-lost (at most once), i2 still fresh
        assert t.poll_once() == 1
        assert t.applied == ["i0", "i2"]

    def test_batch_mode_replays_the_whole_batch_after_a_crash(
            self, memory_storage):
        """The online plane's mode: nothing is marked until _process
        returns, so a crash between fold and watermark advance replays
        the complete batch (at-least-once; fold-in idempotence makes
        the replay free)."""
        class _Batcher(StoreTailer):
            def __init__(self, storage, **kw):
                super().__init__(storage, **kw)
                self.batches = []
                self.crash_next = False

            def _process(self, fresh):
                if fresh and self.crash_next:
                    self.crash_next = False
                    raise RuntimeError("died before the watermark")
                self.batches.append([e.target_entity_id for e in fresh])
                for e in fresh:
                    self._mark(e)
                return len(fresh)

        le = memory_storage.l_events()
        for i in range(3):
            le.insert(_event("u1", f"i{i}", T0 + timedelta(seconds=i)), 1)
        t = _Batcher(memory_storage)
        t.crash_next = True
        with pytest.raises(RuntimeError, match="watermark"):
            t.poll_once()
        assert t.batches == []  # nothing acked before the crash
        assert t.poll_once() == 3  # the SAME batch, replayed whole
        assert t.batches == [["i0", "i1", "i2"]]
        assert t.poll_once() == 0


class TestFoldInMath:
    # rank-4 explicit config; "chol" pinned so auto-resolution can never
    # change the parity reference out from under the bitwise asserts
    CFG = ALSConfig(rank=4, reg=0.1, solver="chol")

    @staticmethod
    def _entries(rng, n_rows=8, n_opposing=8, nnz=4):
        # every row gets the SAME nnz so single-row and batched solves
        # land in identically-shaped buckets: the batched CPU
        # Cholesky/triangular-solve picks kernels by batch shape, so
        # bitwise equality only holds at matched shapes (bucket_ragged
        # pads rows to a multiple of 8 — 8 rows with one cap match a
        # 1-row fold padded to the same [8, cap] bucket)
        out = []
        for _ in range(n_rows):
            cols = np.sort(rng.choice(n_opposing, size=nnz,
                                      replace=False)).astype(np.int32)
            vals = (1.0 + 4.0 * rng.random(nnz)).astype(np.float32)
            out.append((cols, vals))
        return out

    def test_single_row_fold_bitwise_matches_the_batched_half_epoch(self):
        rng = np.random.default_rng(7)
        opposing = rng.standard_normal((8, 4)).astype(np.float32)
        entries = self._entries(rng)
        full = solve_rows(opposing, entries, self.CFG)
        assert full.shape == (8, 4)
        for u in range(8):
            single = solve_rows(opposing, [entries[u]], self.CFG)
            assert np.array_equal(single[0], full[u]), (
                f"row {u}: a lone fold diverged from the same row solved "
                f"inside the full half-epoch")

    def test_fold_solves_the_weighted_normal_equations(self):
        rng = np.random.default_rng(11)
        opposing = rng.standard_normal((8, 4)).astype(np.float32)
        entries = self._entries(rng)
        solved = solve_rows(opposing, entries, self.CFG)
        for (cols, vals), x in zip(entries, solved):
            yc = opposing[cols].astype(np.float64)
            # ALS-WR: (YᵀY + λ·n·I) x = Yᵀ r with n = this row's nnz
            a = yc.T @ yc + self.CFG.reg * len(cols) * np.eye(4)
            ref = np.linalg.solve(a, yc.T @ vals.astype(np.float64))
            np.testing.assert_allclose(x, ref, rtol=1e-3, atol=1e-4)

    def test_empty_history_rows_solve_to_zeros(self):
        rng = np.random.default_rng(3)
        opposing = rng.standard_normal((8, 4)).astype(np.float32)
        empty = (np.zeros(0, np.int32), np.zeros(0, np.float32))
        solved = solve_rows(opposing, [*self._entries(rng, n_rows=2),
                                       empty], self.CFG)
        assert np.array_equal(solved[2], np.zeros(4, np.float32))
        assert solved[:2].any(axis=1).all()

    @staticmethod
    def _model(rng):
        return ALSModel(
            user_factors=rng.standard_normal((5, 4)).astype(np.float32),
            item_factors=rng.standard_normal((6, 4)).astype(np.float32),
            user_ids=BiMap.string_int([f"u{i}" for i in range(5)]),
            item_ids=BiMap.string_int([f"i{i}" for i in range(6)]),
            seen={0: np.asarray([1, 2], np.int32)})

    def test_cold_start_appends_rows_without_disturbing_existing(self):
        rng = np.random.default_rng(5)
        model = self._model(rng)
        folded, stats = fold_model(
            model, self.CFG, {"newu": [("i1", 5.0), ("newi", 3.0)]})
        assert (stats.new_users, stats.new_items) == (1, 1)
        assert (stats.folded_users, stats.folded_items) == (1, 0)
        # never-seen ids take the next dense codes; old codes keep rows
        assert folded.user_ids["newu"] == 5
        assert folded.item_ids["newi"] == 6
        uf = np.asarray(folded.user_factors)
        itf = np.asarray(folded.item_factors)
        assert np.array_equal(uf[:5], np.asarray(model.user_factors))
        assert np.array_equal(itf[:6], np.asarray(model.item_factors))
        assert uf[5].any()  # the cold user's row actually solved
        # the cold ITEM was only referenced, never folded: zero row
        assert np.array_equal(itf[6], np.zeros(4, np.float32))
        # seen overlay: the folded user excludes their rated items; the
        # untouched user's base seen set survives
        assert set(folded.seen.get(5)) == {1, 6}
        assert np.array_equal(folded.seen.get(0),
                              np.asarray([1, 2], np.int32))
        # and the input model was never mutated (serving reads it until
        # the swap lands)
        assert model.user_ids.get("newu") is None
        assert np.asarray(model.user_factors).shape == (5, 4)

    def test_fold_is_bitwise_idempotent_against_fixed_opposing(self):
        # the crash-replay guarantee: same history + same opposing
        # factors → byte-identical factors (item folds off; with them on
        # a replay is one extra alternation half-step — convergent, not
        # byte-stable, see docs/online.md)
        rng = np.random.default_rng(13)
        model = self._model(rng)
        hist = {"u1": [("i0", 4.0), ("i3", 2.0)], "u4": [("i5", 5.0)]}
        once, _ = fold_model(model, self.CFG, hist)
        twice, _ = fold_model(once, self.CFG, hist)
        assert np.array_equal(np.asarray(once.user_factors),
                              np.asarray(twice.user_factors))
        assert np.array_equal(np.asarray(once.item_factors),
                              np.asarray(twice.item_factors))

    def test_seen_overlay_flattens_and_layers(self):
        base = {0: np.asarray([1], np.int32)}
        one = SeenOverlay(base, {1: np.asarray([2], np.int32)})
        two = SeenOverlay(one, {0: np.asarray([9], np.int32)})
        assert two._base is base  # overlay-on-overlay flattens
        assert np.array_equal(two.get(0), [9])  # newest delta wins
        assert np.array_equal(two.get(1), [2])
        assert two.get(7) is None
        assert bool(SeenOverlay(None, {}))  # truthy even when empty

    def test_extend_bimap_appends_and_preserves(self):
        bm = BiMap.string_int(["a", "b"])
        grown, added = extend_bimap(bm, ["b", "c", "d"])
        assert added == ["c", "d"]
        assert (grown["a"], grown["b"], grown["c"], grown["d"]) \
            == (0, 1, 2, 3)
        same, none_added = extend_bimap(grown, ["a", "d"])
        assert same is grown and none_added == []


class TestFoldModelProtocol:
    """PR receipt: generalizing the plane beyond ALS (foldin.FoldModel)
    left ALS fold-in byte-for-byte intact — ALSFold is a thin adapter
    that strips event times off the protocol's history triples and
    calls the original fold_model, mirroring the StoreTailer extraction
    receipt above."""

    def test_alsfold_is_a_thin_adapter(self):
        from predictionio_tpu.online import ALSFold, FoldModel

        assert issubclass(ALSFold, FoldModel)
        assert ALSFold.family == "als"
        # the adapter adds no solve logic of its own: fold_model is
        # still the one entry point (parity/gate callers keep using it)
        import inspect
        src = inspect.getsource(ALSFold.fold)
        assert "fold_model" in src

    def test_alsfold_fold_is_bit_identical_to_fold_model(self):
        # the same histories, once as the protocol's timed triples and
        # once as fold_model's untimed pairs: byte-equal factors, same
        # appended codes, same stats — the extraction changed nothing
        from predictionio_tpu.online import ALSFold

        rng = np.random.default_rng(17)
        model = TestFoldInMath._model(rng)
        cfg = TestFoldInMath.CFG
        user_pairs = {"u1": [("i0", 4.0), ("i3", 2.0)],
                      "newu": [("i5", 5.0), ("newi", 3.0)]}
        item_pairs = {"i0": [("u1", 4.0), ("u2", 1.0)]}

        def timed(hists):
            return {k: [(o, v, T0 + timedelta(seconds=j))
                        for j, (o, v) in enumerate(pairs)]
                    for k, pairs in hists.items()}

        via_handle, st1 = ALSFold(cfg).fold(
            model, timed(user_pairs), timed(item_pairs))
        direct, st2 = fold_model(model, cfg, user_pairs, item_pairs)
        assert np.array_equal(np.asarray(via_handle.user_factors),
                              np.asarray(direct.user_factors))
        assert np.array_equal(np.asarray(via_handle.item_factors),
                              np.asarray(direct.item_factors))
        assert via_handle.user_ids.to_dict() == direct.user_ids.to_dict()
        assert via_handle.item_ids.to_dict() == direct.item_ids.to_dict()
        assert (st1.folded_users, st1.folded_items, st1.new_users,
                st1.new_items) == (st2.folded_users, st2.folded_items,
                                   st2.new_users, st2.new_items)

    def test_plane_context_keeps_the_als_compat_view(self, memory_storage):
        # parity_check and the gate drills read ctx.als as (idx, config)
        # pairs; the property must recover them from the fold handles
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=2)
        with online_server(memory_storage, interval_s=0.05) as server:
            ctx = server.online._contexts[0]
            assert ctx.folds, "variant resolved no fold handles"
            assert [f for _, f in ctx.als] and all(
                isinstance(cfg, ALSConfig) for _, cfg in ctx.als)
            assert [i for i, _ in ctx.als] == \
                [i for i, h in ctx.folds if h.family == "als"]


class TestDeltaSwapper:
    class _Bus:
        def __init__(self):
            self.published = []

        def publish(self, entity_ids, variant=None):
            self.published.append((list(entity_ids), variant))

    def test_swap_replaces_state_and_publishes_touched_users(self):
        state = SimpleNamespace(models=["old"], instance="inst-1")
        states = {"v": state}
        bus = self._Bus()
        swapper = DeltaSwapper(states, threading.Lock(), bus=bus)
        new_state = swapper.swap("v", state, ["new"],
                                 touched_users={"u2", "u1"})
        assert states["v"] is new_state and new_state is not state
        assert new_state.models == ["new"]
        assert new_state.instance == "inst-1"  # everything else copied
        assert state.models == ["old"]  # old immutable state untouched
        assert bus.published == [(["u1", "u2"], "v")]  # sorted, scoped

    def test_stale_swap_is_refused(self):
        state = SimpleNamespace(models=["old"])
        states = {"v": state}
        bus = self._Bus()
        swapper = DeltaSwapper(states, threading.Lock(), bus=bus)
        reloaded = SimpleNamespace(models=["reloaded"])
        states["v"] = reloaded  # a full /reload landed mid-fold
        with pytest.raises(StaleState):
            swapper.swap("v", state, ["folded"], touched_users=["u1"])
        assert states["v"] is reloaded  # the reload was NOT clobbered
        assert bus.published == []  # no invalidation for a refused swap

    def test_per_user_invalidation_spares_other_users_and_variants(self):
        """Satellite receipt: a delta-swap must drop exactly the touched
        users' cache entries — not the whole variant (that's /reload's
        job) and never another variant's."""
        from predictionio_tpu.ingest.invalidation import BUS

        planes = {
            v: ServingPlane(lambda qs: [{"v": q["user"]} for q in qs],
                            config=ServingConfig(batching=False),
                            result_cache=ResultCache(max_entries=64,
                                                     ttl_s=600.0),
                            variant=v)
            for v in ("a", "b")
        }
        try:
            q1, q2 = {"user": "u1", "num": 3}, {"user": "u2", "num": 3}
            for plane in planes.values():
                plane.handle_query(q1, {})
                plane.handle_query(q2, {})
            for v, plane in planes.items():
                assert plane.result_cache.get(q1, v) is not MISS
                assert plane.result_cache.get(q2, v) is not MISS

            state = SimpleNamespace(models=["m"])
            swapper = DeltaSwapper({"a": state}, threading.Lock(), bus=BUS)
            swapper.swap("a", state, ["m2"], touched_users=["u1"])
            cache_a, cache_b = (planes[v].result_cache for v in ("a", "b"))
            assert cache_a.get(q1, "a") is MISS  # folded user dropped
            assert cache_a.get(q2, "a") is not MISS  # cross-user survival
            assert cache_b.get(q1, "b") is not MISS  # other variant intact
            assert cache_b.get(q2, "b") is not MISS
            # the full-reload path still drops the whole variant
            cache_a.invalidate_variant("a")
            assert cache_a.get(q2, "a") is MISS
        finally:
            for plane in planes.values():
                BUS.unsubscribe(plane._invalidate)


@contextlib.contextmanager
def online_server(storage, **online_kw):
    config = ServerConfig(ip="127.0.0.1", port=0, engine_id="rec-test",
                          engine_variant="rec-test")
    server = PredictionServer(config, storage, plugins=None,
                              online=OnlineConfig(**online_kw))
    try:
        # polls are driven by hand in every test: deterministic batches
        server.online.stop()
        yield server
    finally:
        server.shutdown()


def _rate(storage, user, item, rating=5.0):
    app_id = storage.meta_apps().get_by_name("RecApp").id
    storage.l_events().insert(Event(
        event="rate", entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties=DataMap({"rating": rating})), app_id)


class TestOnlinePlaneEndToEnd:
    def test_never_seen_user_is_servable_after_one_poll(
            self, memory_storage):
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=15)
        with online_server(memory_storage, interval_s=0.05) as server:
            assert server.online is not None
            # u99 lands in the odd-item block; i7 is the odd item they
            # have not rated yet
            for i in (1, 3, 5):
                _rate(memory_storage, "u99", f"i{i}")
            assert server.online.poll_once() == 3
            result, degraded = server.serving.handle_query(
                {"user": "u99", "num": 3}, {})
            assert not degraded
            items = [s["item"] for s in result["itemScores"]]
            assert items, "folded user got no recommendations"
            assert "i7" in items, f"expected the unrated odd item, got {items}"
            assert not {"i1", "i3", "i5"} & set(items), \
                "seen-exclusion lost the folded ratings"
            assert server.online.poll_once() == 0  # watermark advanced
            snap = server.online.snapshot()
            assert snap["variants"] == ["rec-test"]
            assert snap["eventsFolded"] == 3
            assert snap["watermark"] is not None

    def test_crash_between_fold_and_watermark_replays_idempotently(
            self, memory_storage, monkeypatch):
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=15)
        # item folds OFF: the opposing factors are fixed across the
        # replay, so recovered factors must be bit-identical (see
        # TestFoldInMath.test_fold_is_bitwise_idempotent_...)
        with online_server(memory_storage, interval_s=0.05,
                           fold_items=False) as server:
            for i in (1, 3, 5):
                _rate(memory_storage, "crash1", f"i{i}")
            monkeypatch.setenv("PIO_FAULTS", "online.pre_watermark=error")
            with pytest.raises(FaultInjected):
                server.online.poll_once()
            # the fold and swap landed BEFORE the crash window...
            model = server._states["rec-test"].models[0]
            row0 = model.user_ids.get("crash1")
            assert row0 is not None, "fold did not land before the crash"
            pre = np.array(np.asarray(model.user_factors)[row0], copy=True)
            # ...and the watermark did not: recovery replays the batch
            monkeypatch.setenv("PIO_FAULTS", "")
            assert server.online.poll_once() == 3
            model2 = server._states["rec-test"].models[0]
            row = model2.user_ids.get("crash1")
            assert np.array_equal(np.asarray(model2.user_factors)[row], pre)
            assert server.online.poll_once() == 0  # settled
            result, _ = server.serving.handle_query(
                {"user": "crash1", "num": 3}, {})
            assert result["itemScores"], "event lost across the crash"

    def test_delta_swap_invalidates_only_the_folded_user(
            self, memory_storage, monkeypatch):
        """The satellite receipt, through the REAL wiring: fold →
        DeltaSwapper → InvalidationBus → ServingPlane subscription →
        per-user drop; /reload keeps its full-variant drop."""
        monkeypatch.setenv("PIO_HTTP_RESULT_CACHE", "1")
        # a fold pass (first one jit-compiles) can outlive the default
        # 5 s TTL; pin it high so expiry can't fake the invalidation
        monkeypatch.setenv("PIO_HTTP_RESULT_CACHE_TTL_S", "600")
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=15)
        with online_server(memory_storage, interval_s=0.05) as server:
            cache = server.serving.result_cache
            assert cache is not None
            q0, q2 = {"user": "u0", "num": 3}, {"user": "u2", "num": 3}
            server.serving.handle_query(q0, {})
            server.serving.handle_query(q2, {})
            assert cache.get(q0, "rec-test") is not MISS
            assert cache.get(q2, "rec-test") is not MISS
            _rate(memory_storage, "u0", "i6")
            assert server.online.poll_once() == 1
            assert cache.get(q0, "rec-test") is MISS, \
                "folded user's cached answer survived the swap"
            assert cache.get(q2, "rec-test") is not MISS, \
                "delta-swap dropped an untouched user's entry"
            # full /reload: EVERY answer changed, whole variant drops
            server.serving.handle_query(q0, {})
            server.reload()
            assert cache.get(q0, "rec-test") is MISS
            assert cache.get(q2, "rec-test") is MISS

    def test_reload_rebases_the_plane_and_folding_continues(
            self, memory_storage):
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=15)
        with online_server(memory_storage, interval_s=0.05) as server:
            _rate(memory_storage, "u50", "i2")
            assert server.online.poll_once() == 1
            server.reload()  # rebases tailers onto the new instance
            # the replaced state no longer holds the fold, but the plane
            # must keep folding against the NEW state
            _rate(memory_storage, "u51", "i3")
            assert server.online.poll_once() >= 1
            result, _ = server.serving.handle_query(
                {"user": "u51", "num": 3}, {})
            assert result["itemScores"]

    def test_parity_check_bounds_drift(self, memory_storage):
        ingest_ratings(memory_storage)
        train_variant(memory_storage, iters=15)
        with online_server(memory_storage, interval_s=0.05,
                           fold_items=False) as server:
            _rate(memory_storage, "u1", "i7", rating=4.0)
            server.online.poll_once()
            stats = server.online.parity_check()
            assert "rec-test" in stats
            s = stats["rec-test"]
            assert s["rows"] > 0
            assert s["rel_max"] <= 0.05, (
                f"served factors drift {s['rel_max']:.3f} (rel max) from "
                f"a fresh half-epoch")


class TestOnlineConfig:
    def test_env_gating_and_knobs(self, monkeypatch):
        monkeypatch.delenv("PIO_ONLINE", raising=False)
        assert OnlineConfig.from_env() is None
        monkeypatch.setenv("PIO_ONLINE", "1")
        assert OnlineConfig.from_env() == OnlineConfig()
        monkeypatch.setenv("PIO_ONLINE_INTERVAL_S", "0.1")
        monkeypatch.setenv("PIO_ONLINE_MAX_BATCH", "256")
        monkeypatch.setenv("PIO_ONLINE_FOLD_ITEMS", "0")
        monkeypatch.setenv("PIO_ONLINE_PARITY_EVERY_S", "30")
        monkeypatch.setenv("PIO_ONLINE_APP_ID", "7")
        cfg = OnlineConfig.from_env()
        assert cfg == OnlineConfig(interval_s=0.1, max_batch=256,
                                   fold_items=False, parity_every_s=30.0,
                                   app_id=7)

    def test_telemetry_families_render(self):
        from predictionio_tpu.telemetry.registry import REGISTRY

        text = REGISTRY.render()
        for family in ("online_events_folded_total",
                       "online_rows_folded_total",
                       "online_cold_start_rows_total",
                       "online_swaps_total",
                       "online_event_to_servable_seconds",
                       "online_lag_seconds",
                       "online_parity_drift"):
            assert f"# TYPE {family} " in text
