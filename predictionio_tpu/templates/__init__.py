"""Built-in engine templates.

The reference ships these as separate repos instantiated into a user dir
(SURVEY.md §2.4); here they are importable packages whose engine.json files
keep the reference shape, so `pio-tpu build/train/deploy` runs them
unchanged at the engine.json level (BASELINE.json north-star requirement).

Templates: recommendation, similarproduct, classification, ecommerce,
textclassification.
"""
