"""Multi-host control plane e2e: 2 real processes × 4 CPU devices each
federate into one 8-device world via `jax.distributed` and assemble a
correct global sharded array — the TPU-native replacement for the
reference's Spark driver↔executor bootstrap (SURVEY.md §2.7). Runs the
same `PIO_COORDINATOR_ADDRESS`/`PIO_NUM_PROCESSES`/`PIO_PROCESS_ID`
contract `pio train` uses on a real pod."""

import json
import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.environ["PIO_TEST_REPO"])
    import numpy as np
    from predictionio_tpu.parallel import distributed

    # PIO_JAX_PLATFORM=cpu in the env exercises the platform override
    # inside initialize_from_env (the production path on CPU-only hosts)
    assert distributed.initialize_from_env()
    import jax
    import jax.numpy as jnp

    mesh = distributed.global_mesh()
    lo, hi = distributed.process_row_range(16)
    local = (np.arange(lo, hi, dtype=np.float32).reshape(-1, 1)
             * np.ones((1, 4), np.float32))
    garr = distributed.make_global_array(mesh, local)
    total = float(jax.jit(jnp.sum)(garr))
    out = {
        "pid": jax.process_index(),
        "devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "sum": total,
        "rows": [int(lo), int(hi)],
        "mesh": dict(mesh.shape),
    }
    with open(os.environ["PIO_TEST_OUT"], "w") as f:
        json.dump(out, f)
""")


@pytest.mark.e2e
def test_two_process_global_mesh(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PIO_CONF_DIR", None)
        env.update(
            PIO_JAX_PLATFORM="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES="2",
            PIO_PROCESS_ID=str(pid),
            PIO_TEST_REPO=str(REPO),
            PIO_TEST_OUT=str(tmp_path / f"out{pid}.json"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o

    results = [json.loads((tmp_path / f"out{i}.json").read_text())
               for i in range(2)]
    expected_sum = float(sum(range(16)) * 4)
    for pid, r in enumerate(results):
        assert r["pid"] == pid
        assert r["devices"] == 8 and r["local_devices"] == 4
        assert r["sum"] == expected_sum  # every rank sees the global sum
        assert r["mesh"] == {"data": 8, "model": 1}
    # the two ranks fed disjoint halves of the global rows
    assert results[0]["rows"] == [0, 8] and results[1]["rows"] == [8, 16]


TRAIN_ENV_KEYS = dict(
    PIO_STORAGE_REPOSITORIES_METADATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE="SQL",
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE="SQL",
    PIO_STORAGE_SOURCES_SQL_TYPE="sqlite",
)


@pytest.mark.e2e
def test_two_process_pio_train_cli(tmp_path):
    """The real pod contract end-to-end: TWO `bin/pio train` processes
    federate via PIO_COORDINATOR_* into one 8-device world over a shared
    file store; every rank trains (collectives need all of them), rank 0
    alone persists the model + COMPLETED instance, and the persisted
    model loads and answers a query."""
    import sqlite3

    db = tmp_path / "pio.db"
    # seed app + ratings through the storage layer
    import sys as _sys

    _sys.path.insert(0, str(REPO))
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.events import Event
    from predictionio_tpu.storage.base import App
    from predictionio_tpu.storage.sqlite import SQLiteBackend

    backend = SQLiteBackend(str(db))
    app_id = backend.apps().insert(App(id=0, name="MHApp"))
    import numpy as np

    rng = np.random.default_rng(3)
    rows = [Event(event="rate", entity_type="user", entity_id=str(u),
                  target_entity_type="item", target_entity_id=str(i),
                  properties=DataMap({"rating": float(r)}))
            for u, i, r in zip(rng.integers(0, 48, 3000),
                               rng.integers(0, 32, 3000),
                               rng.integers(1, 6, 3000))]
    backend.events().insert_batch(rows, app_id=app_id)
    backend.close()

    engine_json = tmp_path / "engine.json"
    engine_json.write_text(json.dumps({
        "id": "mh", "engineFactory":
            "predictionio_tpu.templates.recommendation.RecommendationEngine",
        "datasource": {"params": {"appName": "MHApp"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 8, "numIterations": 3, "lambda": 0.05, "seed": 1}}],
    }))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PIO_CONF_DIR", None)
        env.update(
            TRAIN_ENV_KEYS,
            PIO_STORAGE_SOURCES_SQL_PATH=str(db),
            PIO_FS_BASEDIR=str(tmp_path),
            PIO_JAX_PLATFORM="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PIO_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PIO_NUM_PROCESSES="2",
            PIO_PROCESS_ID=str(pid),
            PYTHONPATH=f"{REPO}{os.pathsep}" + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [str(REPO / "bin" / "pio"), "train",
             "--engine-json", str(engine_json)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    assert "Training completed" in outs[0]  # rank 0 persists + reports

    conn = sqlite3.connect(db)
    completed = conn.execute(
        "SELECT id FROM engine_instances WHERE status='COMPLETED'"
    ).fetchall()
    assert len(completed) == 1  # rank 0 only — no duplicate instances
    models = conn.execute("SELECT count(*) FROM models").fetchone()[0]
    assert models == 1
    conn.close()

    # the persisted model must load and answer a query (single process)
    from predictionio_tpu.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )
    from predictionio_tpu.workflow.workflow_utils import (
        EngineVariant, extract_engine_params, get_engine,
    )

    src = SourceConfig(name="SQL", type="sqlite", path=str(db))
    storage = Storage(StorageConfig(metadata=src, modeldata=src,
                                    eventdata=src))
    try:
        variant = EngineVariant.from_dict(json.loads(engine_json.read_text()))
        engine = get_engine(variant.engine_factory)
        ep = extract_engine_params(engine, variant)
        blob = storage.model_data_models().get(completed[0][0]).models
        models_obj = engine.deserialize_models(blob, completed[0][0], ep)
        r = engine.predict(ep, models_obj, {"user": "1", "num": 3})
        # seen-item exclusion may leave fewer than `num` candidates; the
        # claim is that the persisted model answers, not the exact count
        assert 1 <= len(r["itemScores"]) <= 3
    finally:
        storage.close()
